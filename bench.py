"""Headline benchmark: the five BASELINE query shapes (rows/sec).

Runs every BASELINE.json config through the real PxL frontend
(``Engine.execute_query``) over synthetic replays pushed through the
table-store ingest path, cross-checks each result against a vectorized
numpy implementation (stand-in for CPU Carnot, whose repo publishes no
absolute numbers — SURVEY.md §6), and prints ONE JSON line:

  {"metric": "http_stats_rows_per_sec", "value": rows/s, "unit": "rows/s",
   "vs_baseline": x, "device": "tpu"|"cpu", "shapes": {per-shape results}}

Process model: the launcher runs EACH SHAPE in its own subprocess. This
is load-bearing, not cosmetic. The axon TPU tunnel has two regimes: it
JOURNALS device work lazily until the process's first device-to-host
readback, whose flush executes everything recorded (including the lazy
table-staging uploads), after which every dispatch runs synchronously
(~65ms round trip + real device time) and compiling NEW programs can
stall. So each shape gets a fresh process that (1) compiles everything
during warm-up with ``materialize=False`` (no readback), (2) flushes
once so the one-time table upload executes OUTSIDE the timer, then
(3) times the query in the synchronous regime — real execution, no
upload. The XLA compilation cache (persisted under the repo) makes the
per-process compiles cheap after the first round.

Environment knobs:
  PIXIE_TPU_BENCH_ROWS     http_events replay rows (default 16M TPU / 2M CPU)
  PIXIE_TPU_BENCH_WINDOW   window rows per device dispatch (default 2^21)
  PIXIE_TPU_BENCH_BUDGET   launcher wall-clock budget in seconds (default 540)
  PIXIE_TPU_BENCH_SHAPES   comma list of shapes to run (default all six)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
from pixie_tpu.utils.cache import jax_cache_dir  # noqa: E402

CACHE_DIR = os.environ.get("JAX_COMPILATION_CACHE_DIR", jax_cache_dir())

# Shape registry: name -> (shape fn attr, rows divisor vs headline n).
# Single source for the launcher's shape list, inner's dispatch, and the
# per-shape row scaling (join/regex shapes are heavier per row).
SHAPE_DEFS = {
    "http_stats": ("_shape_http_stats", 1),
    "service_stats": ("_shape_service_stats", 1),
    "net_flow_graph": ("_shape_net_flow_graph", 2),
    "sql_stats": ("_shape_sql_stats", 4),
    "perf_flamegraph": ("_shape_perf_flamegraph", 4),
    "device_join": ("_shape_device_join", 4),
    # Join-distribution shapes (ISSUE 9): skewed keys stress capacity
    # estimation (zipf fan-out), clustered+selective keys exercise
    # zone-map window skipping. Both group on columns from BOTH sides,
    # so eager aggregation cannot rewrite the join away — they measure
    # the REAL N:M join path the single device_join shape no longer
    # reaches (it routes to the fused N:1 lookup after the rewrite).
    "device_join_skew": ("_shape_device_join_skew", 4),
    "device_join_select": ("_shape_device_join_select", 4),
    # Repeat-serving shape (ISSUE 16): the same dashboard script fired
    # repeatedly over a growing replay — cold rescan vs watermark-
    # validated cache hit vs incremental materialized-view fold.
    "dashboard_repeat": ("_shape_dashboard_repeat", 2),
    # Storage-tier shape (ISSUE 20): selective + full scans over a
    # mostly-cold table — zone-map skipping before decode, decode-on-
    # stage overlap, tier on/off x skip on/off A/B.
    "cold_scan": ("_shape_cold_scan", 4),
}
ALL_SHAPES = tuple(SHAPE_DEFS)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _script(name: str) -> str:
    """PxL source of a shipped library script (the bench runs the same
    scripts the library ships — VERDICT r02 ask #8)."""
    from pixie_tpu.scripts import load_script

    return load_script(name).pxl


# ---------------------------------------------------------------------------
# Launcher: one subprocess per shape so a readback in shape k never slows
# shape k+1, and one bad shape never zeroes the run.
# ---------------------------------------------------------------------------


def _inner_env(platform: str, shape: str, rows: int | None) -> dict:
    from pixie_tpu.utils.cache import scrubbed_cpu_env

    env = scrubbed_cpu_env() if platform == "cpu" else dict(os.environ)
    if platform != "cpu":
        env.pop("JAX_PLATFORMS", None)
        env["JAX_COMPILATION_CACHE_DIR"] = CACHE_DIR
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env["PIXIE_TPU_BENCH_INNER"] = "1"
    env["PIXIE_TPU_BENCH_SHAPES"] = shape
    if rows is not None:
        env["PIXIE_TPU_BENCH_ROWS"] = str(rows)
    return env


def _run_shape_proc(platform: str, shape: str, rows: int | None,
                    timeout_s: float):
    """Run one shape in a subprocess; return its parsed result dict."""
    import subprocess

    log(f"[bench] {shape} ({platform}, timeout {timeout_s:.0f}s)")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_inner_env(platform, shape, rows),
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=None,  # stream live
            timeout=timeout_s,
            text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"[bench] {shape} ({platform}) timed out after {timeout_s:.0f}s")
        return None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                if parsed.get("shape") == shape:
                    return parsed
            except json.JSONDecodeError:
                continue
    log(f"[bench] {shape} ({platform}) rc={proc.returncode}, no JSON line")
    return None


def _tpu_alive(timeout_s: float = 90.0) -> bool:
    """Pre-flight: can a fresh process even initialize the TPU backend?
    The tunnel relay can enter a stuck-claim state where jax.devices()
    hangs forever — burning every shape's timeout on a dead backend
    would leave no budget for the CPU fallbacks."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform == 'tpu'"],
            env={**os.environ, "JAX_COMPILATION_CACHE_DIR": CACHE_DIR},
            cwd=REPO, timeout=timeout_s,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def launcher() -> int:
    budget = float(os.environ.get("PIXIE_TPU_BENCH_BUDGET", 540))
    t0 = time.monotonic()
    want = [
        s.strip()
        for s in os.environ.get(
            "PIXIE_TPU_BENCH_SHAPES", ",".join(ALL_SHAPES)
        ).split(",")
        if s.strip()
    ]
    rows_env = os.environ.get("PIXIE_TPU_BENCH_ROWS")
    head_shape = next((s for s in want if s in ALL_SHAPES), "http_stats")
    shapes: dict = {}
    device = None
    tpu_ok = _tpu_alive()
    if not tpu_ok:
        log("[bench] TPU backend unreachable (pre-flight); CPU-only run")

    def left():
        return budget - (time.monotonic() - t0)

    for shape in want:
        if shape not in ALL_SHAPES:
            log(f"[bench] unknown shape {shape!r}")
            continue
        if left() < 60:
            shapes[shape] = {"skipped": "deadline"}
            continue
        # The headline (first requested shape) gets the lion's share and
        # a retry (the tunnel can be transiently UNAVAILABLE).
        is_head = shape == head_shape
        cap = 240.0 if is_head else 150.0
        timeout = min(cap, left() - (30 if is_head else 10))
        rows = int(rows_env) if rows_env else None
        res = None
        if tpu_ok:
            res = _run_shape_proc("tpu", shape, rows, timeout)
            if res is None and is_head and left() > 120:
                log("[bench] headline retry")
                time.sleep(5)
                res = _run_shape_proc("tpu", shape, rows, min(cap, left() - 60))
        if res is None and left() > 60:
            # CPU fallback so every shape reports a number even with the
            # tunnel down; with no TPU attempts burning budget, the
            # fallback gets bigger replays (throughput amortizes).
            fb_rows = rows or (
                16 * 1024 * 1024 if not tpu_ok else 1024 * 1024
            )
            res = _run_shape_proc(
                "cpu", shape, fb_rows,
                max(60.0, min(200.0 if not tpu_ok else 150.0, left() - 5)),
            )
        if res is None:
            shapes[shape] = {"error": "subprocess failed or timed out"}
            continue
        shapes[shape] = res["result"]
        device = device or res.get("platform")

    head = shapes.get(head_shape) or {}
    metric = f"{head_shape}_rows_per_sec"
    if "rows_per_sec" not in head:
        log("[bench] headline shape failed")
        # Still print a parseable line so the round records the failure.
        print(json.dumps({
            "metric": metric, "value": 0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "device": device or "none", "shapes": shapes,
        }), flush=True)
        return 1
    print(json.dumps({
        "metric": metric,
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        # Shapes without a numpy-replay denominator (e.g. the repeat
        # shape, whose headline is a speedup ratio) report 0.0 here.
        "vs_baseline": head.get("vs_baseline", 0.0),
        # The denominator is an in-process numpy replay of the same
        # query, NOT CPU Carnot — the reference engine cannot be built
        # offline (BASELINE.md "CPU-Carnot measurement attempt").
        "baseline": "in-process numpy replay (see BASELINE.md)",
        "device": device or "unknown",
        "shapes": shapes,
    }), flush=True)
    return 0


# ---------------------------------------------------------------------------
# Inner benchmark: one shape — generate a replay, run the PxL script,
# cross-check against numpy.
# ---------------------------------------------------------------------------


def _codes(rng, n, vocab_len):
    return rng.integers(0, vocab_len, n).astype(np.int32)


def _push_encoded(eng, name, rel, col_fn, n, window, dicts):
    """Push pre-encoded windows through the ingest path (append_data).

    String columns arrive as dictionary ids sharing one StringDictionary —
    the state a live collector's staging produces (strings are encoded at
    the edge, SURVEY.md §7 stage 1); the first append makes the table
    adopt these dictionaries so later windows append with zero remapping.
    """
    from pixie_tpu.types.batch import HostBatch

    for off in range(0, n, window):
        m = min(window, n - off)
        hb = HostBatch(
            relation=rel, cols=col_fn(off, m), length=m, dicts=dicts
        )
        eng.append_data(name, hb)


#: Pipeline overlap report of the most recent ``_time_query`` (merged
#: into each shape's result dict via ``_with_pipeline``).
_LAST_PIPELINE: dict | None = None

#: Latency-quantile report (p50/p95/p99 from the tracer's histograms)
#: of the most recent ``_time_query``, merged the same way.
_LAST_LATENCY: dict | None = None


def _latency_report(eng) -> dict | None:
    """p50/p95/p99 pulled from the always-on trace histograms
    (services.observability quantiles over pixie_query_duration_seconds
    and pixie_window_stage_seconds). Each shape runs in its own
    subprocess, so the process-global registry holds only this shape's
    observations (warm-ups + timed run + A/B arms)."""
    reg = eng.tracer.registry
    out: dict = {}

    def pcts(name, **labels):
        q = reg.quantiles(name, (0.5, 0.95, 0.99), **labels)
        if not q:
            return None
        return {"p50": round(q[0.5], 6), "p95": round(q[0.95], 6),
                "p99": round(q[0.99], 6)}

    p = pcts("pixie_query_duration_seconds")
    if p:
        out["query_seconds"] = p
    for stage in ("compute", "stage", "stall"):
        p = pcts("pixie_window_stage_seconds", stage=stage)
        if p:
            out[f"window_{stage}_seconds"] = p
    return out or None


def _host_equal(a: dict, b: dict) -> bool:
    """Exact equality of two {name: HostBatch} query outputs."""
    if set(a) != set(b):
        return False
    for k in a:
        da, db = a[k].to_pydict(), b[k].to_pydict()
        if set(da) != set(db):
            return False
        for c in da:
            if not np.array_equal(da[c], db[c]):
                return False
    return True


def _flag_override(name, value):
    """Scoped flag override preserving any pre-existing one."""
    from pixie_tpu.config import override_flag

    return override_flag(name, value)


def _pipeline_ab(eng, query, host_ref) -> dict:
    """A/B the window pipeline: serial (depth=1) vs pipelined (depth>=2)
    with device residency OFF, so every window pays the real host
    slicing + packing + device_put staging cost the pipeline exists to
    hide (resident windows skip staging entirely and overlap ~nothing).
    ``checked`` asserts the two modes' outputs are bit-identical and
    match the resident-path result."""
    saved_depth = eng.pipeline_depth
    depth = max(2, saved_depth)
    secs, host, pl = {}, {}, {}
    try:
        with _flag_override("device_residency", False):
            for label, d in (("serial", 1), ("pipelined", depth)):
                eng.pipeline_depth = d
                t0 = time.perf_counter()
                out = eng.execute_query(query, materialize=False)
                host[label] = {
                    k: (v.to_host() if hasattr(v, "to_host") else v)
                    for k, v in out.items()
                }
                secs[label] = time.perf_counter() - t0
                pl[label] = dict(eng.last_pipeline or {})
    finally:
        eng.pipeline_depth = saved_depth
    stage = pl["pipelined"].get("stage_secs", 0.0)
    stall = pl["pipelined"].get("stall_secs", 0.0)
    return {
        "depth": depth,
        "serial_secs": round(secs["serial"], 4),
        "pipelined_secs": round(secs["pipelined"], 4),
        "speedup": round(secs["serial"] / max(secs["pipelined"], 1e-9), 3),
        "stage_secs": round(stage, 4),
        "stall_secs": round(stall, 4),
        # Fraction of staging time hidden behind compute.
        "overlap_frac": round(
            max(0.0, min(1.0, 1.0 - stall / stage)) if stage > 0 else 1.0, 3
        ),
        "checked": bool(
            _host_equal(host["serial"], host["pipelined"])
            and _host_equal(host["pipelined"], host_ref)
        ),
    }


def _with_pipeline(res: dict) -> dict:
    """Attach the last ``_time_query`` pipeline + latency-quantile
    reports to a shape result."""
    if _LAST_PIPELINE is not None:
        res["pipeline"] = _LAST_PIPELINE
    if _LAST_LATENCY is not None:
        res["latency"] = _LAST_LATENCY
    return res


def _time_query(eng, query, n_rows, warm_eng=None, profile=False):
    """(rows/s, secs, host result[, profile]) for the steady-state run.

    Warm-up (trace + XLA compile, persisted in the compilation cache)
    runs against ``warm_eng`` — a single-window clone of the replay —
    with ``materialize=False``: compiling after the tunnel's journal
    flush can stall, so every program must exist before the first
    readback. The flush below then executes the journaled one-time
    table staging outside the timer; the timed run measures the query's
    real execution (fold + finalize + readback) in the synchronous
    regime against the already-resident table.

    Unless PIXIE_TPU_BENCH_AB=0, an A/B pass afterwards re-runs the
    query with device residency off at pipeline_depth 1 vs >=2 — the
    host-staged regime where the window-prefetch pipeline earns its keep
    — and reports per-shape overlap efficiency (``pipeline`` key).
    """
    global _LAST_PIPELINE, _LAST_LATENCY
    _LAST_PIPELINE = None
    _LAST_LATENCY = None
    ab = os.environ.get("PIXIE_TPU_BENCH_AB", "1") not in ("0", "false")
    # Single-window engine first (cheap shape coverage), then the FULL
    # engine: its window count selects the scan-fold program, which must
    # exist before the flush (compiling after it can stall).
    for e in ([warm_eng] if warm_eng is not None else []) + [eng]:
        warm_out = e.execute_query(query, materialize=False)
        for v in warm_out.values():
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
    if ab:
        # Warm the host-staged program variants (mask validity instead of
        # the device cache's (lo, hi) pairs) for the A/B pass — they too
        # must exist before the journal flush.
        with _flag_override("device_residency", False):
            for e in ([warm_eng] if warm_eng is not None else []) + [eng]:
                warm_out = e.execute_query(query, materialize=False)
                for v in warm_out.values():
                    if hasattr(v, "block_until_ready"):
                        v.block_until_ready()
    # Steady state means the replay is already resident in HBM: staging
    # H2D is journaled lazily by the tunnel, so force its flush (one tiny
    # readback) before the timer starts; the timed run then measures the
    # query itself, not the one-time table upload. (Intentionally a
    # readback, not a fence: block_until_ready does NOT flush the
    # journal, and an unflushed journal would defer the 600MB upload
    # into the timed run's readback.)
    for t in eng.tables.values():
        be = getattr(t, "_backend", None)
        if be is None:
            continue
        for win, _lo, _hi in t.device_scan(None, None,
                                           window_rows=eng.window_rows):
            for planes in win.cols.values():
                np.asarray(planes[0][:1])
                break
            break
    t0 = time.perf_counter()
    out = eng.execute_query(query, materialize=False)
    host = {
        k: (v.to_host() if hasattr(v, "to_host") else v)
        for k, v in out.items()
    }
    dt = time.perf_counter() - t0
    pl = dict(eng.last_pipeline or {})
    _LAST_PIPELINE = {
        "depth": pl.get("depth", eng.pipeline_depth),
        "windows": pl.get("windows", 0),
        "stall_secs": round(pl.get("stall_secs", 0.0), 4),
    }
    if ab:
        _LAST_PIPELINE["ab"] = _pipeline_ab(eng, query, host)
        # Headline stall/overlap come from the host-staged A/B arm (the
        # resident-path run above stages ~nothing).
        _LAST_PIPELINE["overlap_frac"] = _LAST_PIPELINE["ab"]["overlap_frac"]
        _LAST_PIPELINE["stall_secs"] = _LAST_PIPELINE["ab"]["stall_secs"]
    _LAST_LATENCY = _latency_report(eng)
    if not profile:
        return n_rows / dt, dt, host
    # Per-stage attribution (forces sync per stage; post-readback, so the
    # absolute numbers reflect the slow dispatch mode — ratios still
    # attribute where the time goes).
    eng.execute_query(query, analyze=True)
    prof = eng.last_stats.to_dict()
    return n_rows / dt, dt, host, {
        "stage_totals": prof["stage_totals"],
        "windows": sum(f["windows"] for f in prof["fragments"]),
        "analyzed_seconds": prof["total_seconds"],
    }


def _build_engines(name, rel, col_fn, n, window, dicts):
    """(full engine, single-window warm engine) over the same replay."""
    from pixie_tpu.exec.engine import Engine

    eng = Engine(window_rows=window)
    eng.create_table(name)
    _push_encoded(eng, name, rel, col_fn, n, window, dicts)
    warm = Engine(window_rows=window)
    warm.create_table(name)
    _push_encoded(warm, name, rel, col_fn, min(n, window), window, dicts)
    return eng, warm


def _http_replay(n, window, rng_seed=7):
    """The http_events replay shared by http_stats and service_stats."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(rng_seed)
    services = [f"svc-{i}" for i in range(32)]
    paths = [f"/api/v1/ep{i}" for i in range(8)]
    svc_dict, path_dict = StringDictionary(services), StringDictionary(paths)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("latency_ns", DataType.INT64),
        ("resp_status", DataType.INT64),
        ("service", DataType.STRING),
        ("req_path", DataType.STRING),
    ])
    statuses = np.array([200, 200, 200, 200, 404, 500])
    svc_codes = _codes(rng, n, len(services))
    path_codes = _codes(rng, n, len(paths))
    lat = rng.integers(1_000, 100_000_000, n)
    status = statuses[rng.integers(0, len(statuses), n)].astype(np.int64)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "latency_ns": (lat[s],),
            "resp_status": (status[s],),
            "service": (svc_codes[s],),
            "req_path": (path_codes[s],),
        }

    eng, warm = _build_engines("http_events", rel, cols, n, window,
                               {"service": svc_dict, "req_path": path_dict})
    return eng, warm, (lat, status, svc_codes, path_codes)


def _shape_http_stats(n, window):
    """configs[0]: filter + groupby-agg over http_events."""
    eng, warm, (lat, status, svc_codes, path_codes) = _http_replay(n, window)
    query = _script("px/http_stats")
    rps, dt, out, prof = _time_query(eng, query, n, warm_eng=warm, profile=True)

    # numpy baseline (timed: this is the vs_baseline denominator).
    t0 = time.perf_counter()
    ok = status < 400
    key = svc_codes[ok].astype(np.int64) * 64 + path_codes[ok]
    uniq, inv = np.unique(key, return_inverse=True)
    cnt = np.bincount(inv)
    mean = np.bincount(inv, weights=lat[ok].astype(np.float64)) / cnt
    mx = np.full(len(uniq), -np.inf)
    np.maximum.at(mx, inv, lat[ok])
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    gkey = got["service"].astype(np.int64) * 64 + got["req_path"]
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "http_stats keys mismatch"
    ro = np.argsort(uniq)
    assert np.array_equal(got["n"][order], cnt[ro].astype(got["n"].dtype))
    np.testing.assert_allclose(got["lat_mean"][order], mean[ro], rtol=1e-5)
    np.testing.assert_allclose(got["lat_max"][order], mx[ro])
    return _with_pipeline({
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
        "profile": prof,
    })


def _shape_service_stats(n, window):
    """configs[1]: p50/p99 t-digest + error-rate agg per service."""
    eng, warm, (lat, status, svc_codes, _) = _http_replay(n, window)
    query = _script("px/service_stats")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    ref = {}
    for s in np.unique(svc_codes):
        m = svc_codes == s
        ref[int(s)] = (
            np.quantile(lat[m], 0.5), np.quantile(lat[m], 0.99),
            float(np.mean(status[m] >= 400)), int(m.sum()),
        )
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    for s, p50, p99, err, thr in zip(
        got["service"], got["p50"], got["p99"], got["error_rate"], got["throughput"]
    ):
        r50, r99, rerr, rthr = ref[int(s)]
        assert abs(p50 - r50) / r50 < 0.15, f"p50 off: {p50} vs {r50}"
        assert abs(p99 - r99) / r99 < 0.15, f"p99 off: {p99} vs {r99}"
        np.testing.assert_allclose(err, rerr, rtol=1e-4)
        assert thr == rthr
    return _with_pipeline({
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    })


def _shape_dashboard_repeat(n, window):
    """ISSUE 16: the dashboard-refresh pattern — the SAME library
    scripts repeated over a growing http_events replay, served three
    ways by one engine:

    - cold: px/service_stats with an empty cache — the full rescan
      every repeat used to pay (this is the headline rows/s);
    - cache: repeats with unchanged table watermarks answered from the
      watermark-validated result cache (``hit`` disposition, zero
      execution);
    - view: px/http_stats (manifest ``materialize: true``) answered as
      finalize-over-state; after new windows land, the repeat folds
      ONLY the new rows (``view`` disposition) and must be
      bit-identical to a from-scratch rescan of the grown table.

    The numpy replay checks the cold result exactly like the
    service_stats shape; the view result is checked exactly like the
    http_stats shape AND bit-compared against the flags-off rescan.
    """
    from pixie_tpu.types.batch import HostBatch
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    # The view comparison is only meaningful when the replay spans many
    # windows (the fold touches the new ones; the rescan re-folds all),
    # so cap the window well below the replay size.
    window = max(min(window, n // 64), 1024)

    rng = np.random.default_rng(7)
    services = [f"svc-{i}" for i in range(32)]
    paths = [f"/api/v1/ep{i}" for i in range(8)]
    dicts = {"service": StringDictionary(services),
             "req_path": StringDictionary(paths)}
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("latency_ns", DataType.INT64),
        ("resp_status", DataType.INT64),
        ("service", DataType.STRING),
        ("req_path", DataType.STRING),
    ])
    statuses = np.array([200, 200, 200, 200, 404, 500])
    # The "growth": one more window lands AFTER the view registers, so
    # the incremental fold touches ONE window where a rescan re-folds
    # them all.
    m_extra = window
    total = n + m_extra
    svc_codes = _codes(rng, total, len(services))
    path_codes = _codes(rng, total, len(paths))
    lat = rng.integers(1_000, 100_000_000, total)
    status = statuses[rng.integers(0, len(statuses), total)].astype(np.int64)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "latency_ns": (lat[s],),
            "resp_status": (status[s],),
            "service": (svc_codes[s],),
            "req_path": (path_codes[s],),
        }

    eng, warm_eng = _build_engines("http_events", rel, cols, n, window, dicts)
    q_cache = _script("px/service_stats")
    q_view = _script("px/http_stats")

    # Warm-up compiles every program before the tunnel's journal flush
    # (see _time_query); the flush then runs the table upload outside
    # every timer below.
    for e in (warm_eng, eng):
        for q in (q_cache, q_view):
            out = e.execute_query(q, materialize=False)
            for v in out.values():
                if hasattr(v, "block_until_ready"):
                    v.block_until_ready()
    for t in eng.tables.values():
        for win, _lo, _hi in t.device_scan(None, None,
                                           window_rows=eng.window_rows):
            for planes in win.cols.values():
                np.asarray(planes[0][:1])
                break
            break

    # -- cold vs cache-hit (px/service_stats: budgeted, not a view) ----
    repeats = 10
    with _flag_override("result_cache_mb", 64):
        t0 = time.perf_counter()
        cold_out = eng.execute_query(q_cache)
        cold_s = time.perf_counter() - t0
        dispositions: dict = {}
        hit_times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            hot_out = eng.execute_query(q_cache)
            hit_times.append(time.perf_counter() - t0)
            d = eng.tracer.last().cache or ""
            dispositions[d] = dispositions.get(d, 0) + 1
        assert _host_equal(cold_out, hot_out), "cache hit result drifted"

        # -- view fold vs rescan (px/http_stats: materialize: true) ----
        eng.execute_query(q_view)  # registers the view (full first fold)
        assert eng.tracer.last().cache == "view", "manifest view not served"
        for off in range(n, total, window):
            m = min(window, total - off)
            eng.append_data("http_events", HostBatch(
                relation=rel, cols=cols(off, m), length=m, dicts=dicts,
            ))
        t0 = time.perf_counter()
        view_out = eng.execute_query(q_view)  # folds ONLY the new windows
        fold_s = time.perf_counter() - t0
        assert eng.tracer.last().cache == "view"
    eng.views.close()
    t0 = time.perf_counter()
    rescan_out = eng.execute_query(q_view)  # flags off: the plain path
    rescan_s = time.perf_counter() - t0

    # Checked numpy replay: cold result per the service_stats contract.
    first = slice(0, n)
    f_lat, f_status, f_svc = lat[first], status[first], svc_codes[first]
    got = cold_out["output"].to_pydict(decode_strings=False)
    for s, p50, p99, err, thr in zip(
        got["service"], got["p50"], got["p99"], got["error_rate"],
        got["throughput"],
    ):
        m = f_svc == s
        assert abs(p50 - np.quantile(f_lat[m], 0.5)) < 0.15 * np.quantile(
            f_lat[m], 0.5)
        assert abs(p99 - np.quantile(f_lat[m], 0.99)) < 0.15 * np.quantile(
            f_lat[m], 0.99)
        np.testing.assert_allclose(err, float(np.mean(f_status[m] >= 400)),
                                   rtol=1e-4)
        assert thr == int(m.sum())
    # View result: bit-identical to the rescan AND exact vs numpy.
    assert _host_equal(view_out, rescan_out), "view fold != full rescan"
    ok = status < 400
    key = svc_codes[ok].astype(np.int64) * 64 + path_codes[ok]
    uniq, inv = np.unique(key, return_inverse=True)
    cnt = np.bincount(inv)
    gv = view_out["output"].to_pydict(decode_strings=False)
    gkey = gv["service"].astype(np.int64) * 64 + gv["req_path"]
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order])
    assert np.array_equal(gv["n"][order], cnt[np.argsort(uniq)].astype(
        gv["n"].dtype))

    hit_p50 = float(np.median(hit_times))
    return {
        "rows": n, "rows_per_sec": round(n / cold_s),
        "secs": round(cold_s, 3), "checked": True,
        "repeat": {
            "count": repeats,
            "dispositions": dispositions,
            "hit_rate": round(
                (dispositions.get("hit", 0) + dispositions.get("view", 0))
                / repeats, 3),
            "cold_ms": round(cold_s * 1e3, 2),
            "hit_p50_ms": round(hit_p50 * 1e3, 3),
            "speedup": round(cold_s / max(hit_p50, 1e-9), 1),
        },
        "view": {
            "appended_rows": m_extra,
            "fold_ms": round(fold_s * 1e3, 2),
            "rescan_ms": round(rescan_s * 1e3, 2),
            "speedup": round(rescan_s / max(fold_s, 1e-9), 2),
            "bit_identical": True,
        },
    }


def _shape_net_flow_graph(n, window):
    """configs[2]: conn_stats self-join + groupby over src/dst pod pairs."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(11)
    n_pods = 48
    pods = [f"ns/pod-{i}" for i in range(n_pods)]
    addrs = [f"10.1.{i // 250}.{i % 250}" for i in range(n_pods)]
    pod_dict, addr_dict = StringDictionary(pods), StringDictionary(addrs)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("src_addr", DataType.STRING),
        ("src_pod", DataType.STRING),
        ("remote_addr", DataType.STRING),
        ("bytes_sent", DataType.INT64),
        ("bytes_recv", DataType.INT64),
    ])
    src = _codes(rng, n, n_pods)
    dst = _codes(rng, n, n_pods)
    sent = rng.integers(64, 1 << 20, n)
    recv = rng.integers(64, 1 << 20, n)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "src_addr": (src[s],),   # pod i owns addr i
            "src_pod": (src[s],),
            "remote_addr": (dst[s],),
            "bytes_sent": (sent[s],),
            "bytes_recv": (recv[s],),
        }

    eng, warm = _build_engines("conn_stats", rel, cols, n, window,
                               {"src_addr": addr_dict, "src_pod": pod_dict,
                                "remote_addr": addr_dict})

    query = _script("px/net_flow_graph")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    # Inner-join semantics: flows whose dst pod never appears as a source
    # are dropped by the query; mirror that (matters at tiny row counts).
    m = np.isin(dst, np.unique(src))
    key = src[m].astype(np.int64) * n_pods + dst[m]
    uniq, inv = np.unique(key, return_inverse=True)
    ref_sent = np.bincount(inv, weights=sent[m].astype(np.float64))
    ref_recv = np.bincount(inv, weights=recv[m].astype(np.float64))
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    gkey = got["src_pod"].astype(np.int64) * n_pods + got["src_pod_dst"]
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "net_flow keys mismatch"
    ro = np.argsort(uniq)
    np.testing.assert_allclose(got["bytes_sent"][order], ref_sent[ro], rtol=1e-6)
    np.testing.assert_allclose(got["bytes_recv"][order], ref_recv[ro], rtol=1e-6)
    return _with_pipeline({
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    })


def _shape_sql_stats(n, window):
    """configs[3]: SQL-normalize (dictionary-side regex UDF) + windowed agg."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary
    from pixie_tpu.udf.builtins.sql_ops import normalize_sql

    rng = np.random.default_rng(13)
    tables = ["users", "orders", "items", "carts", "sessions"]
    raw = []
    for i in range(400):  # 400 raw strings -> ~10 normalized shapes
        t = tables[i % len(tables)]
        raw.append(f"SELECT * FROM {t} WHERE id = {i} AND name = 'u{i}'")
        raw.append(f"UPDATE {t} SET v = {i * 3} WHERE id IN ({i}, {i + 1})")
    q_dict = StringDictionary(raw)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("query_str", DataType.STRING),
        ("latency_ns", DataType.INT64),
    ])
    qc = _codes(rng, n, len(raw))
    lat = rng.integers(10_000, 50_000_000, n)
    # ~64 one-second windows across the replay.
    tns = ((np.arange(n, dtype=np.int64) * 64) // max(n, 1)) * 1_000_000_000

    def cols(off, m):
        s = slice(off, off + m)
        return {"time_": (tns[s],), "query_str": (qc[s],), "latency_ns": (lat[s],)}

    eng, warm = _build_engines("mysql_events", rel, cols, n, window,
                               {"query_str": q_dict})

    query = _script("px/sql_stats")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    norm_vocab = np.array([normalize_sql(s) for s in raw])
    norms, norm_inv = np.unique(norm_vocab, return_inverse=True)
    nq = norm_inv[qc].astype(np.int64)
    win = tns // 1_000_000_000
    key = nq * 1_000 + win
    uniq, inv = np.unique(key, return_inverse=True)
    ref_n = np.bincount(inv)
    ref_mean = np.bincount(inv, weights=lat.astype(np.float64)) / ref_n
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict()
    g_nq = np.array([np.searchsorted(norms, s) for s in got["query_norm"]],
                    dtype=np.int64)
    gkey = g_nq * 1_000 + got["window"] // 1_000_000_000
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "sql_stats keys mismatch"
    ro = np.argsort(uniq)
    assert np.array_equal(got["n"][order], ref_n[ro].astype(got["n"].dtype))
    np.testing.assert_allclose(got["lat_mean"][order], ref_mean[ro], rtol=1e-5)
    return _with_pipeline({
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    })


def _shape_perf_flamegraph(n, window):
    """configs[4]: stack-trace groupby-count (continuous profiler shape)."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(17)
    frames = ["main", "run", "poll", "parse", "exec", "gc", "alloc", "read"]
    stacks = []
    for i in range(2000):
        depth = 2 + i % 6
        stacks.append(";".join(frames[(i + d) % len(frames)] + f"_{(i * 7 + d) % 97}"
                               for d in range(depth)))
    st_dict = StringDictionary(stacks)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("stack_trace", DataType.STRING),
        ("count", DataType.INT64),
    ])
    sc = _codes(rng, n, len(stacks))
    cnt = rng.integers(1, 50, n)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "stack_trace": (sc[s],),
            "count": (cnt[s],),
        }

    eng, warm = _build_engines("stack_traces.beta", rel, cols, n, window,
                               {"stack_trace": st_dict})

    query = _script("px/perf_flamegraph")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    ref = np.bincount(sc, weights=cnt.astype(np.float64), minlength=len(stacks))
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    order = np.argsort(got["stack_trace"])
    present = np.nonzero(ref)[0]
    assert np.array_equal(got["stack_trace"][order], present), "stack keys mismatch"
    np.testing.assert_allclose(got["count"][order], ref[present], rtol=1e-6)
    return _with_pipeline({
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    })


def _join_report(eng) -> dict | None:
    """Routing report of the query's materialized join: strategy chosen,
    build-side swap, THIS query's overflow retries (the decision's own
    count — the registry counter is process-cumulative across warm runs
    and would misattribute another run's retries), zone-skipped windows,
    plus the process-wide counter for the ISSUE 9 acceptance gate
    (``retries_total`` at 0 on every standard shape's subprocess)."""
    d = eng.last_join_decision
    retries_total = eng.tracer.registry.counter(
        "pixie_join_capacity_retries_total"
    ).value()
    if d is None:
        return {"retries_total": int(retries_total)}
    return {
        "strategy": d.strategy, "swap": bool(d.swap),
        "retries": int(d.retries),
        "retries_total": int(retries_total),
        "skipped_windows": int(d.skipped_windows),
    }


def _with_join(res: dict, eng) -> dict:
    rep = _join_report(eng)
    if rep is not None:
        res["join"] = rep
    return res


def _join_two_table_engines(n, window, lk, lb, rk, rc, rv):
    """Engines over a two-table join replay: l(time_, k, b), r(time_,
    k, c, v) — shared by the skew/selective join shapes."""
    from pixie_tpu.exec.engine import Engine
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation

    rel_l = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("b", DataType.INT64),
    ])
    rel_r = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("c", DataType.INT64),
        ("v", DataType.INT64),
    ])

    def cols_l(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (lk[s],), "b": (lb[s],)}

    def cols_r(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (rk[s],), "c": (rc[s],), "v": (rv[s],)}

    def build(rows_l, rows_r):
        e = Engine(window_rows=window)
        e.create_table("conn_l")
        e.create_table("conn_r")
        _push_encoded(e, "conn_l", rel_l, cols_l, rows_l, window, {})
        _push_encoded(e, "conn_r", rel_r, cols_r, rows_r, window, {})
        return e

    return build(n, len(rk)), build(min(n, window), min(len(rk), window))


_JOIN_BOTH_SIDES_QUERY = """
import px
l = px.DataFrame(table='conn_l')
r = px.DataFrame(table='conn_r')
g = l.merge(r, how='inner', left_on=['k'], right_on=['k'], suffixes=['', '_r'])
out = g.groupby(['b', 'c']).agg(n=('v', px.count), s=('v', px.sum))
px.display(out)
"""


def _check_join_both_sides(out, n_keys, lk, lb, rk, rc, rv):
    """Verify groupby(b from left, c from right) counts/sums against the
    numpy replay (per-key histograms contracted over the key axis — the
    join never materializes in the reference either, so the baseline is
    as fair as the scan shapes'). Returns the baseline seconds."""
    t0 = time.perf_counter()
    nb_, nc_ = 16, 8
    m_l = np.bincount(lk * nb_ + lb, minlength=n_keys * nb_).reshape(
        n_keys, nb_
    ).astype(np.float64)
    cnt_r = np.bincount(rk * nc_ + rc, minlength=n_keys * nc_).reshape(
        n_keys, nc_
    ).astype(np.float64)
    sum_r = np.bincount(rk * nc_ + rc, weights=rv.astype(np.float64),
                        minlength=n_keys * nc_).reshape(n_keys, nc_)
    ref_n = m_l.T @ cnt_r  # [b, c]
    ref_s = m_l.T @ sum_r
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict()
    gkey = got["b"].astype(np.int64) * nc_ + got["c"]
    order = np.argsort(gkey)
    present = np.nonzero(ref_n.reshape(-1))[0]
    assert np.array_equal(gkey[order], present), "join_both keys mismatch"
    np.testing.assert_allclose(
        got["n"][order], ref_n.reshape(-1)[present], rtol=1e-9
    )
    np.testing.assert_allclose(
        got["s"][order], ref_s.reshape(-1)[present], rtol=1e-9
    )
    return base_dt


def _shape_device_join_skew(n, window):
    """Skewed-key N:M join: build keys are zipf-distributed (a handful
    of keys carry most of the build rows, so per-probe fan-out varies by
    orders of magnitude), probe keys uniform. Group keys span both
    sides, so the eager-agg rewrite can't apply — this measures the raw
    join strategies under the distribution that breaks naive capacity
    guesses."""
    rng = np.random.default_rng(23)
    n_keys = max(n // 2, 1)
    lk = rng.integers(0, n_keys, n)
    lb = rng.integers(0, 16, n)
    # Zipf build keys spread over the id space by a fixed odd multiplier
    # (keeps skew, decorrelates hot ids from zone ranges).
    rk = (np.minimum(rng.zipf(1.5, n), n_keys) - 1) * 2654435761 % n_keys
    rc = rng.integers(0, 8, n)
    rv = rng.integers(0, 1000, n)
    eng, warm = _join_two_table_engines(n, window, lk, lb, rk, rc, rv)
    rps, dt, out = _time_query(eng, _JOIN_BOTH_SIDES_QUERY, 2 * n,
                               warm_eng=warm)
    base_dt = _check_join_both_sides(out, n_keys, lk, lb, rk, rc, rv)
    return _with_join(_with_pipeline({
        "rows": 2 * n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / ((2 * n) / base_dt), 3), "checked": True,
    }), eng)


def _shape_device_join_select(n, window):
    """Selective clustered join: probe keys ascend with time (each probe
    window spans a narrow key band — the live-telemetry shape) while the
    build side only covers the top eighth of the key space, so zone maps
    prove ~7/8 of probe windows can't match and the driver never stages
    them (host path: range pre-filter drops the same rows)."""
    rng = np.random.default_rng(29)
    n_keys = max(n // 2, 2)
    lk = (np.arange(n, dtype=np.int64) * n_keys) // n + rng.integers(
        0, max(n_keys // 256, 1), n
    )
    np.minimum(lk, n_keys - 1, out=lk)
    lb = rng.integers(0, 16, n)
    n_r = max(n // 4, 1)
    rk = rng.integers(n_keys - n_keys // 8, n_keys, n_r)
    rc = rng.integers(0, 8, n_r)
    rv = rng.integers(0, 1000, n_r)
    eng, warm = _join_two_table_engines(n, window, lk, lb, rk, rc, rv)
    rps, dt, out = _time_query(eng, _JOIN_BOTH_SIDES_QUERY, n + n_r,
                               warm_eng=warm)
    base_dt = _check_join_both_sides(out, n_keys, lk, lb, rk, rc, rv)
    return _with_join(_with_pipeline({
        "rows": n + n_r, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / ((n + n_r) / base_dt), 3),
        "checked": True,
    }), eng)


def _shape_device_join(n, window):
    """Bonus shape: RAW pre-agg N:M self-join through the engine's device
    join kernel (VERDICT r02 ask #5 — the five BASELINE joins are all
    post-agg), then a small aggregate so output stays bounded."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation

    rng = np.random.default_rng(19)
    n_keys = max(n // 2, 1)
    rel_l = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("b", DataType.INT64),
    ])
    rel_r = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("v", DataType.INT64),
    ])
    lk = rng.integers(0, n_keys, n)
    lb = rng.integers(0, 16, n)
    rk = rng.integers(0, n_keys, n)
    rv = rng.integers(0, 1000, n)

    def cols_l(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (lk[s],), "b": (lb[s],)}

    def cols_r(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (rk[s],), "v": (rv[s],)}

    from pixie_tpu.exec.engine import Engine

    eng = Engine(window_rows=window)
    eng.create_table("conn_l")
    eng.create_table("conn_r")
    _push_encoded(eng, "conn_l", rel_l, cols_l, n, window, {})
    _push_encoded(eng, "conn_r", rel_r, cols_r, n, window, {})
    warm = Engine(window_rows=window)
    warm.create_table("conn_l")
    warm.create_table("conn_r")
    n_warm = min(n, window)
    _push_encoded(warm, "conn_l", rel_l, cols_l, n_warm, window, {})
    _push_encoded(warm, "conn_r", rel_r, cols_r, n_warm, window, {})
    query = """
import px
l = px.DataFrame(table='conn_l')
r = px.DataFrame(table='conn_r')
g = l.merge(r, how='inner', left_on=['k'], right_on=['k'], suffixes=['', '_r'])
out = g.groupby('b').agg(n=('v', px.count), s=('v', px.sum))
px.display(out)
"""
    rps, dt, out = _time_query(eng, query, 2 * n, warm_eng=warm)

    t0 = time.perf_counter()
    cnt_r = np.bincount(rk, minlength=n_keys)
    sum_r = np.bincount(rk, weights=rv.astype(np.float64), minlength=n_keys)
    ref_n = np.bincount(lb, weights=cnt_r[lk].astype(np.float64), minlength=16)
    ref_s = np.bincount(lb, weights=sum_r[lk], minlength=16)
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict()
    order = np.argsort(got["b"])
    present = np.nonzero(ref_n)[0]
    assert np.array_equal(got["b"][order], present), "join keys mismatch"
    np.testing.assert_allclose(got["n"][order], ref_n[present], rtol=1e-9)
    np.testing.assert_allclose(got["s"][order], ref_s[present], rtol=1e-9)
    return _with_join(_with_pipeline({
        "rows": 2 * n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / ((2 * n) / base_dt), 3), "checked": True,
    }), eng)


def _shape_cold_scan(n, window):
    """ISSUE 20 (pxtier): scans over a MOSTLY-COLD table — the hot ring
    holds ~1/8 of the replay, the rest was demoted into the encoded cold
    store at append time. Two scans, four A/B arms:

    - selective: ``shard == k`` where shard ascends with time (each
      window holds ONE shard value), so zone maps prove every other
      window can't match and the scan skips it BEFORE decode. Run on
      the tiered and an all-hot engine, with zone skipping on and off
      (2x2); all four arms must be bit-identical, and the tiered+skip
      arm must skip >= 90% of windows.
    - full: group-by over every row, host-staged (device residency off
      so every cold window really decodes — resident windows would be
      served from HBM). The tiered wall must stay within 1.5x the
      all-hot wall; ``decode_ms`` vs ``stall_ms`` reports how much of
      the decode the prefetch pipeline hid.

    The headline rows/s is the full tiered scan (decode included); the
    numpy replay checks both results bit-exactly.
    """
    from pixie_tpu.exec.engine import Engine
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    # Many windows (skip-rate resolution) and whole windows only (keeps
    # window k <-> shard k exact).
    window = max(min(window, n // 64), 1024)
    n = max((n // window) * window, window)
    n_win = n // window
    rng = np.random.default_rng(31)
    services = [f"svc-{i}" for i in range(16)]
    dicts = {"service": StringDictionary(services)}
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("shard", DataType.INT64),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ])
    # shard ascends with time (live-telemetry clustering): window k
    # holds exactly shard k.
    shard = np.arange(n, dtype=np.int64) // window
    lat = rng.integers(1_000, 100_000_000, n)
    svc_codes = _codes(rng, n, len(services))

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "shard": (shard[s],),
            "latency_ns": (lat[s],),
            "service": (svc_codes[s],),
        }

    row_bytes = 8 + 8 + 8 + 4  # time + shard + latency + svc codes
    hot_budget = max(row_bytes * n // 8, row_bytes * window + 1)
    cold_mb = (row_bytes * n >> 20) + 64  # never evict: bit-identity

    pick = n_win // 3
    q_sel = f"""
import px
df = px.DataFrame(table='events')
df = df[df.shard == {pick}]
out = df.groupby('shard').agg(
    n=('latency_ns', px.count), s=('latency_ns', px.sum))
px.display(out)
"""
    q_full = """
import px
df = px.DataFrame(table='events')
out = df.groupby('service').agg(
    n=('latency_ns', px.count), s=('latency_ns', px.sum))
px.display(out)
"""

    with _flag_override("cold_tier_mb", cold_mb):
        cold_eng = Engine(window_rows=window)
        cold_eng.create_table("events", max_bytes=hot_budget)
        _push_encoded(cold_eng, "events", rel, cols, n, window, dicts)
    hot_eng = Engine(window_rows=window)
    hot_eng.create_table("events")
    _push_encoded(hot_eng, "events", rel, cols, n, window, dicts)

    st = cold_eng.tables["events"].stats()
    cold_frac = st.cold_rows / max(st.cold_rows + st.hot_rows, 1)
    assert cold_frac >= 0.75, f"replay not mostly cold ({cold_frac:.2f})"
    compression = st.cold_raw_bytes / max(st.cold_bytes, 1)

    def timed(eng, q, repeats=3):
        out = eng.execute_query(q, materialize=False)  # warm/compile
        for v in out.values():
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = eng.execute_query(q)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return out, best, eng.tracer.last().usage

    # 2x2 A/B arms for the selective scan: tier x zone skipping.
    arms, outs = {}, {}
    for tier_label, eng in (("cold", cold_eng), ("hot", hot_eng)):
        for skip_label, flag in (("skip", True), ("noskip", False)):
            with _flag_override("scan_zone_skip", flag):
                out, dt, u = timed(eng, q_sel, repeats=2)
            outs[f"{tier_label}_{skip_label}"] = out
            arms[f"{tier_label}_{skip_label}"] = {
                "secs": round(dt, 4),
                "skipped_windows": int(u.skipped_windows),
                "decode_ms": round(u.decode_ms, 3),
            }
    for k in ("cold_noskip", "hot_skip", "hot_noskip"):
        assert _host_equal(outs["cold_skip"], outs[k]), f"A/B drift: {k}"
    skip_rate = arms["cold_skip"]["skipped_windows"] / n_win
    assert skip_rate >= 0.9, f"skip rate {skip_rate:.2f} < 0.9"

    # Full scan, host-staged: every cold window decodes for real.
    with _flag_override("device_residency", False):
        full_cold, cold_s, u_cold = timed(cold_eng, q_full)
        full_hot, hot_s, _ = timed(hot_eng, q_full)
    assert _host_equal(full_cold, full_hot), "tiered full scan drifted"
    assert cold_s <= 1.5 * hot_s, (
        f"cold full scan {cold_s:.3f}s > 1.5x hot {hot_s:.3f}s"
    )
    decode_ms = float(u_cold.decode_ms)
    stall_ms = float(u_cold.stall_ms)

    # numpy replay (bit-exact: int64 counts/sums).
    t0 = time.perf_counter()
    msk = shard == pick
    ref_n, ref_s = int(msk.sum()), int(lat[msk].sum())
    cnt = np.bincount(svc_codes, minlength=len(services))
    sums = np.bincount(
        svc_codes, weights=lat.astype(np.float64), minlength=len(services)
    )
    base_dt = time.perf_counter() - t0
    g = outs["cold_skip"]["output"].to_pydict()
    assert int(g["shard"][0]) == pick and len(g["shard"]) == 1
    assert int(g["n"][0]) == ref_n and int(g["s"][0]) == ref_s
    gf = full_cold["output"].to_pydict(decode_strings=False)
    order = np.argsort(gf["service"])
    present = np.nonzero(cnt)[0]
    assert np.array_equal(np.sort(gf["service"]), present)
    np.testing.assert_array_equal(gf["n"][order], cnt[present])
    np.testing.assert_allclose(gf["s"][order], sums[present], rtol=1e-12)

    return {
        "rows": n, "rows_per_sec": round(n / cold_s),
        "secs": round(cold_s, 3), "checked": True,
        "vs_baseline": round((n / cold_s) / (n / base_dt), 3),
        "tier": {
            "cold_frac": round(cold_frac, 3),
            "compression": round(compression, 2),
            "demotions": int(st.demotions),
            "evictions": int(st.evictions),
        },
        "selective": dict(arms, **{
            "skip_rate": round(skip_rate, 3),
            "speedup_vs_noskip": round(
                arms["cold_noskip"]["secs"]
                / max(arms["cold_skip"]["secs"], 1e-9), 2),
        }),
        "full": {
            "cold_secs": round(cold_s, 4),
            "hot_secs": round(hot_s, 4),
            "cold_vs_hot": round(cold_s / max(hot_s, 1e-9), 3),
            "decode_ms": round(decode_ms, 2),
            "stall_ms": round(stall_ms, 2),
            # Fraction of decode wall the prefetch pipeline hid behind
            # compute (decode runs on the producer thread).
            "decode_hidden_frac": round(
                max(0.0, 1.0 - stall_ms / decode_ms), 3
            ) if decode_ms > 0 else 1.0,
        },
    }


def inner() -> int:
    shape = os.environ.get("PIXIE_TPU_BENCH_SHAPES", "http_stats").strip()
    if shape not in SHAPE_DEFS:
        log(f"[bench] unknown shape {shape!r}")
        return 1

    import jax

    platform = jax.devices()[0].platform
    log(f"[bench] devices: {jax.devices()}")
    default_rows = 16 * 1024 * 1024 if platform == "tpu" else 2 * 1024 * 1024
    n = int(os.environ.get("PIXIE_TPU_BENCH_ROWS", default_rows))
    fn_name, rows_div = SHAPE_DEFS[shape]
    n //= rows_div
    window = int(os.environ.get("PIXIE_TPU_BENCH_WINDOW", 1 << 21))
    # Device residency stages table windows at append time; the staging
    # window size must match the engines' query window size.
    os.environ["PIXIE_TPU_WINDOW_ROWS"] = str(window)

    log(f"[bench] {shape} @ {n:,} rows ...")
    try:
        res = globals()[fn_name](n, window)
        log(f"[bench] {shape}: {res}")
    except Exception as e:  # a broken shape must not zero the headline
        log(f"[bench] {shape} FAILED: {e!r}")
        res = {"error": repr(e)[:200]}
    print(json.dumps(
        {"shape": shape, "platform": platform, "result": res}
    ), flush=True)
    return 0


if __name__ == "__main__":
    if os.environ.get("PIXIE_TPU_BENCH_INNER"):
        sys.exit(inner())
    sys.exit(launcher())
