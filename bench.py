"""Headline benchmark: the five BASELINE query shapes (rows/sec).

Runs every BASELINE.json config through the real PxL frontend
(``Engine.execute_query``) over synthetic replays pushed through the
table-store ingest path, cross-checks each result against a vectorized
numpy implementation (stand-in for CPU Carnot, whose repo publishes no
absolute numbers — SURVEY.md §6), and prints ONE JSON line:

  {"metric": "http_stats_rows_per_sec", "value": rows/s, "unit": "rows/s",
   "vs_baseline": x, "device": "tpu"|"cpu", "shapes": {per-shape results}}

Self-configuring for the driver environment: the default invocation is a
launcher that runs the actual benchmark in a subprocess — first against
the TPU backend (with retries: the axon tunnel can be transiently
UNAVAILABLE, see BENCH_r01.json), then falling back to CPU with the axon
plugin disabled (PALLAS_AXON_POOL_IPS must be cleared before interpreter
boot; clearing it in-process is too late — tests/conftest.py).

Environment knobs:
  PIXIE_TPU_BENCH_ROWS     http_events replay rows (default 16M TPU / 2M CPU)
  PIXIE_TPU_BENCH_WINDOW   window rows per device dispatch (default 2^21)
  PIXIE_TPU_BENCH_BUDGET   launcher wall-clock budget in seconds (default 540)
  PIXIE_TPU_BENCH_SHAPES   comma list of shapes to run (default all five)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
from pixie_tpu.utils.cache import jax_cache_dir  # noqa: E402

CACHE_DIR = os.environ.get("JAX_COMPILATION_CACHE_DIR", jax_cache_dir())


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _script(name: str) -> str:
    """PxL source of a shipped library script (the bench runs the same
    scripts the library ships — VERDICT r02 ask #8)."""
    from pixie_tpu.scripts import load_script

    return load_script(name).pxl


# ---------------------------------------------------------------------------
# Launcher: subprocess orchestration so one bad backend never zeroes the run.
# ---------------------------------------------------------------------------


def _inner_env(platform: str, deadline_s: float) -> dict:
    from pixie_tpu.utils.cache import scrubbed_cpu_env

    env = scrubbed_cpu_env() if platform == "cpu" else dict(os.environ)
    if platform != "cpu":
        env.pop("JAX_PLATFORMS", None)
        env["JAX_COMPILATION_CACHE_DIR"] = CACHE_DIR
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    env["PIXIE_TPU_BENCH_INNER"] = "1"
    env["PIXIE_TPU_BENCH_DEADLINE"] = str(int(deadline_s))
    return env


def _try_run(platform: str, timeout_s: float):
    """Run the inner benchmark on `platform`; return parsed JSON or None."""
    import subprocess

    deadline = max(60.0, timeout_s - 30.0)
    log(f"[bench] launching inner ({platform}, timeout {timeout_s:.0f}s)")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=_inner_env(platform, deadline),
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=None,  # stream live
            timeout=timeout_s,
            text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"[bench] inner ({platform}) timed out after {timeout_s:.0f}s")
        return None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    log(f"[bench] inner ({platform}) rc={proc.returncode}, no JSON line")
    return None


def launcher() -> int:
    budget = float(os.environ.get("PIXIE_TPU_BENCH_BUDGET", 540))
    t0 = time.monotonic()
    result = None
    # TPU attempts: transient UNAVAILABLE from the tunnel is common; retry.
    for attempt in range(2):
        remaining = budget - (time.monotonic() - t0)
        if remaining < 150:
            break
        tpu_timeout = min(420.0, remaining - 120.0)
        if tpu_timeout < 90:
            break
        result = _try_run("tpu", tpu_timeout)
        if result is not None:
            break
        if attempt == 0:
            log("[bench] TPU attempt 1 failed; retrying")
            time.sleep(10)
        else:
            log("[bench] TPU attempts exhausted")
    if result is None:
        remaining = budget - (time.monotonic() - t0)
        cpu_timeout = max(90.0, remaining - 5.0)
        # A hung TPU attempt may leave only ~100s; keep the CPU run small.
        os.environ.setdefault("PIXIE_TPU_BENCH_ROWS", str(1024 * 1024))
        result = _try_run("cpu", cpu_timeout)
    if result is None:
        log("[bench] all backends failed")
        return 1
    print(json.dumps(result), flush=True)
    return 0


# ---------------------------------------------------------------------------
# Inner benchmark: generate replays, run the five PxL shapes, cross-check.
# ---------------------------------------------------------------------------


def _codes(rng, n, vocab_len):
    return rng.integers(0, vocab_len, n).astype(np.int32)


def _push_encoded(eng, name, rel, col_fn, n, window, dicts):
    """Push pre-encoded windows through the ingest path (append_data).

    String columns arrive as dictionary ids sharing one StringDictionary —
    the state a live collector's staging produces (strings are encoded at
    the edge, SURVEY.md §7 stage 1); the first append makes the table
    adopt these dictionaries so later windows append with zero remapping.
    """
    from pixie_tpu.types.batch import HostBatch

    for off in range(0, n, window):
        m = min(window, n - off)
        hb = HostBatch(
            relation=rel, cols=col_fn(off, m), length=m, dicts=dicts
        )
        eng.append_data(name, hb)


def _time_query(eng, query, n_rows, warm_eng=None, profile=False):
    """(rows/s, secs, result[, profile]) for the steady-state run.

    Warm-up (trace + XLA compile, persisted in the compilation cache)
    runs against ``warm_eng`` — a single-window clone of the replay — so
    the full table is scanned once, not twice. Steady state assumes
    device residency: the replay was staged into device memory at ingest
    (append time), so the timed run re-ships nothing.
    """
    (warm_eng or eng).execute_query(query)
    t0 = time.perf_counter()
    out = eng.execute_query(query)
    dt = time.perf_counter() - t0
    if not profile:
        return n_rows / dt, dt, out
    # Per-stage attribution (forces sync per stage; not the timed number).
    eng.execute_query(query, analyze=True)
    prof = eng.last_stats.to_dict()
    return n_rows / dt, dt, out, {
        "stage_totals": prof["stage_totals"],
        "windows": sum(f["windows"] for f in prof["fragments"]),
        "analyzed_seconds": prof["total_seconds"],
    }


def _build_engines(name, rel, col_fn, n, window, dicts):
    """(full engine, single-window warm engine) over the same replay."""
    from pixie_tpu.exec.engine import Engine

    eng = Engine(window_rows=window)
    eng.create_table(name)
    _push_encoded(eng, name, rel, col_fn, n, window, dicts)
    warm = Engine(window_rows=window)
    warm.create_table(name)
    _push_encoded(warm, name, rel, col_fn, min(n, window), window, dicts)
    return eng, warm


def _shape_http_stats(n, window):
    """configs[0]: filter + groupby-agg over http_events; also returns the
    engine so service_stats reuses the same replay."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(7)
    services = [f"svc-{i}" for i in range(32)]
    paths = [f"/api/v1/ep{i}" for i in range(8)]
    svc_dict, path_dict = StringDictionary(services), StringDictionary(paths)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("latency_ns", DataType.INT64),
        ("resp_status", DataType.INT64),
        ("service", DataType.STRING),
        ("req_path", DataType.STRING),
    ])
    statuses = np.array([200, 200, 200, 200, 404, 500])
    svc_codes = _codes(rng, n, len(services))
    path_codes = _codes(rng, n, len(paths))
    lat = rng.integers(1_000, 100_000_000, n)
    status = statuses[rng.integers(0, len(statuses), n)].astype(np.int64)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "latency_ns": (lat[s],),
            "resp_status": (status[s],),
            "service": (svc_codes[s],),
            "req_path": (path_codes[s],),
        }

    eng, warm = _build_engines("http_events", rel, cols, n, window,
                               {"service": svc_dict, "req_path": path_dict})

    query = _script("px/http_stats")
    rps, dt, out, prof = _time_query(eng, query, n, warm_eng=warm, profile=True)

    # numpy baseline (timed: this is the vs_baseline denominator).
    t0 = time.perf_counter()
    ok = status < 400
    key = svc_codes[ok].astype(np.int64) * 64 + path_codes[ok]
    uniq, inv = np.unique(key, return_inverse=True)
    cnt = np.bincount(inv)
    mean = np.bincount(inv, weights=lat[ok].astype(np.float64)) / cnt
    mx = np.full(len(uniq), -np.inf)
    np.maximum.at(mx, inv, lat[ok])
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    gkey = got["service"].astype(np.int64) * 64 + got["req_path"]
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "http_stats keys mismatch"
    ro = np.argsort(uniq)
    assert np.array_equal(got["n"][order], cnt[ro].astype(got["n"].dtype))
    np.testing.assert_allclose(got["lat_mean"][order], mean[ro], rtol=1e-5)
    np.testing.assert_allclose(got["lat_max"][order], mx[ro])
    return (eng, warm), (lat, status, svc_codes), {
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
        "profile": prof,
    }


def _shape_service_stats(engines, data, n):
    """configs[1]: p50/p99 t-digest + error-rate agg per service (reuses the
    http_events replay already in the engine)."""
    eng, warm = engines
    lat, status, svc_codes = data
    query = _script("px/service_stats")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    ref = {}
    for s in np.unique(svc_codes):
        m = svc_codes == s
        ref[int(s)] = (
            np.quantile(lat[m], 0.5), np.quantile(lat[m], 0.99),
            float(np.mean(status[m] >= 400)), int(m.sum()),
        )
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    for s, p50, p99, err, thr in zip(
        got["service"], got["p50"], got["p99"], got["error_rate"], got["throughput"]
    ):
        r50, r99, rerr, rthr = ref[int(s)]
        assert abs(p50 - r50) / r50 < 0.15, f"p50 off: {p50} vs {r50}"
        assert abs(p99 - r99) / r99 < 0.15, f"p99 off: {p99} vs {r99}"
        np.testing.assert_allclose(err, rerr, rtol=1e-4)
        assert thr == rthr
    return {
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    }


def _shape_net_flow_graph(n, window):
    """configs[2]: conn_stats self-join + groupby over src/dst pod pairs."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(11)
    n_pods = 48
    pods = [f"ns/pod-{i}" for i in range(n_pods)]
    addrs = [f"10.1.{i // 250}.{i % 250}" for i in range(n_pods)]
    pod_dict, addr_dict = StringDictionary(pods), StringDictionary(addrs)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("src_addr", DataType.STRING),
        ("src_pod", DataType.STRING),
        ("remote_addr", DataType.STRING),
        ("bytes_sent", DataType.INT64),
        ("bytes_recv", DataType.INT64),
    ])
    src = _codes(rng, n, n_pods)
    dst = _codes(rng, n, n_pods)
    sent = rng.integers(64, 1 << 20, n)
    recv = rng.integers(64, 1 << 20, n)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "src_addr": (src[s],),   # pod i owns addr i
            "src_pod": (src[s],),
            "remote_addr": (dst[s],),
            "bytes_sent": (sent[s],),
            "bytes_recv": (recv[s],),
        }

    eng, warm = _build_engines("conn_stats", rel, cols, n, window,
                               {"src_addr": addr_dict, "src_pod": pod_dict,
                                "remote_addr": addr_dict})

    query = _script("px/net_flow_graph")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    # Inner-join semantics: flows whose dst pod never appears as a source
    # are dropped by the query; mirror that (matters at tiny row counts).
    m = np.isin(dst, np.unique(src))
    key = src[m].astype(np.int64) * n_pods + dst[m]
    uniq, inv = np.unique(key, return_inverse=True)
    ref_sent = np.bincount(inv, weights=sent[m].astype(np.float64))
    ref_recv = np.bincount(inv, weights=recv[m].astype(np.float64))
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    gkey = got["src_pod"].astype(np.int64) * n_pods + got["src_pod_dst"]
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "net_flow keys mismatch"
    ro = np.argsort(uniq)
    np.testing.assert_allclose(got["bytes_sent"][order], ref_sent[ro], rtol=1e-6)
    np.testing.assert_allclose(got["bytes_recv"][order], ref_recv[ro], rtol=1e-6)
    return {
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    }


def _shape_sql_stats(n, window):
    """configs[3]: SQL-normalize (dictionary-side regex UDF) + windowed agg."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary
    from pixie_tpu.udf.builtins.sql_ops import normalize_sql

    rng = np.random.default_rng(13)
    tables = ["users", "orders", "items", "carts", "sessions"]
    raw = []
    for i in range(400):  # 400 raw strings -> ~10 normalized shapes
        t = tables[i % len(tables)]
        raw.append(f"SELECT * FROM {t} WHERE id = {i} AND name = 'u{i}'")
        raw.append(f"UPDATE {t} SET v = {i * 3} WHERE id IN ({i}, {i + 1})")
    q_dict = StringDictionary(raw)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("query_str", DataType.STRING),
        ("latency_ns", DataType.INT64),
    ])
    qc = _codes(rng, n, len(raw))
    lat = rng.integers(10_000, 50_000_000, n)
    # ~64 one-second windows across the replay.
    tns = ((np.arange(n, dtype=np.int64) * 64) // max(n, 1)) * 1_000_000_000

    def cols(off, m):
        s = slice(off, off + m)
        return {"time_": (tns[s],), "query_str": (qc[s],), "latency_ns": (lat[s],)}

    eng, warm = _build_engines("mysql_events", rel, cols, n, window,
                               {"query_str": q_dict})

    query = _script("px/sql_stats")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    norm_vocab = np.array([normalize_sql(s) for s in raw])
    norms, norm_inv = np.unique(norm_vocab, return_inverse=True)
    nq = norm_inv[qc].astype(np.int64)
    win = tns // 1_000_000_000
    key = nq * 1_000 + win
    uniq, inv = np.unique(key, return_inverse=True)
    ref_n = np.bincount(inv)
    ref_mean = np.bincount(inv, weights=lat.astype(np.float64)) / ref_n
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict()
    g_nq = np.array([np.searchsorted(norms, s) for s in got["query_norm"]],
                    dtype=np.int64)
    gkey = g_nq * 1_000 + got["window"] // 1_000_000_000
    order = np.argsort(gkey)
    assert np.array_equal(np.sort(uniq), gkey[order]), "sql_stats keys mismatch"
    ro = np.argsort(uniq)
    assert np.array_equal(got["n"][order], ref_n[ro].astype(got["n"].dtype))
    np.testing.assert_allclose(got["lat_mean"][order], ref_mean[ro], rtol=1e-5)
    return {
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    }


def _shape_perf_flamegraph(n, window):
    """configs[4]: stack-trace groupby-count (continuous profiler shape)."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(17)
    frames = ["main", "run", "poll", "parse", "exec", "gc", "alloc", "read"]
    stacks = []
    for i in range(2000):
        depth = 2 + i % 6
        stacks.append(";".join(frames[(i + d) % len(frames)] + f"_{(i * 7 + d) % 97}"
                               for d in range(depth)))
    st_dict = StringDictionary(stacks)
    rel = Relation([
        ("time_", DataType.TIME64NS),
        ("stack_trace", DataType.STRING),
        ("count", DataType.INT64),
    ])
    sc = _codes(rng, n, len(stacks))
    cnt = rng.integers(1, 50, n)

    def cols(off, m):
        s = slice(off, off + m)
        return {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "stack_trace": (sc[s],),
            "count": (cnt[s],),
        }

    eng, warm = _build_engines("stack_traces.beta", rel, cols, n, window,
                               {"stack_trace": st_dict})

    query = _script("px/perf_flamegraph")
    rps, dt, out = _time_query(eng, query, n, warm_eng=warm)

    t0 = time.perf_counter()
    ref = np.bincount(sc, weights=cnt.astype(np.float64), minlength=len(stacks))
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict(decode_strings=False)
    order = np.argsort(got["stack_trace"])
    present = np.nonzero(ref)[0]
    assert np.array_equal(got["stack_trace"][order], present), "stack keys mismatch"
    np.testing.assert_allclose(got["count"][order], ref[present], rtol=1e-6)
    return {
        "rows": n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / (n / base_dt), 3), "checked": True,
    }


def _shape_device_join(n, window):
    """Bonus shape: RAW pre-agg N:M self-join through the engine's device
    join kernel (VERDICT r02 ask #5 — the five BASELINE joins are all
    post-agg), then a small aggregate so output stays bounded."""
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.relation import Relation

    rng = np.random.default_rng(19)
    n_keys = max(n // 2, 1)
    rel_l = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("b", DataType.INT64),
    ])
    rel_r = Relation([
        ("time_", DataType.TIME64NS),
        ("k", DataType.INT64),
        ("v", DataType.INT64),
    ])
    lk = rng.integers(0, n_keys, n)
    lb = rng.integers(0, 16, n)
    rk = rng.integers(0, n_keys, n)
    rv = rng.integers(0, 1000, n)

    def cols_l(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (lk[s],), "b": (lb[s],)}

    def cols_r(off, m):
        s = slice(off, off + m)
        return {"time_": (np.arange(off, off + m, dtype=np.int64),),
                "k": (rk[s],), "v": (rv[s],)}

    from pixie_tpu.exec.engine import Engine

    eng = Engine(window_rows=window)
    eng.create_table("conn_l")
    eng.create_table("conn_r")
    _push_encoded(eng, "conn_l", rel_l, cols_l, n, window, {})
    _push_encoded(eng, "conn_r", rel_r, cols_r, n, window, {})
    warm = Engine(window_rows=window)
    warm.create_table("conn_l")
    warm.create_table("conn_r")
    n_warm = min(n, window)
    _push_encoded(warm, "conn_l", rel_l, cols_l, n_warm, window, {})
    _push_encoded(warm, "conn_r", rel_r, cols_r, n_warm, window, {})
    query = """
import px
l = px.DataFrame(table='conn_l')
r = px.DataFrame(table='conn_r')
g = l.merge(r, how='inner', left_on=['k'], right_on=['k'], suffixes=['', '_r'])
out = g.groupby('b').agg(n=('v', px.count), s=('v', px.sum))
px.display(out)
"""
    rps, dt, out = _time_query(eng, query, 2 * n, warm_eng=warm)

    t0 = time.perf_counter()
    cnt_r = np.bincount(rk, minlength=n_keys)
    sum_r = np.bincount(rk, weights=rv.astype(np.float64), minlength=n_keys)
    ref_n = np.bincount(lb, weights=cnt_r[lk].astype(np.float64), minlength=16)
    ref_s = np.bincount(lb, weights=sum_r[lk], minlength=16)
    base_dt = time.perf_counter() - t0

    got = out["output"].to_pydict()
    order = np.argsort(got["b"])
    present = np.nonzero(ref_n)[0]
    assert np.array_equal(got["b"][order], present), "join keys mismatch"
    np.testing.assert_allclose(got["n"][order], ref_n[present], rtol=1e-9)
    np.testing.assert_allclose(got["s"][order], ref_s[present], rtol=1e-9)
    return {
        "rows": 2 * n, "rows_per_sec": round(rps), "secs": round(dt, 3),
        "vs_baseline": round(rps / ((2 * n) / base_dt), 3), "checked": True,
    }


def inner() -> int:
    t_start = time.monotonic()
    deadline = float(os.environ.get("PIXIE_TPU_BENCH_DEADLINE", 420))

    import jax

    platform = jax.devices()[0].platform
    log(f"[bench] devices: {jax.devices()}")
    default_rows = 16 * 1024 * 1024 if platform == "tpu" else 2 * 1024 * 1024
    n = int(os.environ.get("PIXIE_TPU_BENCH_ROWS", default_rows))
    window = int(os.environ.get("PIXIE_TPU_BENCH_WINDOW", 1 << 21))
    # Device residency stages table windows at append time; the staging
    # window size must match the engines' query window size.
    os.environ["PIXIE_TPU_WINDOW_ROWS"] = str(window)
    want = [
        s.strip()
        for s in os.environ.get(
            "PIXIE_TPU_BENCH_SHAPES",
            "http_stats,service_stats,net_flow_graph,sql_stats,"
            "perf_flamegraph,device_join",
        ).split(",")
        if s.strip()
    ]

    shapes: dict = {}

    def time_left():
        return deadline - (time.monotonic() - t_start)

    # http_stats always runs: it is the headline metric.
    log(f"[bench] http_stats: generating {n:,} rows ...")
    engines, data, shapes["http_stats"] = _shape_http_stats(n, window)
    log(f"[bench] http_stats: {shapes['http_stats']}")

    # Tail shapes run SMALL first so every shape reports a number, then
    # upscale in order while budget remains (VERDICT r02 ask #2).
    n_small = min(n, 2 * 1024 * 1024)
    tails = [
        ("net_flow_graph", _shape_net_flow_graph, n // 2),
        ("sql_stats", _shape_sql_stats, n // 4),
        ("perf_flamegraph", _shape_perf_flamegraph, n // 4),
        ("device_join", _shape_device_join, n // 4),
    ]
    known = {"service_stats"} | {t[0] for t in tails}
    unknown = [s for s in want if s != "http_stats" and s not in known]
    if unknown:
        log(f"[bench] unknown shapes in PIXIE_TPU_BENCH_SHAPES: {unknown}")

    def run_shape(name, fn, rows):
        log(f"[bench] {name} @ {rows:,} rows ...")
        try:
            res = fn(rows, window)
            log(f"[bench] {name}: {res}")
            return res
        except Exception as e:  # a broken shape must not zero the headline
            log(f"[bench] {name} FAILED: {e!r}")
            return {"error": repr(e)[:200]}

    if "service_stats" in want:
        if time_left() > 30:
            log("[bench] service_stats ...")
            try:
                shapes["service_stats"] = _shape_service_stats(engines, data, n)
                log(f"[bench] service_stats: {shapes['service_stats']}")
            except Exception as e:
                shapes["service_stats"] = {"error": repr(e)[:200]}
        else:
            shapes["service_stats"] = {"skipped": "deadline"}
    else:
        shapes["service_stats"] = {"skipped": "not selected"}

    for name, fn, _full in tails:
        if name not in want:
            shapes[name] = {"skipped": "not selected"}
            continue
        if time_left() < 30:
            shapes[name] = {"skipped": "deadline"}
            continue
        shapes[name] = run_shape(name, fn, min(n_small, _full))
    # Upscale pass: spend leftover budget on full-size tail runs.
    for name, fn, full in tails:
        if name not in want or full <= n_small:
            continue
        if "error" in shapes.get(name, {}) or "skipped" in shapes.get(name, {}):
            continue
        if time_left() < 150:
            break
        res = run_shape(name, fn, full)
        if "error" not in res:
            shapes[name] = res

    head = shapes["http_stats"]
    print(json.dumps({
        "metric": "http_stats_rows_per_sec",
        "value": head["rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": head["vs_baseline"],
        "device": platform,
        "shapes": shapes,
    }), flush=True)
    return 0


if __name__ == "__main__":
    if os.environ.get("PIXIE_TPU_BENCH_INNER"):
        sys.exit(inner())
    sys.exit(launcher())
