"""Headline benchmark: px/http_stats-class query throughput (rows/sec).

Runs BASELINE.json configs[0] — filter + group-by aggregate over an
http_events replay — through the single-chip engine, streaming fixed-size
windows device-side, and compares against a vectorized numpy CPU baseline
(stand-in for CPU Carnot, whose repo publishes no absolute numbers —
SURVEY.md §6).

Prints ONE JSON line:
  {"metric": ..., "value": rows/sec, "unit": "rows/s", "vs_baseline": x}

Environment knobs:
  PIXIE_TPU_BENCH_ROWS    total replay rows (default 16M)
  PIXIE_TPU_BENCH_WINDOW  window rows per device dispatch (default 2^21)
"""

import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def gen_http_events(n, window):
    """Pre-encoded http_events replay, chunked into HostBatch windows."""
    from pixie_tpu.types.batch import HostBatch
    from pixie_tpu.types.relation import Relation
    from pixie_tpu.types.dtypes import DataType
    from pixie_tpu.types.strings import StringDictionary

    rng = np.random.default_rng(7)
    services = [f"svc-{i}" for i in range(32)]
    paths = [f"/api/v1/ep{i}" for i in range(8)]
    svc_dict, path_dict = StringDictionary(services), StringDictionary(paths)
    rel = Relation(
        [
            ("time_", DataType.TIME64NS),
            ("latency_ns", DataType.INT64),
            ("resp_status", DataType.INT64),
            ("service", DataType.STRING),
            ("req_path", DataType.STRING),
        ]
    )
    batches = []
    for off in range(0, n, window):
        m = min(window, n - off)
        cols = {
            "time_": (np.arange(off, off + m, dtype=np.int64),),
            "latency_ns": (rng.integers(1_000, 100_000_000, m),),
            "resp_status": (
                rng.choice(np.array([200, 200, 200, 200, 404, 500]), m),
            ),
            "service": (rng.integers(0, len(services), m).astype(np.int32),),
            "req_path": (rng.integers(0, len(paths), m).astype(np.int32),),
        }
        batches.append(
            HostBatch(
                relation=rel,
                cols=cols,
                length=m,
                dicts={"service": svc_dict, "req_path": path_dict},
            )
        )
    return rel, batches


def build_plan():
    from pixie_tpu.exec.plan import (
        AggExpr, AggOp, ColumnRef, FilterOp, FuncCall, Literal,
        MemorySourceOp, Plan, ResultSinkOp,
    )
    from pixie_tpu.types.dtypes import DataType

    p = Plan()
    src = p.add(MemorySourceOp(table="http_events"))
    flt = p.add(
        FilterOp(
            predicate=FuncCall(
                "lessThan", (ColumnRef("resp_status"), Literal(400, DataType.INT64))
            )
        ),
        [src],
    )
    agg = p.add(
        AggOp(
            group_cols=("service", "req_path"),
            aggs=(
                AggExpr("n", "count", (ColumnRef("latency_ns"),)),
                AggExpr("lat_mean", "mean", (ColumnRef("latency_ns"),)),
                AggExpr("lat_max", "max", (ColumnRef("latency_ns"),)),
            ),
            max_groups=512,
        ),
        [flt],
    )
    p.add(ResultSinkOp("out"), [agg])
    return p


def numpy_baseline(batches):
    """Vectorized single-core CPU implementation of the same query."""
    t0 = time.perf_counter()
    key_acc, lat_acc = [], []
    for hb in batches:
        ok = hb.cols["resp_status"][0] < 400
        key = (
            hb.cols["service"][0][ok].astype(np.int64) * 1024
            + hb.cols["req_path"][0][ok]
        )
        key_acc.append(key)
        lat_acc.append(hb.cols["latency_ns"][0][ok])
    key = np.concatenate(key_acc)
    lat = np.concatenate(lat_acc)
    uniq, inv = np.unique(key, return_inverse=True)
    n = np.bincount(inv)
    s = np.bincount(inv, weights=lat.astype(np.float64))
    mx = np.full(len(uniq), -np.inf)
    np.maximum.at(mx, inv, lat)
    dt = time.perf_counter() - t0
    return {"n": n, "mean": s / n, "max": mx, "uniq": uniq}, dt


def main():
    n_rows = int(os.environ.get("PIXIE_TPU_BENCH_ROWS", 16 * 1024 * 1024))
    window = int(os.environ.get("PIXIE_TPU_BENCH_WINDOW", 1 << 21))

    import jax

    log(f"devices: {jax.devices()}")
    from pixie_tpu.exec.engine import Engine

    log(f"generating {n_rows:,} rows ...")
    rel, batches = gen_http_events(n_rows, window)

    eng = Engine(window_rows=window)
    t = eng.create_table("http_events", rel)
    for hb in batches:
        t.dicts.update(hb.dicts)
        t.batches.append(hb)

    plan = build_plan()
    # Warmup: one pass over a single window to compile.
    warm = Engine(window_rows=window)
    tw = warm.create_table("http_events", rel)
    tw.dicts.update(batches[0].dicts)
    tw.batches.append(batches[0])
    t0 = time.perf_counter()
    warm.execute_plan(plan)
    log(f"warmup (compile + first window): {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    out = eng.execute_plan(plan)["out"]
    elapsed = time.perf_counter() - t0
    rows_per_sec = n_rows / elapsed
    log(f"engine: {elapsed:.3f}s  {rows_per_sec:,.0f} rows/s  ({out.length} groups)")

    ref, ref_dt = numpy_baseline(batches)
    ref_rows_per_sec = n_rows / ref_dt
    log(f"numpy baseline: {ref_dt:.3f}s  {ref_rows_per_sec:,.0f} rows/s")

    # Correctness cross-check vs the baseline.
    got = out.to_pydict(decode_strings=False)
    order = np.argsort(got["service"].astype(np.int64) * 1024 + got["req_path"])
    assert np.array_equal(np.sort(ref["uniq"]),
                          (got["service"].astype(np.int64) * 1024 + got["req_path"])[order])
    ref_order = np.argsort(ref["uniq"])
    assert np.array_equal(got["n"][order], ref["n"][ref_order].astype(got["n"].dtype))
    np.testing.assert_allclose(got["lat_mean"][order], ref["mean"][ref_order], rtol=1e-6)
    np.testing.assert_allclose(got["lat_max"][order], ref["max"][ref_order])
    log("correctness vs baseline: OK")

    print(
        json.dumps(
            {
                "metric": "http_stats_rows_per_sec",
                "value": round(rows_per_sec),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / ref_rows_per_sec, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
