"""Structured diagnostics with plan-node provenance.

Reference parity: Carnot's compiler surfaces typed Status errors with IR
node context (``src/carnot/planner/compiler/...``); its C++ type system
catches bad plans before execution. The Python rebuild discovers the
same bugs as device-side shape errors mid-query — a ``Diagnostic``
restores the compile-time failure mode: every finding names the plan
node (id + operator) or source location it came from, a stable rule
code, and a human message.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..planner.objects import PxLError


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"


@dataclass(frozen=True)
class Diagnostic:
    """One verifier/lint finding.

    ``code`` is the stable rule identifier (``unbound-column``,
    ``udf-signature``, ``dangling-output``, ...); ``node`` / ``op`` give
    plan provenance for verifier findings, ``path`` / ``line`` source
    provenance for lint findings.
    """

    code: str
    message: str
    severity: Severity = Severity.ERROR
    node: int | None = None  # plan node id
    op: str | None = None  # operator class name at that node
    plan: str = ""  # which plan: "logical" | "data" | "merge"
    path: str | None = None  # lint: source file
    line: int | None = None  # lint: 1-based line

    def render(self) -> str:
        where = ""
        if self.node is not None:
            frag = f" in {self.plan} plan" if self.plan else ""
            where = f" [node {self.node}: {self.op}{frag}]"
        elif self.path is not None:
            where = f" [{self.path}:{self.line}]"
        return f"{self.code}: {self.message}{where}"


class PlanCheckError(PxLError):
    """A compiled plan failed static verification.

    Subclasses ``PxLError`` so every existing compile-error path (CLI
    stderr, API error payloads, broker error replies) renders it as a
    compile-time failure rather than a mid-query execution error.
    """

    def __init__(self, diagnostics: list):
        self.diagnostics = list(diagnostics)
        lines = [d.render() for d in self.diagnostics]
        super().__init__(
            "plan verification failed:\n  " + "\n  ".join(lines)
        )
