"""pxbound soundness gate (``run_tests.sh --bounds``; runs in
``--analyze``/``--tier1``).

The resource-bound pass (``analysis/bounds.py``) is load-bearing — the
broker's admission control rejects queries on its predictions — so it
must be FALSIFIABLE, not advisory. This gate replays every bench shape
(the same queries ``bench.py`` times, over synthetic ingest pushed
through the real table-store append path so the sketches exist) plus
the bundled self-monitoring scripts, and asserts for each query that
the OBSERVED ``QueryResourceUsage`` (PR 7 telemetry: the trace's
``bytes_staged``/``rows_in``/``rows_out``) stays <= the PREDICTED
bound (which already includes the ``bounds_safety`` factor). It then
proves the rejection half of the contract: an intentionally
over-budget query fails AT COMPILE with a structured ``resource-bound``
``Diagnostic`` — never an OOM or a silent truncation at run time.

Also reports pass overhead relative to compile time: like the plan
verifier, pxbound rides inside the ``compile`` span and is budgeted at
<5% of it.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from .bench_check import SHAPE_SCHEMAS, _shape_query

#: Rows appended per table in the replay (small: the gate checks
#: bound SOUNDNESS, not throughput — bench.py owns the numbers).
GATE_ROWS = 4096

#: (observed usage key, predicted cost key) pairs the gate asserts on.
CHECKS = (
    ("bytes_staged", "bytes_staged_hi"),
    ("rows_in", "rows_in_hi"),
    ("rows_out", "rows_out_hi"),
)


def _synth_column(dtype, n: int, rng, col: str):
    from ..types.dtypes import DataType

    if dtype == DataType.TIME64NS:
        t0 = time.time_ns() - n * 1_000_000
        return t0 + np.arange(n, dtype=np.int64) * 1_000_000
    if dtype == DataType.INT64:
        if col == "resp_status":
            return rng.choice(np.array([200, 200, 404, 500]), n)
        return rng.integers(0, 1_000, n).astype(np.int64)
    if dtype == DataType.FLOAT64:
        return rng.random(n)
    if dtype == DataType.BOOLEAN:
        return rng.integers(0, 2, n).astype(bool)
    # STRING: a small vocabulary (realistic NDV; joins/self-joins match)
    vocab = [f"{col}-{i}" for i in range(16)]
    return [vocab[int(i)] for i in rng.integers(0, len(vocab), n)]


def _replay_engine(schemas, rows: int = GATE_ROWS, tiered: bool = False):
    """A fresh Engine with ``rows`` synthetic rows per table pushed
    through the REAL append path (so ingest sketches exist and pxbound
    sees what production would). ``tiered=True`` replays onto
    byte-bounded tables with the cold tier on (docs/STORAGE.md) so
    most windows demote — the cold-heavy regime the decode bound is
    stated against."""
    import contextlib

    from ..config import override_flag
    from ..exec.engine import Engine
    from .bounds import _row_bytes

    win = 256
    ctx = (
        override_flag("cold_tier_mb", 64)
        if tiered else contextlib.nullcontext()
    )
    with ctx:
        engine = Engine(window_rows=win) if tiered else Engine()
        rng = np.random.default_rng(7)
        for table, rel in schemas.items():
            data = {
                name: _synth_column(dt, rows, rng, name)
                for name, dt in rel.items()
            }
            if not tiered:
                engine.append_data(table, data)
                continue
            # Hot budget of ~1/4 the replay: ~3/4 of windows end cold.
            engine.create_table(
                table, relation=rel,
                max_bytes=max((_row_bytes(rel) or 32) * rows // 4, win),
            )
            for lo in range(0, rows, win):
                engine.append_data(table, {
                    c: v[lo:lo + win] for c, v in data.items()
                })
    return engine


def _check_one(name, engine, query, verbose) -> tuple[int, float, float]:
    """Run one query; compare observed usage vs the predicted report.
    Returns (failures, compile_s, bounds_s)."""
    from ..planner import CompilerState, compile_pxl
    from .bounds import plan_bounds

    t0 = time.perf_counter()
    engine.execute_query(query)
    report = engine.last_resource_report
    trace = engine.tracer.recent()[0]
    observed = trace["usage"]
    failures = 0
    if report is None:
        print(f"[bounds] {name}: FAIL (no resource report attached)",
              file=sys.stderr)
        return 1, (0.0, 0.0), (0.0, 0.0)
    cost = report.cost()
    for obs_key, pred_key in CHECKS:
        pred = cost.get(pred_key)
        if pred is None:
            continue  # unbounded: trivially sound
        obs = int(observed.get(obs_key, 0))
        if obs > pred:
            failures += 1
            print(
                f"[bounds] {name}: FAIL — observed {obs_key}={obs} > "
                f"predicted {pred_key}={pred} (unsound bound)",
                file=sys.stderr,
            )
    # Overhead: re-time a warm compile (every memo hot — the repeat-
    # compile regime the <5% budget is about) and the UNcached bounds
    # walk (what an ingest-invalidated snapshot pays).
    state = CompilerState(
        schemas={n: t.relation for n, t in engine.tables.items()},
        registry=engine.registry,
        table_stats=engine._compile_table_stats(),
    )
    compiled = compile_pxl(query, state)  # warm the memos
    from .bounds import apply_plan_bounds

    def best_of(fn, n=5):
        best = float("inf")
        for _ in range(n):
            t = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t)
        return best

    # A genuinely NOVEL compile span (cache-busted script: every memo
    # misses, the rule passes run) — the denominator the verifier's
    # <5% budget is stated against; repeat compiles only get cheaper.
    novel = best_of(
        lambda: compile_pxl(query + f"\n# cold {time.monotonic_ns()}",
                            state),
        n=3,
    )
    warm_compile = best_of(lambda: compile_pxl(query, state))
    # The memoized in-compile cost (key build + cache hit + re-presize)
    # — what the always-on pass actually adds to a repeat compile.
    hit = best_of(lambda: apply_plan_bounds(
        compiled.plan, state.schemas, state.registry, state.table_stats,
        script=query,
        plan_params=(state.max_output_rows, state.max_groups),
    ))
    # The cold walk an ingest-invalidated snapshot pays (uncached).
    cold = best_of(lambda: plan_bounds(
        compiled.plan, state.schemas, state.registry, state.table_stats,
    ), n=3)
    if verbose and not failures:
        print(
            f"[bounds] {name}: ok — staged {observed['bytes_staged']}/"
            f"{cost['bytes_staged_hi']} rows_in {observed['rows_in']}/"
            f"{cost['rows_in_hi']} rows_out {observed['rows_out']}/"
            f"{cost['rows_out_hi']} (observed/predicted, origin "
            f"{cost['origin']}, total {time.perf_counter() - t0:.2f}s)",
            file=sys.stderr,
        )
    return failures, (novel, warm_compile), (hit, cold)


def _check_cold_decode(name, engine, verbose) -> int:
    """Cold-heavy soundness (ISSUE 20): with most replay windows
    demoted, observed decoded bytes must hold ``<= predicted
    cold_decode_bytes_hi`` (zone-map skipping only lowers the
    realized value — the bound assumes every cold window decodes)."""
    tiers = [
        t._tier for t in engine.tables.values()
        if getattr(t, "_tier", None) is not None
    ]
    cold_rows = sum(t.table.stats().cold_rows for t in tiers)
    if not tiers or not cold_rows:
        print(f"[bounds] {name}: FAIL — tiered replay produced no cold "
              "windows (gate is vacuous)", file=sys.stderr)
        return 1
    pred = engine.last_resource_report.cold_decode_bytes_hi
    obs = sum(t.store.decoded_bytes for t in tiers)
    if pred is None or obs > pred:
        print(f"[bounds] {name}: FAIL — observed decoded bytes {obs} > "
              f"predicted cold_decode_bytes_hi {pred}", file=sys.stderr)
        return 1
    if verbose:
        print(f"[bounds] {name}: cold decode ok — {obs}/{pred} bytes "
              f"(observed/predicted, {cold_rows} cold rows)",
              file=sys.stderr)
    return 0


def _check_rejection(verbose: bool) -> int:
    """The admission half: an over-budget query must fail at COMPILE
    with a structured resource-bound Diagnostic (and never execute)."""
    from ..config import override_flag
    from .diagnostics import PlanCheckError

    schemas = SHAPE_SCHEMAS["http_stats"]
    engine = _replay_engine(schemas, rows=GATE_ROWS)
    executed = {"n": 0}
    orig = engine._execute_plan_inner
    engine._execute_plan_inner = lambda *a, **k: (
        executed.__setitem__("n", executed["n"] + 1) or orig(*a, **k)
    )
    # GATE_ROWS rows x ~20B/row x safety ~= 160KB >> 0.01MB budget.
    with override_flag("bounds_query_budget_mb", 0.01):
        try:
            engine.execute_query(_shape_query("http_stats"))
        except PlanCheckError as e:
            codes = {d.code for d in e.diagnostics}
            if "resource-bound" in codes and executed["n"] == 0:
                if verbose:
                    print(
                        "[bounds] over-budget rejection: ok (compile-"
                        f"time resource-bound diagnostic, 0 executions)",
                        file=sys.stderr,
                    )
                return 0
            print(
                f"[bounds] over-budget rejection: FAIL (codes {codes}, "
                f"{executed['n']} executions)", file=sys.stderr,
            )
            return 1
    print(
        "[bounds] over-budget rejection: FAIL (query was admitted)",
        file=sys.stderr,
    )
    return 1


def check_bounds(verbose: bool = True) -> int:
    """Replay every bench shape + the bundled self-monitoring scripts
    against pxbound's predictions; returns the failure count."""
    from ..scripts import load_script
    from ..services.telemetry import enable_self_telemetry
    from .obs_check import OBS_SCRIPTS

    failures = 0
    compile_total = warm_total = hit_total = cold_total = 0.0
    for shape, schemas in SHAPE_SCHEMAS.items():
        tiered = shape == "cold_scan"
        engine = _replay_engine(schemas, tiered=tiered)
        f, c, b = _check_one(shape, engine, _shape_query(shape), verbose)
        if tiered:
            f += _check_cold_decode(shape, engine, verbose)
        failures += f
        compile_total += c[0]
        warm_total += c[1]
        hit_total += b[0]
        cold_total += b[1]

    # The bundled self-monitoring scripts run over the telemetry tables
    # a self-observing engine maintains — including the sketch-LESS
    # fallback path (telemetry rings carry few sketched columns), which
    # must degrade to unbounded predictions, never crash or reject.
    engine = _replay_engine(SHAPE_SCHEMAS["http_stats"])
    enable_self_telemetry(engine)
    engine.execute_query(_shape_query("http_stats"))  # seed __queries__
    for name in OBS_SCRIPTS:
        f, c, b = _check_one(
            name, engine, load_script(name).pxl, verbose
        )
        failures += f
        compile_total += c[0]
        warm_total += c[1]
        hit_total += b[0]
        cold_total += b[1]

    failures += _check_rejection(verbose)
    if verbose and compile_total > 0:
        pct = hit_total / compile_total
        print(
            f"[bounds] novel compile {compile_total * 1e3:.1f}ms (repeat "
            f"{warm_total * 1e3:.1f}ms); in-compile pass (memoized, the "
            f"always-on repeat cost) {hit_total * 1e3:.2f}ms "
            f"({pct:.1%} of compile); cold walk on a fresh stats "
            f"snapshot {cold_total * 1e3:.1f}ms "
            f"({cold_total / compile_total:.1%})",
            file=sys.stderr,
        )
        if pct >= 0.05:
            failures += 1
            print(
                "[bounds] FAIL: memoized pass exceeds 5% of the compile "
                "span", file=sys.stderr,
            )
    return failures


def main() -> int:
    failures = check_bounds()
    n = len(SHAPE_SCHEMAS)
    if failures:
        print(f"[bounds] {failures} soundness check(s) failed",
              file=sys.stderr)
        return 1
    print(
        f"[bounds] all {n} bench shapes + self-monitoring scripts hold "
        "observed <= predicted; over-budget rejection verified",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
