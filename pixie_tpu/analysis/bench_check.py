"""Plan verification over the bench shapes (``run_tests.sh
--analyze``).

Compiles every bench shape's query (the same shipped library scripts
``bench.py`` runs) against the bench replay schemas, with the always-on
plan verifier active, then splits each through the DistributedPlanner
(2 PEMs + 1 Kelvin) and runs the full distributed schema walk. Any
diagnostic is a regression: these plans are the repo's
performance-critical shapes and must stay statically clean.

Also reports verifier overhead relative to compile time — the pass
rides inside the ``compile`` span, budgeted at <5% of its p50
(ISSUE 7 acceptance; ``bench.py`` measures the span itself).

Schemas mirror the replay builders in ``bench.py`` (``_http_replay``,
``_shape_net_flow_graph``, ``_shape_sql_stats``,
``_shape_perf_flamegraph``, ``_shape_device_join``); a column drift
there will fail here with an unbound-column diagnostic, which is the
point.
"""

from __future__ import annotations

import sys
import time

from ..types.dtypes import DataType
from ..types.relation import Relation

T, I, F, S = (
    DataType.TIME64NS, DataType.INT64, DataType.FLOAT64, DataType.STRING,
)

#: shape -> (tables, query source loader). Queries load lazily so a
#: missing script surfaces as THIS shape's failure, not an import error.
SHAPE_SCHEMAS = {
    "http_stats": {
        "http_events": Relation([
            ("time_", T), ("latency_ns", I), ("resp_status", I),
            ("service", S), ("req_path", S),
        ]),
    },
    "service_stats": {
        "http_events": Relation([
            ("time_", T), ("latency_ns", I), ("resp_status", I),
            ("service", S), ("req_path", S),
        ]),
    },
    "net_flow_graph": {
        "conn_stats": Relation([
            ("time_", T), ("src_addr", S), ("src_pod", S),
            ("remote_addr", S), ("bytes_sent", I), ("bytes_recv", I),
        ]),
    },
    "sql_stats": {
        "mysql_events": Relation([
            ("time_", T), ("query_str", S), ("latency_ns", I),
        ]),
    },
    "perf_flamegraph": {
        "stack_traces.beta": Relation([
            ("time_", T), ("stack_trace", S), ("count", I),
        ]),
    },
    "device_join": {
        "conn_l": Relation([("time_", T), ("k", I), ("b", I)]),
        "conn_r": Relation([("time_", T), ("k", I), ("v", I)]),
    },
    # The join-distribution shapes (skewed keys / selective clustered
    # keys) share one query whose group keys span BOTH sides — the
    # eager-agg rewrite cannot fire, so this verifies the REAL N:M
    # JoinOp plan the windowed/radix drivers execute.
    "device_join_skew": {
        "conn_l": Relation([("time_", T), ("k", I), ("b", I)]),
        "conn_r": Relation([("time_", T), ("k", I), ("c", I), ("v", I)]),
    },
    "device_join_select": {
        "conn_l": Relation([("time_", T), ("k", I), ("b", I)]),
        "conn_r": Relation([("time_", T), ("k", I), ("c", I), ("v", I)]),
    },
    # Storage-tier shape (ISSUE 20): selective scan whose FilterOp
    # drives zone-map window skipping over a mostly-cold table.
    "cold_scan": {
        "events": Relation([
            ("time_", T), ("shard", I), ("latency_ns", I), ("service", S),
        ]),
    },
}

# bench.py's inline queries, verbatim (the shapes whose queries are not
# shipped library scripts).
_DEVICE_JOIN_QUERY = """
import px
l = px.DataFrame(table='conn_l')
r = px.DataFrame(table='conn_r')
g = l.merge(r, how='inner', left_on=['k'], right_on=['k'], suffixes=['', '_r'])
out = g.groupby('b').agg(n=('v', px.count), s=('v', px.sum))
px.display(out)
"""

_COLD_SCAN_QUERY = """
import px
df = px.DataFrame(table='events')
df = df[df.shard == 7]
out = df.groupby('shard').agg(
    n=('latency_ns', px.count), s=('latency_ns', px.sum))
px.display(out)
"""

_JOIN_BOTH_SIDES_QUERY = """
import px
l = px.DataFrame(table='conn_l')
r = px.DataFrame(table='conn_r')
g = l.merge(r, how='inner', left_on=['k'], right_on=['k'], suffixes=['', '_r'])
out = g.groupby(['b', 'c']).agg(n=('v', px.count), s=('v', px.sum))
px.display(out)
"""


def _shape_query(shape: str) -> str:
    if shape == "device_join":
        return _DEVICE_JOIN_QUERY
    if shape in ("device_join_skew", "device_join_select"):
        return _JOIN_BOTH_SIDES_QUERY
    if shape == "cold_scan":
        return _COLD_SCAN_QUERY
    from ..scripts import load_script

    return load_script(f"px/{shape}").pxl


def check_bench_shapes(verbose: bool = True) -> int:
    """Compile + verify every bench shape; returns the number of failing
    shapes (0 = green)."""
    from ..planner import CompilerState, compile_pxl
    from ..planner.distributed import DistributedPlanner
    from ..planner.distributed.distributed_state import DistributedState
    from ..udf.registry import default_registry
    from .diagnostics import PlanCheckError, Severity
    from .verifier import verify_distributed_plan, verify_plan

    registry = default_registry()
    dstate = DistributedState.homogeneous(2, 1)
    failures = 0
    compile_total = verify_total = 0.0
    for shape, schemas in SHAPE_SCHEMAS.items():
        state = CompilerState(schemas=dict(schemas), registry=registry)
        try:
            t0 = time.perf_counter()
            compiled = compile_pxl(_shape_query(shape), state)
            t1 = time.perf_counter()
            # Re-run the verifier standalone to time it (inside
            # compile_pxl it already ran once, included in t1-t0).
            diags = verify_plan(compiled.plan, schemas, registry)
            dplan = DistributedPlanner(registry).plan(
                compiled.plan, dstate
            )
            diags += verify_distributed_plan(dplan, schemas, registry)
            t2 = time.perf_counter()
        except PlanCheckError as e:
            failures += 1
            if verbose:
                print(f"[analyze] {shape}: FAIL\n{e}", file=sys.stderr)
            continue
        compile_total += t1 - t0
        verify_total += t2 - t1
        errors = [d for d in diags if d.severity == Severity.ERROR]
        if errors:
            failures += 1
            if verbose:
                print(f"[analyze] {shape}: FAIL", file=sys.stderr)
                for d in errors:
                    print(f"  {d.render()}", file=sys.stderr)
        elif verbose:
            print(
                f"[analyze] {shape}: ok "
                f"({len(compiled.plan.nodes)} logical nodes, "
                f"{len(dplan.split.before_blocking.nodes)}+"
                f"{len(dplan.split.after_blocking.nodes)} split)",
                file=sys.stderr,
            )
    if verbose and compile_total > 0:
        # verify_total counts a FULL standalone re-verify + the whole
        # distributed split+walk; the in-compile incremental cost is
        # smaller still.
        print(
            f"[analyze] compile {compile_total * 1e3:.1f}ms, "
            f"standalone verify+split {verify_total * 1e3:.1f}ms "
            f"({verify_total / compile_total:.1%} of compile)",
            file=sys.stderr,
        )
    return failures


def main() -> int:
    failures = check_bench_shapes()
    if failures:
        print(f"[analyze] {failures} bench shape(s) failed verification",
              file=sys.stderr)
        return 1
    print(f"[analyze] all {len(SHAPE_SCHEMAS)} bench shapes verify clean",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
