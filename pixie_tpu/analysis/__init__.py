"""Static analysis: plan-time verification + source lint framework.

Two subsystems, one goal — fail fast on plan bugs and concurrency/JAX
hazards *before* a fragment blows up on-device mid-query:

- ``verifier``: an always-on pass between ``planner/compiler.py`` and
  ``exec/engine.py`` that walks compiled logical and distributed plans
  doing schema propagation, column binding, dtype checking of every
  expression against ``udf/registry.py`` signatures, and
  distributed-plan invariants. Diagnostics carry plan-node provenance
  (node id + operator) instead of a device-side shape error.
- ``lint``: a reusable AST-rule engine (driven by ``tools/pxlint.py``)
  with JAX- and concurrency-aware rules over the source tree.
- ``bounds`` (pxbound): an abstract interpreter propagating per-node
  resource bounds (row intervals, bytes, group counts, join output,
  bridge wire bytes) seeded from ingest sketches; its
  ``PlanResourceReport`` pre-sizes engine buffers and drives the
  broker's predicted-cost admission control, audited by the
  ``bound_check`` soundness gate against PR-7 telemetry.

See docs/ANALYSIS.md for the rule catalog, suppression syntax, the
baseline workflow, and the bounds domain.
"""

from .bounds import (
    PlanResourceReport,
    check_plan_bounds,
    distributed_bounds,
    plan_bounds,
)
from .diagnostics import Diagnostic, PlanCheckError, Severity
from .verifier import (
    check_plan,
    verify_dispatch_sets,
    verify_distributed_plan,
    verify_plan,
)

__all__ = [
    "Diagnostic",
    "PlanCheckError",
    "PlanResourceReport",
    "Severity",
    "check_plan",
    "check_plan_bounds",
    "distributed_bounds",
    "plan_bounds",
    "verify_dispatch_sets",
    "verify_distributed_plan",
    "verify_plan",
]
