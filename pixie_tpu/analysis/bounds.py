"""pxbound: plan-time resource-bound verification via abstract
interpretation.

Runs as an always-on pass AFTER the plan verifier (``verifier.py``) in
``compile_pxl`` and (for distributed queries) after
``check_distributed_plan`` in ``DistributedPlanner.plan``. Where the
verifier answers "is this plan well-formed?", pxbound answers "what can
this plan COST?": it propagates a per-node resource domain through the
operator DAG —

- **row-count interval** ``[lo, hi]`` (``hi=None`` = unbounded),
  seeded from ingest-sketch row counts (``CompilerState.table_stats``,
  maintained by ``table_store/sketches.py`` at append time),
- **bytes per row** from the propagated relation's host dtype widths
  (the exact unit ``HostBatch.nbytes`` / ``QueryResourceUsage.
  bytes_staged`` accounts in),
- **group-count bound** for aggregates (HLL NDV product of the group
  columns traced through renames to the source sketches, clamped by
  ``max_groups_limit``),
- **join output bound** reusing the runtime's own
  ``exec/joins.estimate_join_capacity`` (NDV fan-out x zone overlap)
  with side statistics synthesized from the table stats,
- **bridge wire-bytes bound** at every ``BridgeSinkOp``.

The walk produces a :class:`PlanResourceReport` — the query's
*predicted* ``QueryResourceUsage`` — that

1. the engine uses to pre-size aggregate group capacity
   (``presize_plan_aggs``: grow ``AggOp.max_groups`` to the NDV bound
   so a first run starts at the predicted rung instead of climbing the
   overflow-doubling ladder, one whole-table re-fold per rung) and to
   seed join output capacities where run-time sketches cannot see
   (post-aggregate build sides), and
2. the broker attaches to each dispatch as ``predicted_cost`` and
   schedules on: admission control rejects or queues queries whose
   predicted bytes exceed the configured per-engine budget
   (``admission_bytes_budget_mb``), surfaced through ``px debug
   queries`` as predicted-vs-observed columns.

Soundness contract: every bound is an inclusive UPPER bound on the
observed counter under the ``bounds_safety`` factor, falsifiable
against PR 7 telemetry — ``analysis/bound_check.py`` replays the bench
shapes + the bundled self-monitoring scripts and asserts observed
``QueryResourceUsage`` <= predicted. Two deliberate exceptions, both
with run-time escape hatches: join output bounds are NDV *estimates*
(adversarial key skew can exceed them; the kernel's overflow-retry
ladder absorbs it, counted in ``usage.retries``), and bounds are
sketch-seeded, so concurrent ingest between compile and execution can
raise the true row count (the safety factor absorbs normal churn).
Sketch-less inputs degrade to unbounded (``hi=None``) — conservative,
never a crash, and never a rejection.

Reference grounding: PAPERS.md "Online Sketch-based Query
Optimization" (arXiv:2102.02440) and "Sketched Sum-Product Networks
for Joins" (arXiv:2506.14034) run the same sketch-driven estimation
loop as best-effort optimizer hints; here it runs as an always-on
verifier whose predictions are load-bearing (admission control) and
audited (the soundness gate). See docs/ANALYSIS.md.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field

import numpy as np

from ..exec.plan import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    LookupJoinOp,
    MapOp,
    MemorySourceOp,
    Plan,
    UDTFSourceOp,
    UnionOp,
    trace_map_renames,
)
from .diagnostics import Diagnostic, PlanCheckError, Severity
from .verifier import _Ctx, _topo

#: Per-slot aggregate-state byte estimate multiplier: a group slot
#: carries the packed key planes + one or two f64/i64 carries per
#: aggregate (mean = sum+count) + validity. 24 bytes per (slot, column)
#: is a deliberate over-estimate of the 8-16 real bytes.
_AGG_SLOT_BYTES = 24

#: Device bytes per join row across the kernel's output planes
#: (probe idx, probe take, build idx, build take + the staged key).
_JOIN_ROW_BYTES = 40


def _unb(*vals):
    """None-propagating helper: any unbounded operand -> unbounded."""
    return any(v is None for v in vals)


@dataclass
class Interval:
    """Row-count interval; ``hi=None`` means unbounded (no sketch)."""

    lo: int = 0
    hi: int | None = None

    def cap(self, n: int | None) -> "Interval":
        if n is None:
            return Interval(self.lo, self.hi)
        hi = n if self.hi is None else min(self.hi, n)
        return Interval(min(self.lo, hi), hi)

    def zero_lo(self) -> "Interval":
        return Interval(0, self.hi)


@dataclass
class NodeBound:
    """The resource domain at one plan node."""

    rows: Interval
    row_bytes: int | None = None  # host bytes/row of the out relation
    groups: int | None = None  # agg: NDV-derived group bound
    join_capacity: int | None = None  # join: estimated output capacity
    wire_bytes: int | None = None  # bridge sink: payload bound
    cold_rows: int | None = None  # source: rows resident in the cold tier
    origin: str = "none"  # 'sketch' | 'derived' | 'none'


@dataclass
class PlanResourceReport:
    """Predicted resource envelope of one plan — the plan-time
    counterpart of ``QueryResourceUsage``. ``None`` totals mean
    unbounded (some input had no sketches); consumers must treat them
    as "unknown, admit/skip", never as zero."""

    plan_name: str = "logical"
    safety: float = 1.0
    nodes: dict = field(default_factory=dict)  # nid -> NodeBound
    rows_in_hi: int | None = None
    rows_out_hi: int | None = None
    bytes_staged_hi: int | None = None
    wire_bytes_hi: int | None = None
    peak_node_bytes_hi: int | None = None
    #: Upper bound on raw bytes that must be DECODED from the cold
    #: storage tier to serve the scan (docs/STORAGE.md). Zone-map
    #: window skipping only lowers the realized value. 0 for untiered
    #: sources; None when a tiered source's rows are unbounded.
    cold_decode_bytes_hi: int | None = None
    agg_groups: dict = field(default_factory=dict)  # nid -> group bound
    join_capacity: dict = field(default_factory=dict)  # nid -> capacity
    diagnostics: list = field(default_factory=list)

    @property
    def origin(self) -> str:
        origins = {b.origin for b in self.nodes.values()}
        if origins <= {"none"} or not origins:
            return "none"
        return "sketch" if "none" not in origins else "mixed"

    def cost(self) -> dict:
        """Wire-safe summary: what the broker attaches to dispatches as
        ``predicted_cost`` and stamps on the query trace (the
        predicted-vs-observed columns of ``px debug queries``)."""
        return {
            "bytes_staged_hi": self.bytes_staged_hi,
            "rows_in_hi": self.rows_in_hi,
            "rows_out_hi": self.rows_out_hi,
            "wire_bytes_hi": self.wire_bytes_hi,
            "peak_node_bytes_hi": self.peak_node_bytes_hi,
            "cold_decode_bytes_hi": self.cold_decode_bytes_hi,
            "origin": self.origin,
            "safety": self.safety,
        }


_DT_BYTES: dict = {}  # DataType -> host bytes/row (lazy: import order)


def _row_bytes(rel) -> int | None:
    """Host bytes per row of ``rel`` (sum of plane itemsizes — the unit
    ``HostBatch.nbytes`` reports)."""
    if rel is None:
        return None
    if not _DT_BYTES:
        from ..types.dtypes import DataType, host_dtypes

        for dt in DataType:
            _DT_BYTES[dt] = int(sum(
                np.dtype(hd).itemsize for hd in host_dtypes(dt)
            ))
    return sum(_DT_BYTES[dt] for _n, dt in rel.items())


def _source_col_stats(plan: Plan, nid: int, cols, table_stats):
    """Trace ``cols`` at node ``nid`` back through Map renames /
    Filter / Limit to a MemorySourceOp's sketch stats. Returns
    ``(rows, {col: (ndv, lo, hi)})`` or ``(None, None)`` when any hop
    computes the columns or stats are missing (sketches then no longer
    describe the values — same reverse walk as
    ``exec/joins._chain_key_sources``)."""
    if not table_stats:
        return None, None
    mapping = {c: c for c in cols}
    while True:
        node = plan.nodes.get(nid)
        if node is None:
            return None, None
        op = node.op
        if isinstance(op, MemorySourceOp):
            st = table_stats.get(op.table)
            if not isinstance(st, dict):
                return None, None
            ndvs = st.get("ndv") or {}
            zones = st.get("zones") or {}
            out = {}
            for want, src in mapping.items():
                ndv = ndvs.get(src)
                if ndv is None:
                    return None, None
                lo, hi = (zones.get(src) or (None, None))[:2] \
                    if zones.get(src) else (None, None)
                out[want] = (int(ndv), lo, hi)
            return st.get("rows"), out
        if isinstance(op, (FilterOp, LimitOp)) and node.inputs:
            nid = node.inputs[0]
        elif isinstance(op, MapOp) and node.inputs:
            mapping = trace_map_renames(op, mapping)
            if mapping is None:
                return None, None
            nid = node.inputs[0]
        else:
            return None, None


def _join_side_stats(plan: Plan, nid: int, on_cols, table_stats,
                     rows_hi: int | None):
    """Synthesize a ``JoinSideStats`` for one join input from the
    traced source sketches, falling back to the propagated row bound
    alone (NDV-less) when tracing fails."""
    from ..exec.joins import JoinSideStats

    rows, stats = _source_col_stats(plan, nid, list(on_cols), table_stats)
    if stats is not None and len(on_cols) == 1:
        ndv, lo, hi = stats[on_cols[0]]
        r = rows if rows_hi is None else min(int(rows or 0), rows_hi)
        return JoinSideStats(
            rows=int(r or 0), lo=lo, hi=hi,
            ndv=max(1, min(ndv, int(r or ndv))), origin="sketch",
        )
    if rows_hi is not None:
        return JoinSideStats(rows=int(rows_hi), origin="none")
    return None


def _node_bound(plan, nid, node, in_bounds, ctx, table_stats,
                max_groups_limit):
    """One transfer step of the abstract interpreter: the node's
    resource domain from its inputs' domains."""
    op = node.op
    rel = ctx.rels.get(nid)
    rb = _row_bytes(rel)
    first = in_bounds[0] if in_bounds else None

    if isinstance(op, MemorySourceOp):
        st = (table_stats or {}).get(op.table)
        rows = st.get("rows") if isinstance(st, dict) else None
        tier = st.get("tier") if isinstance(st, dict) else None
        cold_rows = None
        if isinstance(tier, dict):
            # Per-tier seeding from the table's freshness envelope
            # (docs/STORAGE.md): the OBSERVED raw bytes/row of the
            # resident data. Taken as a max with the schema-derived
            # width so the staged-bytes bound never narrows below
            # either; the cold row count seeds the decode-bytes bound.
            obs = tier.get("raw_row_bytes")
            if obs:
                rb = max(rb or 0, int(math.ceil(obs)))
            cr = tier.get("cold_rows")
            if cr is not None:
                cold_rows = int(cr)
        if rows is not None:
            return NodeBound(
                Interval(0, int(rows)), rb,
                cold_rows=cold_rows, origin="sketch",
            )
        return NodeBound(Interval(0, None), rb, cold_rows=cold_rows)

    if isinstance(op, EmptySourceOp):
        return NodeBound(Interval(0, 0), rb, origin="derived")

    if isinstance(op, UDTFSourceOp):
        return NodeBound(Interval(0, None), rb)

    if isinstance(op, BridgeSourceOp):
        # Seeded by the distributed walk (data-side sink bound x agent
        # count) via ctx.bridge_relations' sibling dict; standalone
        # merge plans degrade to unbounded.
        hi = getattr(ctx, "bridge_rows", {}).get(op.bridge_id)
        return NodeBound(
            Interval(0, hi), rb,
            origin="derived" if hi is not None else "none",
        )

    if first is None:
        return NodeBound(Interval(0, None), rb)

    if isinstance(op, MapOp):
        return NodeBound(
            Interval(first.rows.lo, first.rows.hi), rb, origin=first.origin
        )

    if isinstance(op, FilterOp):
        return NodeBound(first.rows.zero_lo(), rb, origin=first.origin)

    if isinstance(op, LimitOp):
        return NodeBound(
            first.rows.zero_lo().cap(max(op.n, 0)), rb, origin=first.origin
        )

    if isinstance(op, AggOp):
        if not op.group_cols:
            return NodeBound(Interval(0, 1), rb, origin="derived")
        hi = first.rows.hi
        groups = None
        _rows, stats = _source_col_stats(
            plan, node.inputs[0], list(op.group_cols), table_stats
        )
        if stats is not None:
            groups = 1
            for _c, (ndv, _lo, _hi) in stats.items():
                groups *= max(int(ndv), 1)
        if groups is not None:
            hi = groups if hi is None else min(hi, groups)
        if hi is not None:
            hi = min(hi, int(max_groups_limit))
        origin = "sketch" if groups is not None else (
            first.origin if hi is not None else "none"
        )
        return NodeBound(Interval(0, hi), rb, groups=groups, origin=origin)

    if isinstance(op, JoinOp):
        left, right = (in_bounds + [None, None])[:2]
        l_hi = left.rows.hi if left else None
        r_hi = right.rows.hi if right else None
        l_stats = _join_side_stats(
            plan, node.inputs[0], op.left_on, table_stats, l_hi
        ) if node.inputs else None
        r_stats = _join_side_stats(
            plan, node.inputs[1], op.right_on, table_stats, r_hi
        ) if len(node.inputs) > 1 else None
        from ..exec.joins import estimate_join_capacity

        # N:1 structural bound: a build side aggregated ON the join
        # keys has unique keys by construction (the eager-agg rewrite's
        # shape), so each probe row matches at most once — no NDV
        # estimate needed, and l_hi x r_hi would be absurdly loose.
        build = plan.nodes.get(node.inputs[1]) if len(node.inputs) > 1 \
            else None
        n_to_1 = (
            build is not None
            and isinstance(build.op, AggOp)
            and set(build.op.group_cols) == set(op.right_on)
        )
        capacity = None
        hi = None
        if l_hi is not None and r_hi is not None:
            if n_to_1:
                hi = l_hi + (r_hi if op.how in ("right", "outer") else 0)
                capacity = hi
            else:
                # Sound worst case: every probe row matches every build
                # row (+ unmatched emits for the outer flavors).
                hi = l_hi * max(r_hi, 1) + (l_hi + r_hi)
                if r_stats is not None and r_stats.ndv:
                    # NDV-estimate refinement (the runtime's own sizing
                    # model — an ESTIMATE; the kernel's overflow retry
                    # is the escape hatch, so the bound stays the min
                    # of both).
                    capacity = estimate_join_capacity(
                        l_hi, r_stats, l_stats, op.how
                    )
                    hi = min(hi, capacity)
                else:
                    capacity = estimate_join_capacity(
                        l_hi, r_stats, l_stats, op.how
                    )
        origin = (
            "sketch"
            if r_stats is not None and r_stats.origin == "sketch"
            else ("derived" if hi is not None else "none")
        )
        return NodeBound(
            Interval(0, hi), rb, join_capacity=capacity, origin=origin
        )

    if isinstance(op, LookupJoinOp):
        # Fused N:1 lookup: at most one build row per probe row.
        return NodeBound(first.rows.zero_lo(), rb, origin=first.origin)

    if isinstance(op, UnionOp):
        his = [b.rows.hi for b in in_bounds if b is not None]
        hi = None if (_unb(*his) or not his) else sum(his)
        return NodeBound(
            Interval(0, hi), rb,
            origin="derived" if hi is not None else "none",
        )

    if isinstance(op, BridgeSinkOp):
        wb = None
        if first.rows.hi is not None and first.row_bytes:
            # Rows payloads ship the relation's planes; agg-state
            # payloads ship carries (sum+count per mean, etc.) — the
            # x4 factor over-covers the carry expansion.
            wb = first.rows.hi * first.row_bytes * 4
        return NodeBound(
            Interval(first.rows.lo, first.rows.hi), first.row_bytes,
            wire_bytes=wb, origin=first.origin,
        )

    # Sinks and anything unknown: pass the first input through (sinks
    # don't change cardinality; unknown operators stay conservative).
    return NodeBound(first.rows.zero_lo(), rb or first.row_bytes,
                     origin=first.origin)


def _node_peak_bytes(node, bound, in_bounds, window_rows) -> int | None:
    """Rough per-node device-allocation demand (the ``bounds_device_
    budget_mb`` unit): staged window planes, aggregate group state, or
    join build+output buffers. Estimates, deliberately generous."""
    op = node.op
    if isinstance(op, MemorySourceOp):
        if bound.rows.hi is None or not bound.row_bytes:
            return None if bound.rows.hi is None else 0
        return min(bound.rows.hi, window_rows) * bound.row_bytes
    if isinstance(op, AggOp):
        groups = bound.groups
        if groups is None:
            groups = bound.rows.hi
        if groups is None:
            return None
        width = len(op.aggs) + len(op.group_cols) + 1
        return int(groups) * width * _AGG_SLOT_BYTES
    if isinstance(op, JoinOp):
        right = in_bounds[1] if len(in_bounds) > 1 else None
        build_hi = right.rows.hi if right is not None else None
        cap = bound.join_capacity
        if build_hi is None and cap is None:
            return None
        total = 0
        if build_hi is not None:
            total += build_hi * 16  # staged sorted keys + order
        if cap is not None:
            total += cap * _JOIN_ROW_BYTES
        return total
    return 0


def plan_bounds(plan: Plan, schemas, registry, table_stats=None, *,
                plan_name: str = "logical", bridge_rows=None,
                bridge_relations=None, safety: float | None = None,
                ) -> PlanResourceReport:
    """Abstract-interpret ``plan``: per-node bounds + predicted query
    totals. Never raises on missing statistics — sketch-less inputs
    propagate as unbounded (``None``) bounds.

    ``bridge_rows`` maps bridge id -> row bound for merge fragments
    (the distributed walk seeds it from the data side);
    ``bridge_relations`` is the verifier's bridge schema dict.
    """
    from ..config import get_flag

    if safety is None:
        safety = float(get_flag("bounds_safety"))
    window_rows = int(get_flag("window_rows"))
    max_groups_limit = int(get_flag("max_groups_limit"))
    report = PlanResourceReport(plan_name=plan_name, safety=safety)
    if not plan.nodes:
        report.rows_in_hi = report.rows_out_hi = 0
        report.bytes_staged_hi = report.wire_bytes_hi = 0
        report.peak_node_bytes_hi = report.cold_decode_bytes_hi = 0
        return report

    # Relation propagation: planner-built plans already carry per-node
    # relations (PlanNode.relation, maintained by the rule passes) —
    # reuse them so the always-on pass costs arithmetic, not a second
    # schema walk. Split/manual plans with gaps fall back to the
    # verifier's walk (the plan already verified clean in compile;
    # diagnostics here are dropped).
    from .verifier import _node_out_relation

    ctx = _Ctx(plan, schemas, registry, plan_name, bridge_relations)
    ctx.bridge_rows = dict(bridge_rows or {})
    order = _topo(plan)
    for nid in order:
        node = plan.nodes[nid]
        if node.relation is not None:
            ctx.rels[nid] = node.relation
        else:
            in_rels = [
                ctx.rels.get(i) for i in node.inputs if i in plan.nodes
            ]
            ctx.rels[nid] = _node_out_relation(ctx, node, in_rels)

    consumers: dict[int, int] = {}
    for n in plan.nodes.values():
        for i in n.inputs:
            consumers[i] = consumers.get(i, 0) + 1

    rows_in: int | None = 0
    bytes_staged: int | None = 0
    rows_out: int | None = 0
    wire: int | None = 0
    peak: int | None = 0
    cold_decode: int | None = 0
    for nid in order:
        node = plan.nodes[nid]
        in_bounds = [
            report.nodes.get(i) for i in node.inputs if i in plan.nodes
        ]
        b = _node_bound(plan, nid, node, in_bounds, ctx, table_stats,
                        max_groups_limit)
        report.nodes[nid] = b
        if b.groups is not None:
            report.agg_groups[nid] = b.groups
        if b.join_capacity is not None:
            report.join_capacity[nid] = b.join_capacity

        # -- ledger ----------------------------------------------------------
        # Any node's output may materialize host-side and re-stage in
        # windows for a downstream fragment (join outputs feeding an
        # aggregate are the common case), so EVERY node contributes its
        # row bound once; sources contribute once per consumer (pure-
        # scan fan-out re-executes the scan — the engine's materialize-
        # once rule exempts pure table scans) and join inputs once more
        # (the windowed device drivers re-stage the materialized probe
        # side and count its rows in ``stats.rows_in``). Over-counts
        # fused chains — a sound, deliberately simple model.
        op = node.op
        mult = (
            max(1, consumers.get(nid, 0))
            if isinstance(op, MemorySourceOp) else 1
        )
        events = [(b, mult)]
        if isinstance(op, JoinOp):
            events += [(s, 1) for s in in_bounds if s is not None]
        for side, m in events:
            if side.rows.hi is None:
                rows_in = bytes_staged = None
            else:
                if rows_in is not None:
                    rows_in += side.rows.hi * m
                if side.row_bytes is None:
                    # Rows known but the relation (hence the per-row
                    # width) is not: a silent 0-byte contribution would
                    # understate the total — degrade it to unbounded.
                    bytes_staged = None
                elif bytes_staged is not None:
                    bytes_staged += side.rows.hi * side.row_bytes * m
        # Cold-tier decode demand: each consumer's scan decodes the
        # source's cold windows afresh (same fan-out rule as staging);
        # zone maps can only skip BELOW this.
        if isinstance(op, MemorySourceOp) and b.cold_rows:
            if b.rows.hi is None or not b.row_bytes:
                cold_decode = None
            elif cold_decode is not None:
                cold_decode += (
                    min(b.cold_rows, b.rows.hi) * b.row_bytes * mult
                )
        if b.rows.hi is None:
            rows_out = None
        elif rows_out is not None:
            rows_out += b.rows.hi
        if b.wire_bytes is not None and wire is not None:
            wire += b.wire_bytes
        elif isinstance(op, BridgeSinkOp) and b.wire_bytes is None:
            wire = None
        pb = _node_peak_bytes(node, b, in_bounds, window_rows)
        if pb is None:
            peak = None
        elif peak is not None:
            peak = max(peak, pb)

    s = safety

    def scaled(v):
        return None if v is None else int(v * s)

    report.rows_in_hi = scaled(rows_in)
    report.rows_out_hi = scaled(rows_out)
    report.bytes_staged_hi = scaled(bytes_staged)
    report.wire_bytes_hi = scaled(wire)
    report.peak_node_bytes_hi = scaled(peak)
    report.cold_decode_bytes_hi = scaled(cold_decode)
    _budget_diagnostics(report, plan)
    return report


def _budget_diagnostics(report: PlanResourceReport, plan: Plan) -> None:
    """Budget checks (both flags default 0 = disabled, so the always-on
    pass adds no behavior until an operator opts in)."""
    from ..config import get_flag

    qb = float(get_flag("bounds_query_budget_mb")) * (1 << 20)
    if qb > 0 and report.bytes_staged_hi is not None \
            and report.bytes_staged_hi > qb:
        report.diagnostics.append(Diagnostic(
            code="resource-bound",
            message=(
                f"predicted staged bytes {report.bytes_staged_hi} "
                f"(x{report.safety} safety) exceed the per-query budget "
                f"{int(qb)} (bounds_query_budget_mb="
                f"{get_flag('bounds_query_budget_mb')}); the plan would "
                "be admitted only to fail or thrash at run time"
            ),
            plan=report.plan_name,
        ))
    db = float(get_flag("bounds_device_budget_mb")) * (1 << 20)
    if db > 0:
        for nid, b in report.nodes.items():
            node = plan.nodes.get(nid)
            if node is None:
                continue
            pb = _node_peak_bytes(
                node, b,
                [report.nodes.get(i) for i in node.inputs],
                int(get_flag("window_rows")),
            )
            if pb is not None and pb > db:
                report.diagnostics.append(Diagnostic(
                    code="resource-bound",
                    message=(
                        f"predicted device allocation {pb} bytes exceeds "
                        f"the device budget {int(db)} "
                        "(bounds_device_budget_mb)"
                    ),
                    node=nid, op=type(node.op).__name__,
                    plan=report.plan_name,
                ))


def check_plan_bounds(report: PlanResourceReport) -> None:
    """Raise :class:`PlanCheckError` on any error-severity bound
    diagnostic (compile-time rejection — the ``never an OOM at run
    time`` half of the soundness contract)."""
    errors = [
        d for d in report.diagnostics if d.severity == Severity.ERROR
    ]
    if errors:
        raise PlanCheckError(errors)


def presize_plan_aggs(plan: Plan, report: PlanResourceReport) -> int:
    """Grow ``AggOp.max_groups`` to the sketch-NDV group bound (x1.25
    HLL slack, next power of two, clamped to ``max_groups_limit``) —
    the same sizing rule ``push_agg_through_join`` applies to its
    partial agg, generalized to every aggregate whose group columns
    trace to sketches. Growth only: results are identical at any
    sufficient capacity, and a too-small capacity re-folds the whole
    table once per doubling rung. Returns the number of resized nodes.
    """
    import dataclasses

    from ..config import get_flag

    if not report.agg_groups:
        return 0
    limit = int(get_flag("max_groups_limit"))
    resized = 0
    for nid, groups in report.agg_groups.items():
        node = plan.nodes.get(nid)
        if node is None or not isinstance(node.op, AggOp):
            continue
        want = int(groups * 1.25) + 1
        sized = min(1 << (want - 1).bit_length(), limit)
        if sized > node.op.max_groups:
            node.op = dataclasses.replace(node.op, max_groups=sized)
            resized += 1
    return resized


# Report memo, mirroring the verifier's clean-verification cache: the
# compiler is deterministic, so two compiles of one script against one
# schema set, registry, and STATS SNAPSHOT produce plans with identical
# bounds (node ids included — the per-plan counter is deterministic).
# Repeat compiles — bench warm/timed rounds, dashboard refresh traffic
# between ingest batches — skip the walk entirely (~2µs hit), keeping
# the always-on pass inside the <5%-of-compile-span budget; any ingest
# changes the stats snapshot and naturally misses. Reports cache
# whether clean or over-budget: check_plan_bounds re-raises from the
# cached diagnostics either way.
_BOUNDS_CACHE: dict = {}
_BOUNDS_CACHE_MAX = 256
_BOUNDS_CACHE_LOCK = threading.Lock()


def _stats_key(table_stats: dict) -> str:
    """Cache key for a table_stats snapshot. ``repr`` is one C-level
    pass (a recursive freeze dominated the memo hit); it keys on dict
    ORDER as well as content, so a semantically-equal snapshot built in
    a different order merely misses the cache — never a wrong hit."""
    return repr(table_stats)


def apply_plan_bounds(plan: Plan, schemas, registry, table_stats=None, *,
                      plan_name: str = "logical",
                      script: str | None = None,
                      plan_params: tuple = ()) -> PlanResourceReport:
    """The compile-path entry point (``compile_pxl``): compute bounds,
    enforce budgets, pre-size aggregates, and attach the report to the
    plan (``plan.resource_report``) for the engine and broker.
    ``script`` enables the repeat-compile memo; ``plan_params`` must
    carry every compile input that shapes the plan beyond the script
    text (max_output_rows sizes the injected LimitOp that caps row/byte
    bounds, max_groups sizes AggOps) — same contract as
    ``check_script_plan``."""
    from ..config import get_flag, get_flags

    key = None
    if script is not None:
        try:
            key = (
                script,
                # items_tuple() is cached on the (immutable) Relation —
                # rebuilding ~20 canonical tables' tuples per compile
                # was the dominant cost of a memo hit.
                tuple(sorted(
                    (t, r.items_tuple())
                    for t, r in (schemas or {}).items()
                )),
                id(registry),
                _stats_key(table_stats or {}),
                plan_params,
                # Every flag the walk or its budget checks read.
                get_flags(
                    "bounds_safety", "bounds_query_budget_mb",
                    "bounds_device_budget_mb", "window_rows",
                    "max_groups_limit", "bounds_presize",
                ),
            )
            hash(key)
        except TypeError:
            key = None
    report = None
    if key is not None:
        with _BOUNDS_CACHE_LOCK:
            cached = _BOUNDS_CACHE.get(key)
        if cached is not None:
            report, _registry_pin = cached
    if report is None:
        report = plan_bounds(
            plan, schemas, registry, table_stats, plan_name=plan_name
        )
        if key is not None:
            with _BOUNDS_CACHE_LOCK:
                if len(_BOUNDS_CACHE) >= _BOUNDS_CACHE_MAX:
                    _BOUNDS_CACHE.pop(next(iter(_BOUNDS_CACHE)))
                # Pin the registry (id-keyed; a freed registry's id
                # could be recycled) — same discipline as _VERIFY_CACHE.
                _BOUNDS_CACHE[key] = (report, registry)
    check_plan_bounds(report)
    if bool(get_flag("bounds_presize")):
        presize_plan_aggs(plan, report)
    plan.resource_report = report
    return report


def distributed_bounds(dplan, schemas, registry, table_stats=None,
                       n_agents: int = 1) -> dict:
    """Bounds for a split plan: the data fragment per agent (each
    agent's shard is at most the whole table), the merge fragment with
    bridge row bounds seeded from the data side x ``n_agents``, and the
    total bridge wire bound. Attached as ``dplan.resource_report``."""
    split = dplan.split
    data = plan_bounds(
        split.before_blocking, schemas, registry, table_stats,
        plan_name="data",
    )
    bridge_rows: dict = {}
    bridge_rels: dict = {}
    for nid, n in split.before_blocking.nodes.items():
        if isinstance(n.op, BridgeSinkOp):
            b = data.nodes.get(nid)
            if b is not None and b.rows.hi is not None:
                bridge_rows[n.op.bridge_id] = b.rows.hi * max(n_agents, 1)
    wire = data.wire_bytes_hi
    if wire is not None:
        wire *= max(n_agents, 1)
    merge = plan_bounds(
        split.after_blocking, schemas, registry, table_stats,
        plan_name="merge", bridge_rows=bridge_rows,
    )
    # Fragment plans travel to the agents in dispatch messages; riding
    # the report on them gives each agent engine the same join-buffer
    # pre-sizing seam local queries get (engine reads
    # plan.resource_report).
    split.before_blocking.resource_report = data
    split.after_blocking.resource_report = merge
    report = {"data": data, "merge": merge, "wire_bytes_hi": wire}
    dplan.resource_report = report
    return report


def merged_cost(logical: PlanResourceReport | None,
                distributed: dict | None) -> dict | None:
    """The broker's ``predicted_cost``: the logical plan's envelope
    (scan work happens once across the shard set — each agent scans its
    SLICE, the union of which the logical bound covers, so no per-agent
    scaling here; ``distributed_bounds`` already scaled the wire bound
    by the agent count) with the distributed wire bound folded in."""
    if logical is None:
        return None
    cost = logical.cost()
    if distributed:
        # Unconditional: the logical plan's wire bound is a known 0 (no
        # BridgeSinkOps), but a distributed query ships bridge bytes —
        # an unknown wire bound (sketch-less data fragment) must stay
        # None per PlanResourceReport's contract, never that stale 0.
        cost["wire_bytes_hi"] = distributed.get("wire_bytes_hi")
        # Merge-side staging (bridge payload re-staging on the kelvin)
        # rides the safety factor; per-agent peak is the data fragment's.
        data = distributed.get("data")
        if data is not None and data.peak_node_bytes_hi is not None:
            cost["peak_node_bytes_hi"] = data.peak_node_bytes_hi
    return cost
