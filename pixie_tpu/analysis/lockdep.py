"""lockdep: opt-in runtime lock-order validation (Linux lockdep analog).

The static ``lock-order`` pxlint rule (analysis/lint.py) proves what it
can see — ``with self.<lock>`` nesting through a resolvable call graph.
Its blind spots are exactly where past concurrency bugs lived: locks in
containers (``Agent._streaming_merges[qid]["merge_lock"]``), bare
``.acquire()`` calls, duck-typed receivers, and cross-instance order
inversions. This module closes them at run time:

- ``enable()`` patches ``threading.Lock/RLock/Condition`` so every lock
  created afterwards is a thin wrapper that maintains a per-thread
  held-stack and a process-wide observed acquisition-order graph
  (edges: "held A when acquiring B", with the stack pair that first
  observed each edge).
- The FIRST blocking acquisition that would close a cycle in that graph
  raises :class:`LockOrderError` carrying both stack pairs — the
  would-deadlock is reported on the thread that would have completed
  it, before anything actually deadlocks. A non-reentrant lock
  re-acquired by its holder raises immediately too.
- ``RLock`` reentrancy is modeled (a re-acquire by the holder bumps a
  count, no edge); ``Condition.wait`` is modeled through the
  ``_release_save``/``_acquire_restore`` protocol the real Condition
  calls on its lock — while a thread waits, the condition's lock is
  NOT in its held set, and the wake-up re-acquire runs the normal
  edge/cycle bookkeeping (a wait-window inversion is still caught).
- Violations are ALSO recorded on ``state().violations``: product code
  that swallows exceptions (bus handlers) cannot swallow the verdict —
  the conftest wiring fails the run on any recorded violation.

Enable with the ``lockdep`` flag (env ``PIXIE_TPU_LOCKDEP=1``);
``run_tests.sh --locks`` runs the concurrency-heavy suites under it.
Off by default: ``threading.Lock`` stays the raw C type, zero overhead.

Scope notes: only locks CREATED while enabled are tracked (module-level
locks born at import time stay raw); identity is per lock instance, so
the graph never invents cross-instance aliasing, at the cost of only
catching inversions between the instances a run actually exercised.
"""

from __future__ import annotations

import sys
import threading

__all__ = [
    "LockOrderError",
    "LockDep",
    "enable",
    "disable",
    "enabled",
    "state",
    "active",
]

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


class LockOrderError(RuntimeError):
    """An acquisition would close a cycle in the observed lock-order
    graph (or re-acquire a held non-reentrant lock): a schedule exists
    in which the involved threads deadlock."""


def _stack(skip: int = 2, limit: int = 10) -> tuple:
    """Cheap stack capture: (filename, lineno, function) frames, no
    formatting (runs on every tracked acquire)."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    out = []
    while f is not None and len(out) < limit:
        co = f.f_code
        out.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    return tuple(out)


def _fmt_stack(stack: tuple, indent: str = "    ") -> str:
    return "\n".join(
        f"{indent}{fn}:{ln} in {fname}" for fn, ln, fname in stack
    ) or f"{indent}<no frames>"


class _Held:
    __slots__ = ("serial", "name", "count", "stack")

    def __init__(self, serial, name, stack):
        self.serial = serial
        self.name = name
        self.count = 1
        self.stack = stack


class LockDep:
    """Process-wide observed-order graph + per-thread held stacks."""

    def __init__(self):
        self._guard = _REAL_LOCK()  # protects the graph, never wrapped
        self._tls = threading.local()
        self._all_held: dict = {}  # ident -> held list (introspection)
        self._serial = 0
        # (held serial, acquired serial) -> {"held_stack", "acq_stack",
        # "held_name", "acq_name"} — first observation wins.
        self.edges: dict = {}
        self._adj: dict = {}  # serial -> set(serial)
        self.violations: list = []  # LockOrderError instances, in order
        self.tracked_locks = 0

    # -- wiring ---------------------------------------------------------------
    def new_serial(self, kind: str) -> tuple:
        with self._guard:
            self._serial += 1
            self.tracked_locks += 1
            serial = self._serial
        site = next(
            (
                (fn, ln)
                for fn, ln, _f in _stack(skip=3, limit=6)
                if "lockdep" not in fn and "threading" not in fn
                and "queue.py" not in fn
            ),
            ("?", 0),
        )
        return serial, f"{kind}#{serial}@{site[0].rsplit('/', 1)[-1]}:{site[1]}"

    def _held_list(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
            self._all_held[threading.get_ident()] = held
        return held

    def held(self, ident: int | None = None) -> list:
        """[(name, count)] snapshot of a thread's held locks (defaults
        to the calling thread) — test introspection."""
        if ident is None:
            held = self._held_list()
        else:
            held = self._all_held.get(ident, [])
        return [(h.name, h.count) for h in list(held)]

    # -- acquisition bookkeeping ----------------------------------------------
    def before_acquire(self, lock, blocking: bool) -> str:
        """Cycle check BEFORE the real (possibly blocking) acquire, so
        a would-deadlock raises instead of deadlocking. Returns the
        bookkeeping action for ``after_acquire``."""
        held = self._held_list()
        entry = next(
            (h for h in held if h.serial == lock._dep_serial), None
        )
        if entry is not None:
            if lock._dep_reentrant:
                return "reent"
            if not blocking:
                # A trylock probe of a lock this thread holds is legal
                # on a raw Lock (returns False) — never a deadlock.
                return "new"
            err = LockOrderError(
                f"self-deadlock: non-reentrant {lock._dep_name} "
                f"re-acquired by its holder\n"
                f"  first acquired at:\n{_fmt_stack(entry.stack)}\n"
                f"  re-acquired at:\n{_fmt_stack(_stack(3))}"
            )
            self.violations.append(err)
            raise err
        if not blocking or not held:
            return "new"  # trylocks can't deadlock; no held = no edge
        acq_stack = _stack(3)
        with self._guard:
            for h in held:
                key = (h.serial, lock._dep_serial)
                if key in self.edges:
                    continue
                cycle = self._find_path(lock._dep_serial, h.serial)
                if cycle is not None:
                    err = self._violation(h, lock, acq_stack, cycle)
                    self.violations.append(err)
                    raise err
                self.edges[key] = {
                    "held_name": h.name,
                    "acq_name": lock._dep_name,
                    "held_stack": h.stack,
                    "acq_stack": acq_stack,
                }
                self._adj.setdefault(h.serial, set()).add(
                    lock._dep_serial
                )
        return "new"

    def after_acquire(self, lock, action: str) -> None:
        held = self._held_list()
        if action == "reent":
            for h in held:
                if h.serial == lock._dep_serial:
                    h.count += 1
                    return
        held.append(_Held(lock._dep_serial, lock._dep_name, _stack(3)))

    def on_release(self, lock) -> None:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i].serial == lock._dep_serial:
                held[i].count -= 1
                if held[i].count <= 0:
                    del held[i]
                return
        # Released by a non-holder thread (legal for a raw Lock used as
        # a signal/handoff): the ACQUIRER's entry must not stay behind
        # — a stale entry would poison every later acquisition by that
        # thread with false edges and a false self-deadlock on its next
        # legitimate acquire. Best-effort cross-thread removal (GIL-
        # atomic list ops; the owner is blocked or gone, it cannot be
        # mid-acquire of this same serial).
        with self._guard:
            # Snapshot: _held_list registers new threads' lists in
            # _all_held without the guard (hot path) — iterating the
            # live dict could see it change size mid-iteration.
            for other in list(self._all_held.values()):
                for i in range(len(other) - 1, -1, -1):
                    if other[i].serial == lock._dep_serial:
                        other[i].count -= 1
                        if other[i].count <= 0:
                            del other[i]
                        return

    def wait_release(self, lock) -> int:
        """Condition.wait released the lock: drop it from the held set
        for the whole wait window. Returns the stashed recursion count
        for the wake-up restore."""
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i].serial == lock._dep_serial:
                count = held[i].count
                del held[i]
                return count
        return 1

    # -- lock factories -------------------------------------------------------
    # Also test-facing: unit tests validate a PRIVATE LockDep without
    # patching threading (so they can seed violations even while a
    # global lockdep — a PIXIE_TPU_LOCKDEP run — watches the process).
    def make_lock(self):
        return _DepLock(self, _REAL_LOCK(), "Lock")

    def make_rlock(self):
        return _DepRLock(self, _REAL_RLOCK(), "RLock")

    def make_condition(self, lock=None):
        if lock is None:
            lock = self.make_rlock()
        return _REAL_CONDITION(lock)

    # -- graph ----------------------------------------------------------------
    def _find_path(self, src: int, dst: int):
        """Edge path src -> ... -> dst in the observed graph (caller
        holds ``_guard``), or None."""
        if src == dst:
            return []
        parent: dict = {src: None}
        frontier = [src]
        while frontier:
            nxt = []
            for u in frontier:
                for v in self._adj.get(u, ()):
                    if v in parent:
                        continue
                    parent[v] = u
                    if v == dst:
                        path = [v]
                        while parent[path[-1]] is not None:
                            path.append(parent[path[-1]])
                        path.reverse()
                        return [
                            (path[i], path[i + 1])
                            for i in range(len(path) - 1)
                        ]
                    nxt.append(v)
            frontier = nxt
        return None

    def _violation(self, held_entry, lock, acq_stack, cycle_edges):
        lines = [
            f"lock-order cycle closed: acquiring {lock._dep_name} while "
            f"holding {held_entry.name}, but the observed graph already "
            f"orders {lock._dep_name} before {held_entry.name}:",
            f"  this thread holds {held_entry.name}, acquired at:",
            _fmt_stack(held_entry.stack),
            f"  and is acquiring {lock._dep_name} at:",
            _fmt_stack(acq_stack),
        ]
        for a, b in cycle_edges:
            ev = self.edges[(a, b)]
            lines.append(
                f"  prior observation {ev['held_name']} -> "
                f"{ev['acq_name']}: held at:"
            )
            lines.append(_fmt_stack(ev["held_stack"]))
            lines.append("    while acquiring at:")
            lines.append(_fmt_stack(ev["acq_stack"]))
        return LockOrderError("\n".join(lines))


# -- threading wrappers -------------------------------------------------------

class _DepLockBase:
    _dep_reentrant = False

    def __init__(self, state: LockDep, inner, kind: str):
        self._dep_state = state
        self._inner = inner
        self._dep_serial, self._dep_name = state.new_serial(kind)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        action = self._dep_state.before_acquire(self, blocking)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._dep_state.after_acquire(self, action)
        return ok

    def release(self):
        self._dep_state.on_release(self)
        self._inner.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<{type(self).__name__} {self._dep_name} of {self._inner!r}>"

    # Condition protocol: the real threading.Condition lifts these off
    # its lock when present — which is exactly where wait()'s
    # release/re-acquire becomes visible to the dependency tracker.
    def _is_owned(self):
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        return any(
            h.serial == self._dep_serial
            for h in self._dep_state._held_list()
        )

    def _release_save(self):
        count = self._dep_state.wait_release(self)
        inner_save = getattr(self._inner, "_release_save", None)
        if inner_save is not None:
            return (inner_save(), count)
        self._inner.release()
        return (None, count)

    def _acquire_restore(self, saved):
        inner_saved, count = saved
        inner_restore = getattr(self._inner, "_acquire_restore", None)
        # A wake-up re-acquire that closes a cycle must still COMPLETE
        # the restore before raising: Condition.wait calls this from a
        # finally block and the caller's `with cond:` will release —
        # raising with the lock un-reacquired would corrupt lock state
        # on top of reporting the violation (it is already recorded on
        # ``violations`` either way).
        try:
            action = self._dep_state.before_acquire(self, blocking=True)
            pending = None
        except LockOrderError as e:
            action, pending = "new", e
        if inner_restore is not None:
            inner_restore(inner_saved)
        else:
            self._inner.acquire()
        self._dep_state.after_acquire(self, action)
        if count > 1:
            for h in self._dep_state._held_list():
                if h.serial == self._dep_serial:
                    h.count = count
                    break
        if pending is not None:
            raise pending


class _DepLock(_DepLockBase):
    _dep_reentrant = False

    def locked(self):
        return self._inner.locked()


class _DepRLock(_DepLockBase):
    _dep_reentrant = True


_STATE: LockDep | None = None


def _make_lock():
    return _STATE.make_lock()


def _make_rlock():
    return _STATE.make_rlock()


def _make_condition(lock=None):
    return _STATE.make_condition(lock)


# -- enable / disable ---------------------------------------------------------

def enable() -> LockDep:
    """Patch ``threading.Lock/RLock/Condition``; locks created from now
    on are order-tracked. Idempotent; returns the active state."""
    global _STATE
    if _STATE is not None:
        return _STATE
    _STATE = LockDep()
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    return _STATE


def disable() -> LockDep | None:
    """Restore the raw lock types. Locks created while enabled keep
    their (now inert-ish) wrappers — bookkeeping on them continues
    against the final state object, which is returned for inspection."""
    global _STATE
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    st, _STATE = _STATE, None
    return st


def enabled() -> bool:
    return _STATE is not None


def state() -> LockDep | None:
    return _STATE


class active:
    """``with lockdep.active() as dep:`` — scoped enable for tests."""

    def __enter__(self) -> LockDep:
        self._was = enabled()
        return enable()

    def __exit__(self, *exc):
        if not self._was:
            disable()
