"""Self-observability gate (``run_tests.sh --obs``; runs in --tier1).

Compiles every bundled self-monitoring PxL script (px/slow_queries,
px/query_cost, px/agent_health, px/program_cost, px/bound_accuracy)
against the telemetry table schemas
(``ingest/schemas.py`` TELEMETRY_SCHEMAS) with the always-on plan
verifier active, then splits each through the DistributedPlanner (2
PEMs + 1 Kelvin) and runs the full distributed schema walk — the same
contract ``bench_check.py`` enforces for the performance shapes. A
schema drift in the TelemetryCollector's fold (services/telemetry.py)
surfaces HERE as an unbound-column diagnostic, before any cluster
runs it.
"""

from __future__ import annotations

import sys

#: The bundled self-monitoring scripts this gate covers.
OBS_SCRIPTS = (
    "px/slow_queries", "px/query_cost", "px/agent_health",
    # Device tier (PR 12): the program registry's __programs__ table
    # and the predicted-vs-observed calibration over __queries__.
    "px/program_cost", "px/bound_accuracy",
    # Storage tier: cluster-merged table health + per-agent watermark
    # lag over the __tables__ snapshots (TableStatsCollector fold).
    "px/table_health", "px/ingest_lag",
    # Result cache: hit/miss/stale/bypass/view rollup per script hash
    # over the __queries__ cache column (exec/result_cache.py).
    "px/cache_stats",
    # Profiling tier: attributed CPU from the __stacks__ ring — per
    # script/tenant burn, per-tenant phase split, and the diff-ready
    # folded-stack feed (ingest/profiler.py + exec/threadmap.py).
    "px/query_cpu", "px/tenant_cpu", "px/flame_diff",
    # Transport tier: per-topic-class bus throughput/lag/queue
    # high-water and request/reply RTT over the __bus__ snapshots
    # (services/busstats.py + BusStatsCollector fold).
    "px/bus_health", "px/rpc_latency",
)


def check_obs_scripts(verbose: bool = True) -> int:
    """Compile + verify every self-monitoring script; returns the
    number of failing scripts (0 = green)."""
    from ..ingest.schemas import TELEMETRY_SCHEMAS
    from ..planner import CompilerState, compile_pxl
    from ..planner.distributed import DistributedPlanner
    from ..planner.distributed.distributed_state import DistributedState
    from ..scripts import load_script
    from ..udf.registry import default_registry
    from .diagnostics import PlanCheckError, Severity
    from .verifier import verify_distributed_plan, verify_plan

    registry = default_registry()
    dstate = DistributedState.homogeneous(2, 1)
    schemas = dict(TELEMETRY_SCHEMAS)
    failures = 0
    for name in OBS_SCRIPTS:
        try:
            pxl = load_script(name).pxl
            state = CompilerState(schemas=dict(schemas), registry=registry)
            compiled = compile_pxl(pxl, state)
            diags = verify_plan(compiled.plan, schemas, registry)
            dplan = DistributedPlanner(registry).plan(compiled.plan, dstate)
            diags += verify_distributed_plan(dplan, schemas, registry)
        except (PlanCheckError, Exception) as e:  # noqa: BLE001 — gate
            failures += 1
            if verbose:
                print(f"[obs] {name}: FAIL\n{e}", file=sys.stderr)
            continue
        errors = [d for d in diags if d.severity == Severity.ERROR]
        if errors:
            failures += 1
            if verbose:
                print(f"[obs] {name}: FAIL", file=sys.stderr)
                for d in errors:
                    print(f"  {d.render()}", file=sys.stderr)
        elif verbose:
            print(
                f"[obs] {name}: ok ({len(compiled.plan.nodes)} logical "
                f"nodes, {len(dplan.split.before_blocking.nodes)}+"
                f"{len(dplan.split.after_blocking.nodes)} split)",
                file=sys.stderr,
            )
    return failures


def main() -> int:
    failures = check_obs_scripts()
    if failures:
        print(f"[obs] {failures} self-monitoring script(s) failed "
              "verification", file=sys.stderr)
        return 1
    print(f"[obs] all {len(OBS_SCRIPTS)} self-monitoring scripts verify "
          "clean against the telemetry schemas", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
