"""Plan verifier: static schema/dtype/topology checks on compiled plans.

Always-on pass between ``planner/compiler.py`` and ``exec/engine.py``
(and, for distributed queries, between ``DistributedPlanner.plan`` and
the broker's dispatch). Walks the operator DAG in topological order
doing exactly the schema propagation the engine's fragment binder will
do at execution time — but eagerly, over every node, with diagnostics
that carry plan-node provenance instead of a device-side shape error
three windows into a fold.

Checks:

- **Topology**: input arity per operator, references to missing nodes,
  unreachable/cyclic nodes, and outputs nobody consumes (every
  non-sink node must feed something — a dangling fragment output is a
  plan bug, not dead code, because the rule pass already pruned).
- **Column binding**: every ``ColumnRef`` in every Map/Filter/Agg/Join
  expression resolves in the propagated input relation.
- **Dtypes**: every ``FuncCall`` resolves an overload in the UDF
  registry under the implicit-cast lattice (``udf/udf.py``); filter
  predicates are BOOLEAN; host-dict UDF non-dict args are literals
  (the binder's compile-time-constant rule).
- **UDA definitions**: referenced UDAs have init/update/merge/finalize
  callables of the segmented-UDA arity (init(G); update(carry, gids,
  mask, *args); merge(a, b); finalize(carry)).
- **Distributed invariants** (``verify_distributed_plan``): every
  bridge sink pairs with exactly one bridge source and a BridgeSpec;
  agg-state bridges feed a finalize AggOp (and only they do); the data
  fragment holds no blocking operators; the dispatch agent set matches
  the merge fragment's expected set (``verify_dispatch_sets``).

Semantic types ride the registry definitions (``semantic_type`` on
ScalarUDFDef/UDADef); relations carry dtypes only, so semantic checking
happens where it is representable: overload resolution + the cast
lattice. See docs/ANALYSIS.md.
"""

from __future__ import annotations

import inspect
import threading

from ..exec.plan import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    ColumnRef,
    EmptySourceOp,
    FilterOp,
    FuncCall,
    JoinOp,
    LimitOp,
    Literal,
    LookupJoinOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    ResultSinkOp,
    TableSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from ..types.dtypes import DataType
from ..types.relation import Relation
from ..udf.udf import Executor, SignatureError
from .diagnostics import Diagnostic, PlanCheckError, Severity

# Terminal operators: legitimately have no consumer.
_SINK_OPS = (ResultSinkOp, TableSinkOp, OTelExportSinkOp, BridgeSinkOp)

# Expected input arity per operator class (None = any >= 1).
_ARITY = {
    MemorySourceOp: 0,
    UDTFSourceOp: 0,
    EmptySourceOp: 0,
    BridgeSourceOp: 0,
    MapOp: 1,
    FilterOp: 1,
    AggOp: 1,
    LimitOp: 1,
    LookupJoinOp: 1,
    ResultSinkOp: 1,
    TableSinkOp: 1,
    OTelExportSinkOp: 1,
    BridgeSinkOp: 1,
    JoinOp: 2,
    UnionOp: None,
}


class _Ctx:
    """One verification walk: diagnostics + per-node relations."""

    def __init__(self, plan: Plan, schemas, registry, plan_name: str,
                 bridge_relations=None):
        self.plan = plan
        self.schemas = schemas or {}
        self.registry = registry
        self.plan_name = plan_name
        self.bridge_relations = bridge_relations or {}
        self.diags: list[Diagnostic] = []
        self.rels: dict[int, Relation | None] = {}
        self._seen: set = set()
        self._checked_udas: set = set()

    def add(self, code: str, message: str, node=None,
            severity=Severity.ERROR):
        op = None
        if node is not None and node in self.plan.nodes:
            op = type(self.plan.nodes[node].op).__name__
        key = (code, message, node, self.plan_name)
        if key in self._seen:
            return
        self._seen.add(key)
        self.diags.append(Diagnostic(
            code=code, message=message, severity=severity,
            node=node, op=op, plan=self.plan_name,
        ))


def _callable_arity_ok(fn, n_expected: int) -> bool:
    """True when ``fn`` accepts exactly ``n_expected`` positional args
    (or cannot be introspected — builtins/partials get the benefit of
    the doubt; the goal is catching hand-written UDA protocol slips)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True
    req = opt = 0
    var = False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.default is p.empty:
                req += 1
            else:
                opt += 1
        elif p.kind == p.VAR_POSITIONAL:
            var = True
    if var:
        return n_expected >= req
    return req <= n_expected <= req + opt


# UDADef -> tuple of protocol-violation messages (() = clean). UDADefs
# are frozen and live as long as their registry; caching here keeps the
# inspect.signature cost out of the per-query verify pass (it dominated
# the walk before: ~70% of verify time).
_UDA_PROTOCOL_CACHE: dict = {}


def _uda_protocol_errors(uda) -> tuple:
    try:
        cached = _UDA_PROTOCOL_CACHE.get(uda)
    except TypeError:
        cached = None  # unhashable exotic def: check uncached
    if cached is not None:
        return cached
    msgs = []
    expect = (
        ("init", uda.init, 1),
        ("update", uda.update, 3 + len(uda.arg_types)),
        ("merge", uda.merge, 2),
        ("finalize", uda.finalize, 1),
    )
    for part, fn, n in expect:
        if not callable(fn):
            msgs.append(f"UDA {uda.name!r} {part} is not callable")
        elif not _callable_arity_ok(fn, n):
            msgs.append(
                f"UDA {uda.name!r} {part} must accept {n} positional "
                f"argument(s) ({part} of a segmented UDA over "
                f"{len(uda.arg_types)} arg column(s))"
            )
    out = tuple(msgs)
    try:
        _UDA_PROTOCOL_CACHE[uda] = out
    except TypeError:
        pass
    return out


def _check_uda_def(ctx: _Ctx, uda, node) -> None:
    """Segmented-UDA protocol arity: init(G); update(carry, gids, mask,
    *args); merge(a, b); finalize(carry) (udf/udf.py UDADef)."""
    key = (uda.name, uda.arg_types)
    if key in ctx._checked_udas:
        return
    ctx._checked_udas.add(key)
    for msg in _uda_protocol_errors(uda):
        ctx.add("uda-arity", msg, node)


def _expr_type(ctx: _Ctx, expr, rel: Relation, node) -> DataType | None:
    """Propagated dtype of ``expr`` against ``rel``; None (after adding
    a diagnostic) when the expression cannot bind. Mirrors
    ``exec/expr.bind_expr``'s type resolution without dictionaries."""
    if isinstance(expr, ColumnRef):
        if not rel.has_column(expr.name):
            ctx.add(
                "unbound-column",
                f"column {expr.name!r} is not in the input relation "
                f"{rel!r}",
                node,
            )
            return None
        return rel.col_type(expr.name)
    if isinstance(expr, Literal):
        return expr.dtype
    if isinstance(expr, FuncCall):
        arg_types = [_expr_type(ctx, a, rel, node) for a in expr.args]
        if any(t is None for t in arg_types):
            return None  # upstream diagnostics already explain it
        try:
            udf = ctx.registry.get_scalar(expr.name, arg_types)
        except SignatureError as e:
            ctx.add(
                "udf-signature",
                f"{e} (in expression {expr!r})",
                node,
            )
            return None
        if udf.executor == Executor.HOST_DICT:
            for i, a in enumerate(expr.args):
                if i != udf.dict_arg and not isinstance(a, Literal):
                    ctx.add(
                        "udf-signature",
                        f"{udf.name}: argument {i} must be a literal "
                        "(host-dict UDFs take compile-time-constant "
                        f"args; in expression {expr!r})",
                        node,
                    )
                    return None
        return udf.return_type
    ctx.add("bad-expression", f"cannot type expression {expr!r}", node)
    return None


def _agg_out_relation(ctx: _Ctx, op: AggOp, in_rel: Relation, node):
    """Relation of an AggOp's finalized output, checking group cols,
    agg arg binding, UDA overload resolution and UDA definitions."""
    items = []
    ok = True
    for c in op.group_cols:
        if not in_rel.has_column(c):
            ctx.add(
                "unbound-column",
                f"group column {c!r} is not in the input relation "
                f"{in_rel!r}",
                node,
            )
            ok = False
        else:
            items.append((c, in_rel.col_type(c)))
    for ae in op.aggs:
        arg_types = [_expr_type(ctx, a, in_rel, node) for a in ae.args]
        if any(t is None for t in arg_types):
            ok = False
            continue
        try:
            uda = ctx.registry.get_uda(ae.uda_name, arg_types)
        except SignatureError as e:
            ctx.add(
                "udf-signature",
                f"{e} (aggregate {ae.out_name} = "
                f"{ae.uda_name}({', '.join(map(repr, ae.args))}))",
                node,
            )
            ok = False
            continue
        _check_uda_def(ctx, uda, node)
        items.append((ae.out_name, uda.return_type))
    if not ok:
        return None
    try:
        return Relation(items)
    except ValueError as e:
        ctx.add("duplicate-column", str(e), node)
        return None


def _node_out_relation(ctx: _Ctx, node, in_rels):
    """Output relation of one node given its input relations (None
    entries = unknown upstream, checks involving them are skipped)."""
    op = node.op
    nid = node.id

    if isinstance(op, MemorySourceOp):
        rel = ctx.schemas.get(op.table)
        if rel is None:
            ctx.add(
                "unknown-table",
                f"no table named {op.table!r} in the compile schemas",
                nid,
            )
            return None
        if op.columns is not None:
            missing = [c for c in op.columns if not rel.has_column(c)]
            if missing:
                ctx.add(
                    "unbound-column",
                    f"source columns {missing!r} are not in table "
                    f"{op.table!r} ({rel!r})",
                    nid,
                )
                return None
            return rel.select(op.columns)
        return rel

    if isinstance(op, UDTFSourceOp):
        if ctx.registry is None or not ctx.registry.has_udtf(op.name):
            ctx.add("unknown-udtf", f"no UDTF named {op.name!r}", nid)
            return None
        return Relation(list(ctx.registry.get_udtf(op.name).relation))

    if isinstance(op, EmptySourceOp):
        return Relation(list(op.relation_items))

    if isinstance(op, BridgeSourceOp):
        return ctx.bridge_relations.get(op.bridge_id)

    in_rel = in_rels[0] if in_rels else None

    if isinstance(op, MapOp):
        if in_rel is None:
            return None
        items = []
        ok = True
        for name, e in op.exprs:
            dt = _expr_type(ctx, e, in_rel, nid)
            if dt is None:
                ok = False
            else:
                items.append((name, dt))
        if not ok:
            return None
        try:
            return Relation(items)
        except ValueError as e:
            ctx.add("duplicate-column", str(e), nid)
            return None

    if isinstance(op, FilterOp):
        if in_rel is None:
            return None
        dt = _expr_type(ctx, op.predicate, in_rel, nid)
        if dt is not None and dt != DataType.BOOLEAN:
            ctx.add(
                "dtype-mismatch",
                f"filter predicate {op.predicate!r} has type {dt.name}, "
                "want BOOLEAN",
                nid,
            )
        return in_rel

    if isinstance(op, AggOp):
        if in_rel is None:
            return None
        return _agg_out_relation(ctx, op, in_rel, nid)

    if isinstance(op, JoinOp):
        left, right = (in_rels + [None, None])[:2]
        if len(op.left_on) != len(op.right_on) or not op.left_on:
            ctx.add(
                "join-keys",
                f"join key lists differ in length or are empty "
                f"(left_on={op.left_on!r}, right_on={op.right_on!r})",
                nid,
            )
            return None
        for side, rel, cols in (("left", left, op.left_on),
                                ("right", right, op.right_on)):
            if rel is None:
                continue
            for c in cols:
                if not rel.has_column(c):
                    ctx.add(
                        "unbound-column",
                        f"{side} join key {c!r} is not in the {side} "
                        f"input relation {rel!r}",
                        nid,
                    )
        if left is None or right is None:
            return None
        # Mirror exec/joins._join_out_schema: all left columns, then
        # right value columns with collision suffixing.
        return left.merge(
            right.select(
                [c for c in right.column_names if c not in op.right_on]
            ),
            suffix=op.suffix,
        )

    if isinstance(op, UnionOp):
        known = [r for r in in_rels if r is not None]
        if not known:
            return None
        first = known[0]
        for r in known[1:]:
            if tuple(r.column_names) != tuple(first.column_names):
                ctx.add(
                    "union-schema",
                    f"union inputs must share a schema "
                    f"({first!r} vs {r!r})",
                    nid,
                )
                return None
            for c in first.column_names:
                if r.col_type(c) != first.col_type(c):
                    ctx.add(
                        "union-schema",
                        f"union input dtypes differ on {c!r} "
                        f"({first.col_type(c).name} vs "
                        f"{r.col_type(c).name})",
                        nid,
                        severity=Severity.WARNING,
                    )
        return first

    if isinstance(op, LookupJoinOp):
        # Engine-internal (never planner-emitted); keep the schema walk
        # alive if one ever shows up in a verified plan.
        if in_rel is None:
            return None
        return Relation(
            list(in_rel.items()) + [(n, dt) for n, dt, _p in op.out_cols]
        )

    if isinstance(op, LimitOp):
        if op.n < 0:
            ctx.add("bad-limit", f"negative limit {op.n}", nid)
        return in_rel

    if isinstance(op, _SINK_OPS):
        return in_rel

    ctx.add(
        "unknown-operator",
        f"unsupported operator {type(op).__name__}",
        nid,
        severity=Severity.WARNING,
    )
    return None


def _topo(plan: Plan) -> list:
    """plan.topo_order(), but tolerant of inputs referencing missing
    nodes (the verifier must diagnose malformed plans, not crash)."""
    seen: set = set()
    out: list = []

    def visit(nid):
        if nid in seen or nid not in plan.nodes:
            return
        seen.add(nid)
        for i in plan.nodes[nid].inputs:
            visit(i)
        out.append(nid)

    for s in plan.sinks():
        visit(s)
    return out


def _walk(ctx: _Ctx, require_consumers: bool = True) -> None:
    plan = ctx.plan
    consumers: dict[int, int] = {}
    for n in plan.nodes.values():
        for i in n.inputs:
            consumers[i] = consumers.get(i, 0) + 1
            if i not in plan.nodes:
                ctx.add(
                    "dangling-input",
                    f"input node {i} does not exist in the plan",
                    n.id,
                )

    order = _topo(plan)
    placed = set(order)
    for nid in plan.nodes:
        if nid not in placed:
            ctx.add(
                "unreachable-node",
                "node is unreachable from every sink (cycle or "
                "orphaned subgraph)",
                nid,
            )

    done: set = set()
    for nid in order:
        node = plan.nodes[nid]
        for i in node.inputs:
            if i in plan.nodes and i not in done:
                ctx.add(
                    "plan-cycle",
                    f"node depends on {i} which does not precede it "
                    "(cycle in the operator DAG)",
                    nid,
                )
        done.add(nid)

        want = _ARITY.get(type(node.op), None)
        n_in = len([i for i in node.inputs if i in plan.nodes])
        if want is None:
            if isinstance(node.op, UnionOp) and n_in < 1:
                ctx.add("bad-arity", "union has no inputs", nid)
        elif n_in != want:
            ctx.add(
                "bad-arity",
                f"{type(node.op).__name__} takes {want} input(s), "
                f"has {n_in}",
                nid,
            )
            ctx.rels[nid] = None
            continue

        in_rels = [ctx.rels.get(i) for i in node.inputs if i in plan.nodes]
        ctx.rels[nid] = _node_out_relation(ctx, node, in_rels)

        if (
            require_consumers
            and not consumers.get(nid)
            and not isinstance(node.op, _SINK_OPS)
        ):
            ctx.add(
                "dangling-output",
                f"{type(node.op).__name__} output has no consumer "
                "(fragment output feeds no sink)",
                nid,
            )


def verify_plan(plan: Plan, schemas, registry, *, plan_name: str = "logical",
                bridge_relations=None,
                require_consumers: bool = True) -> list[Diagnostic]:
    """Verify one operator DAG; returns diagnostics (empty = clean).

    ``schemas`` maps table name -> Relation (the CompilerState view);
    ``bridge_relations`` maps bridge id -> payload Relation for plans
    that start from BridgeSourceOps (merge fragments).
    """
    ctx = _Ctx(plan, schemas, registry, plan_name, bridge_relations)
    if plan.nodes:
        _walk(ctx, require_consumers=require_consumers)
    return ctx.diags


def check_plan(plan: Plan, schemas, registry, **kw) -> None:
    """``verify_plan`` raising ``PlanCheckError`` on any error finding."""
    diags = verify_plan(plan, schemas, registry, **kw)
    errors = [d for d in diags if d.severity == Severity.ERROR]
    if errors:
        raise PlanCheckError(errors)


# Clean-verification memo, keyed on (script, schemas, registry): the
# compiler is deterministic at the TYPE level — two compiles of one
# script against one schema set and registry produce plans that differ
# at most in folded literal VALUES (now_ns time arithmetic), never in
# column names, dtypes, or topology, so their verification outcome is
# identical. Only CLEAN results cache (a failing script re-verifies to
# rebuild its diagnostics); repeat compiles of one script — bench's
# warm/timed/AB rounds, dashboard refresh traffic — skip the walk,
# keeping the always-on pass inside the <5%-of-compile-span budget.
_VERIFY_CACHE: dict = {}
_VERIFY_CACHE_MAX = 256
_VERIFY_CACHE_LOCK = threading.Lock()


def check_script_plan(plan: Plan, script: str, schemas, registry,
                      plan_params: tuple = ()) -> None:
    """``check_plan`` memoized by (script, schemas, registry,
    plan_params). ``plan_params`` must carry every compile input that
    changes plan VALUES the verifier checks (max_output_rows shapes the
    injected LimitOp.n the bad-limit check reads) — type-level inputs
    are covered by script+schemas+registry."""
    try:
        key = (
            script,
            # items_tuple(): cached on the immutable Relation (see
            # apply_plan_bounds' key — same memo-hit cost argument).
            tuple(sorted(
                (t, r.items_tuple()) for t, r in schemas.items()
            )),
            id(registry),
            plan_params,
        )
        hash(key)
    except TypeError:
        check_plan(plan, schemas, registry)
        return
    # Locked: brokers/agents compile on their dispatcher threads, and
    # an unguarded evict-while-insert can raise "dict changed size".
    with _VERIFY_CACHE_LOCK:
        if key in _VERIFY_CACHE:
            return
    check_plan(plan, schemas, registry)
    with _VERIFY_CACHE_LOCK:
        if len(_VERIFY_CACHE) >= _VERIFY_CACHE_MAX:
            _VERIFY_CACHE.pop(next(iter(_VERIFY_CACHE)))
        # Pin the registry: a freed registry's id could be recycled by
        # a different one with different signatures.
        _VERIFY_CACHE[key] = registry


# -- distributed plans --------------------------------------------------------

def verify_distributed_plan(dplan, schemas=None,
                            registry=None) -> list[Diagnostic]:
    """Invariants of a split/assigned DistributedPlan.

    Structural checks always run; when ``schemas`` + ``registry`` are
    given the data and merge fragments also get the full schema walk,
    with each bridge's payload relation propagated from the data side
    so merge-side expressions bind against real schemas.
    """
    from ..planner.distributed.splitter import AGG_STATE_MERGE, ROW_GATHER

    split = dplan.split
    before, after = split.before_blocking, split.after_blocking
    diags: list[Diagnostic] = []

    def add(code, message, node=None, plan_name=""):
        diags.append(Diagnostic(
            code=code, message=message, node=node,
            op=(
                type(
                    (before if plan_name == "data" else after)
                    .nodes[node].op
                ).__name__
                if node is not None else None
            ),
            plan=plan_name,
        ))

    spec_ids = [b.bridge_id for b in split.bridges]
    if len(set(spec_ids)) != len(spec_ids):
        add("dangling-bridge", f"duplicate bridge specs: {spec_ids!r}")
    sinks_by_bridge: dict[int, int] = {}
    for nid, n in before.nodes.items():
        if isinstance(n.op, BridgeSinkOp):
            if n.op.bridge_id in sinks_by_bridge:
                add(
                    "dangling-bridge",
                    f"bridge {n.op.bridge_id} has two sinks",
                    nid, "data",
                )
            sinks_by_bridge[n.op.bridge_id] = nid
    sources_by_bridge: dict[int, int] = {}
    for nid, n in after.nodes.items():
        if isinstance(n.op, BridgeSourceOp):
            if n.op.bridge_id in sources_by_bridge:
                add(
                    "dangling-bridge",
                    f"bridge {n.op.bridge_id} has two sources",
                    nid, "merge",
                )
            sources_by_bridge[n.op.bridge_id] = nid

    for bid in set(spec_ids) | set(sinks_by_bridge) | set(sources_by_bridge):
        missing = []
        if bid not in spec_ids:
            missing.append("spec")
        if bid not in sinks_by_bridge:
            missing.append("GRPC-sink analog (BridgeSinkOp)")
        if bid not in sources_by_bridge:
            missing.append("GRPC-source analog (BridgeSourceOp)")
        if missing:
            add(
                "dangling-bridge",
                f"bridge {bid} is missing its {' + '.join(missing)}",
                sinks_by_bridge.get(bid, sources_by_bridge.get(bid)),
                "data" if bid in sinks_by_bridge else "merge",
            )

    # The data fragment runs shard-local: no blocking operators (full/
    # finalize aggs, joins, unions, result sinks — splitter.h:75).
    # Exception (pushdown_union_agg): a UnionOp whose sole consumer
    # chain through row-wise ops ends at a PARTIAL AggOp — it unions
    # shard-LOCAL rows only, and the partial agg's carry merge makes
    # the per-agent interleaving unobservable downstream.
    before_consumers: dict[int, list] = {}
    for n in before.nodes.values():
        for i in n.inputs:
            before_consumers.setdefault(i, []).append(n.id)

    def _feeds_partial_agg(union_nid: int) -> bool:
        cur = union_nid
        while True:
            outs = before_consumers.get(cur, [])
            if len(outs) != 1:
                return False
            nxt = before.nodes[outs[0]].op
            if isinstance(nxt, AggOp):
                return nxt.mode == "partial"
            if not isinstance(nxt, (MapOp, FilterOp)):
                return False
            cur = outs[0]

    for nid, n in before.nodes.items():
        op = n.op
        blocking = (
            isinstance(op, (JoinOp, ResultSinkOp))
            or (isinstance(op, UnionOp) and not _feeds_partial_agg(nid))
            or (isinstance(op, AggOp) and op.mode != "partial")
        )
        if blocking:
            add(
                "fragment-invariant",
                f"blocking operator {type(op).__name__}"
                f"{' (mode=' + op.mode + ')' if isinstance(op, AggOp) else ''}"
                " in the shard-local data fragment",
                nid, "data",
            )
    # Every data-fragment output must reach a bridge (dangling outputs
    # would compute rows nobody ships).
    for nid in before.sinks():
        if not isinstance(before.nodes[nid].op, _SINK_OPS):
            add(
                "dangling-output",
                f"{type(before.nodes[nid].op).__name__} output has no "
                "consumer in the data fragment",
                nid, "data",
            )
    for nid in after.sinks():
        if not isinstance(after.nodes[nid].op, _SINK_OPS):
            add(
                "dangling-output",
                f"{type(after.nodes[nid].op).__name__} output has no "
                "consumer in the merge fragment",
                nid, "merge",
            )

    # Agg bridges must feed a finalize AggOp (the engine's
    # merge_agg_bridge contract) and finalize aggs must be fed by one.
    after_consumers: dict[int, list] = {}
    for n in after.nodes.values():
        for i in n.inputs:
            after_consumers.setdefault(i, []).append(n.id)
    kinds = {b.bridge_id: b.kind for b in split.bridges}
    for bid, src_nid in sources_by_bridge.items():
        kind = kinds.get(bid)
        feeds = [
            after.nodes[c] for c in after_consumers.get(src_nid, [])
        ]
        feeds_finalize = any(
            isinstance(c.op, AggOp) and c.op.mode == "finalize"
            for c in feeds
        )
        if kind == AGG_STATE_MERGE and not feeds_finalize:
            add(
                "bridge-kind",
                f"agg-state bridge {bid} must feed its finalize AggOp "
                "(merge would receive carries with no merge/finalize "
                "step)",
                src_nid, "merge",
            )
        if kind == ROW_GATHER and feeds_finalize:
            add(
                "bridge-kind",
                f"row-gather bridge {bid} feeds a finalize AggOp, "
                "which expects mergeable agg carries, not rows",
                src_nid, "merge",
            )

    if schemas is not None and registry is not None:
        ctx = _Ctx(before, schemas, registry, "data")
        if before.nodes:
            _walk(ctx)
        bridge_rels: dict[int, Relation | None] = {}
        for bid, sink_nid in sinks_by_bridge.items():
            producer = before.nodes[sink_nid].inputs
            producer = producer[0] if producer else None
            if producer is None or producer not in before.nodes:
                continue
            pnode = before.nodes[producer]
            if (
                kinds.get(bid) == AGG_STATE_MERGE
                and isinstance(pnode.op, AggOp)
                and pnode.inputs
            ):
                # Carry payload: the finalize half re-binds group cols
                # and agg args against the PRE-agg relation.
                bridge_rels[bid] = ctx.rels.get(pnode.inputs[0])
            else:
                bridge_rels[bid] = ctx.rels.get(producer)
        diags += ctx.diags
        diags += verify_plan(
            after, schemas, registry, plan_name="merge",
            bridge_relations=bridge_rels,
        )
    return diags


def check_distributed_plan(dplan, schemas=None, registry=None) -> None:
    errors = [
        d for d in verify_distributed_plan(dplan, schemas, registry)
        if d.severity == Severity.ERROR
    ]
    if errors:
        raise PlanCheckError(errors)


def verify_dispatch_sets(dplan, merge_expected, dispatched,
                         merge_agent=None) -> list[Diagnostic]:
    """The broker's dispatch set vs the merge fragment's expected set.

    ``merge_expected`` is the agent list shipped in the merge dispatch
    (what the merge waits for); ``dispatched`` the agents actually sent
    an execute fragment. Any asymmetry means either a merge that waits
    forever for an agent that was never dispatched, or an agent whose
    bridge payload the merge will drop on the floor.
    """
    diags: list[Diagnostic] = []
    exp, got = set(merge_expected), set(dispatched)
    plan_set = set(dplan.data_agent_ids)
    if exp != got:
        diags.append(Diagnostic(
            code="dispatch-set-mismatch",
            message=(
                "merge expected-agent set != dispatched set: "
                f"merge waits for {sorted(exp - got)!r} never "
                f"dispatched; dispatched {sorted(got - exp)!r} the "
                "merge will ignore"
            ),
            plan="distributed",
        ))
    if got != plan_set:
        diags.append(Diagnostic(
            code="dispatch-set-mismatch",
            message=(
                f"dispatched set {sorted(got)!r} != planned data-agent "
                f"set {sorted(plan_set)!r}"
            ),
            plan="distributed",
        ))
    if merge_agent is not None and dplan.kelvin_agent_ids and \
            merge_agent not in dplan.kelvin_agent_ids:
        diags.append(Diagnostic(
            code="dispatch-set-mismatch",
            message=(
                f"merge agent {merge_agent!r} is not one of the "
                f"planned kelvins {list(dplan.kelvin_agent_ids)!r}"
            ),
            plan="distributed",
        ))
    return diags
