"""pxlint: a reusable AST-rule engine with JAX/concurrency-aware rules.

One lint framework for the tree (``tools/pxlint.py`` drives it; the
metrics-name gate of ``run_tests.sh --lint-metrics`` is a rule here
too). Rules are pure AST visitors — no imports of the linted modules,
so linting never executes device code.

Rules:

- ``host-sync-hot-path``: no ``block_until_ready`` / ``.item()`` /
  ``np.asarray`` / ``jax.device_get`` inside registered hot regions
  (the per-window execution path). A host sync per window serializes
  the pipelined executor (docs/EXECUTOR.md) and on the TPU tunnel
  costs a full round trip per call. Hot regions are *registered* by
  the modules that own them via a module-level
  ``PXLINT_HOT_REGIONS = ("path-suffix:qualname-glob", ...)``
  assignment (``exec/pipeline.py`` registers the window path).
- ``jit-recompile-hazard``: a Python ``if``/``while`` on a traced
  argument inside a ``@jax.jit`` function — every distinct runtime
  value forces a retrace+recompile (closure constants and shape/dtype
  attributes are static and stay allowed).
- ``thread-shared-state``: an attribute mutated both from a thread
  context (``Thread(target=...)`` entry methods and bus
  ``subscribe`` callbacks, transitively through same-class calls) and
  from a public method, with at least one side not holding a lock.
- ``lock-order``: whole-tree interprocedural lock-acquisition graph —
  ``with self.<lock>`` nesting tracked transitively through same-class
  ``self.m()`` calls and cross-module ``self.attr.m()`` calls (attr
  types inferred from ``self.attr = ClassName(...)`` assignments); any
  cycle in the (class, lock-attr) order graph is a potential deadlock,
  reported with both acquisition chains. Re-acquiring a non-reentrant
  lock already held on the path (directly or through a call chain) is
  a certain self-deadlock and is reported too.
- ``request-from-handler``: a bus ``subscribe`` callback that
  (transitively through same-class calls and nested defs) issues a
  blocking ``bus.request``/``RemoteBus.request`` — the dispatcher
  thread blocks for the reply, and if the responder (or the reply
  inbox) is served by this same dispatcher the handler self-deadlocks
  until the timeout (the PR 3 netbus-race shape).
- ``metrics-naming``: metric names registered via
  ``.counter/.gauge/.histogram`` must match ``^pixie_[a-z0-9_]+$``
  and must not end in a Prometheus histogram-series suffix.

Suppression: append ``# pxlint: disable=<rule>[,<rule>...]`` to the
offending line (or the line directly above). Known-legacy findings live
in ``pixie_tpu/analysis/baseline.json``; see docs/ANALYSIS.md for the
baseline workflow.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import os
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(r"#\s*pxlint:\s*disable=([A-Za-z0-9_,\- ]+)")
_HOT_REGION_ATTR = "PXLINT_HOT_REGIONS"
# Metric-name policy — the single source for both the static rule here
# and the dynamic registration checks in tests/test_metrics_lint.py.
METRIC_RE = re.compile(r"^pixie_[a-z0-9_]+$")
RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str  # enclosing qualname ("<module>" at top level)

    def key(self) -> tuple:
        """Baseline identity: line numbers drift, these don't."""
        return (self.rule, self.path, self.symbol, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message} " \
               f"[{self.symbol}]"


class FileCtx:
    """One parsed file: AST with parent/qualname info + suppressions."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppress: dict[int, set] = {}
        for i, line in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self.suppress[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
        self._qual: dict[int, str] = {}  # id(node) -> qualname
        self._annotate(self.tree, [])

    def _annotate(self, node, stack):
        for child in ast.iter_child_nodes(node):
            self._qual[id(child)] = ".".join(stack) or "<module>"
            named = isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            )
            if named:
                stack.append(child.name)
            self._annotate(child, stack)
            if named:
                stack.pop()

    def qualname(self, node) -> str:
        """Qualname of the scope CONTAINING node (for a def node, its
        own dotted name)."""
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            outer = self._qual.get(id(node), "<module>")
            return node.name if outer == "<module>" else \
                f"{outer}.{node.name}"
        return self._qual.get(id(node), "<module>")

    def suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            rules = self.suppress.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


#: Modules known to register hot regions, parsed even when the lint
#: path set does not include them (linting a single edited file must
#: not silently turn the host-sync rule into a no-op).
_KNOWN_REGISTRARS = ("pixie_tpu/exec/pipeline.py",)


def _hot_regions(ctxs, repo_root=None) -> list[tuple[str, str]]:
    """Collect (path-suffix, qualname-glob) hot-region registrations
    from every scanned module's ``PXLINT_HOT_REGIONS`` assignment,
    plus the known registrar modules under ``repo_root``."""
    ctxs = list(ctxs)
    scanned = {ctx.relpath for ctx in ctxs}
    if repo_root:
        for rel in _KNOWN_REGISTRARS:
            if rel in scanned:
                continue
            path = os.path.join(repo_root, rel)
            try:
                with open(path) as f:
                    ctxs.append(FileCtx(path, rel, f.read()))
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue
    regions: list[tuple[str, str]] = []
    for ctx in ctxs:
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == _HOT_REGION_ATTR
                for t in node.targets
            ):
                continue
            try:
                entries = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            for e in entries:
                if isinstance(e, str) and ":" in e:
                    suffix, glob = e.split(":", 1)
                    regions.append((suffix, glob))
    return regions


# -- rule: host-sync-hot-path -------------------------------------------------

class HostSyncHotPathRule:
    name = "host-sync-hot-path"
    description = (
        "no block_until_ready/.item()/np.asarray/jax.device_get inside "
        "registered hot regions (PXLINT_HOT_REGIONS)"
    )

    def __init__(self):
        self.regions: list[tuple[str, str]] = []

    def prepare(self, ctxs, repo_root=None):
        self.regions = _hot_regions(ctxs, repo_root)

    def _hot_globs(self, relpath: str) -> list[str]:
        # Anchored at a path-component boundary: "somexec/engine.py"
        # must not match the "exec/engine.py" registration.
        return [
            g for suffix, g in self.regions
            if relpath == suffix or relpath.endswith("/" + suffix)
        ]

    def check(self, ctx: FileCtx):
        globs = self._hot_globs(ctx.relpath)
        if not globs:
            return
        scanned: list[str] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            qn = ctx.qualname(node)
            if not any(fnmatch.fnmatch(qn, g) for g in globs):
                continue
            # A nested def inside an already-scanned hot function was
            # covered by the enclosing scan (ast.walk descends into
            # nested bodies) — scanning it again would double-report.
            if any(qn.startswith(outer + ".") for outer in scanned):
                continue
            scanned.append(qn)
            yield from self._check_fn(ctx, node, qn)

    def _check_fn(self, ctx, fn, qn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            msg = None
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready":
                    msg = "block_until_ready() forces a device sync"
                elif f.attr == "item" and not node.args:
                    msg = ".item() forces a device-to-host readback"
                elif (
                    f.attr == "asarray"
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy", "onp")
                ):
                    msg = ("np.asarray() on a device value forces a "
                           "host readback")
                elif (
                    f.attr == "device_get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax"
                ):
                    msg = "jax.device_get() forces a host readback"
            if msg:
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=node.lineno,
                    message=f"{msg} inside hot region",
                    symbol=qn,
                )


# -- rule: jit-recompile-hazard -----------------------------------------------

_SAFE_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
_SAFE_CALLS = frozenset({"len", "isinstance", "type"})


def _is_jit_decorator(dec) -> bool:
    """@jax.jit / @jit / @partial(jax.jit, ...) / @functools.partial(jit)."""

    def is_jit_name(n):
        return (isinstance(n, ast.Name) and n.id == "jit") or (
            isinstance(n, ast.Attribute) and n.attr == "jit"
        )

    if is_jit_name(dec):
        return True
    if isinstance(dec, ast.Call):
        if is_jit_name(dec.func):
            return True
        f = dec.func
        if (
            (isinstance(f, ast.Name) and f.id == "partial")
            or (isinstance(f, ast.Attribute) and f.attr == "partial")
        ) and dec.args:
            return is_jit_name(dec.args[0])
    return False


def _traced_name_refs(expr, params: set) -> list:
    """Param Name nodes referenced in ``expr`` outside static contexts
    (len/isinstance calls, shape/ndim/dtype/size attributes)."""
    hits: list = []

    def walk(e):
        if isinstance(e, ast.Call):
            f = e.func
            if isinstance(f, ast.Name) and f.id in _SAFE_CALLS:
                return
        if isinstance(e, ast.Attribute) and e.attr in _SAFE_ATTRS:
            return
        if isinstance(e, ast.Name) and e.id in params:
            hits.append(e)
            return
        for child in ast.iter_child_nodes(e):
            walk(child)

    walk(expr)
    return hits


class JitRecompileHazardRule:
    name = "jit-recompile-hazard"
    description = (
        "python if/while on a traced argument inside a @jax.jit "
        "function recompiles per distinct value"
    )

    def prepare(self, ctxs, repo_root=None):
        pass

    def check(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_is_jit_decorator(d) for d in node.decorator_list):
                continue
            params = {
                a.arg
                for a in (
                    node.args.posonlyargs + node.args.args
                    + node.args.kwonlyargs
                )
                if a.arg != "self"
            }
            qn = ctx.qualname(node)
            for inner in ast.walk(node):
                if isinstance(inner, (ast.If, ast.While)):
                    for ref in _traced_name_refs(inner.test, params):
                        yield Finding(
                            rule=self.name,
                            path=ctx.relpath,
                            line=inner.lineno,
                            message=(
                                f"python branch on traced argument "
                                f"{ref.id!r} in jitted function — each "
                                "distinct value retraces and recompiles"
                            ),
                            symbol=qn,
                        )


# -- rule: thread-shared-state ------------------------------------------------

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"})

#: Method calls that mutate their receiver in place (self.x.append(...)
#: is a write to self.x just as much as self.x = ... is).
_MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear",
})


@dataclass
class _AttrWrite:
    attr: str
    line: int
    locked: bool
    method: str


@dataclass
class _ClassInfo:
    name: str
    qualname: str
    methods: dict = field(default_factory=dict)  # name -> FunctionDef
    lock_attrs: set = field(default_factory=set)
    thread_entries: set = field(default_factory=set)  # method names
    # method -> nested defs used as thread targets/callbacks
    nested_thread_bodies: dict = field(default_factory=dict)
    calls: dict = field(default_factory=dict)  # method -> {self.m called}
    writes: dict = field(default_factory=dict)  # method -> [_AttrWrite]


def _self_attr(node) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _class_lock_attrs(cls: ast.ClassDef) -> set:
    """``self.X`` attributes assigned a Lock/RLock/Condition/Semaphore
    anywhere in the class body (shared by thread-shared-state and
    blocking-call-under-lock)."""
    out: set = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            vf = node.value.func
            ctor = (
                vf.attr if isinstance(vf, ast.Attribute)
                else vf.id if isinstance(vf, ast.Name) else None
            )
            if ctor in _LOCK_CTORS:
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        out.add(a)
    return out


class ThreadSharedStateRule:
    name = "thread-shared-state"
    description = (
        "attribute mutated from both a thread context (Thread target / "
        "bus subscribe callback) and a public method without a lock"
    )

    def prepare(self, ctxs, repo_root=None):
        pass

    def check(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    # -- per-class analysis ---------------------------------------------------
    def _check_class(self, ctx: FileCtx, cls: ast.ClassDef):
        info = _ClassInfo(name=cls.name, qualname=ctx.qualname(cls))
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods[item.name] = item
        # Pass 1: lock attrs from EVERY method, so a lock assigned in a
        # textually-later method (e.g. __init__ not first in the class
        # body) still counts when earlier methods' writes are scanned.
        info.lock_attrs = _class_lock_attrs(cls)
        for name, fn in info.methods.items():
            self._scan_method(info, name, fn)

        # Each Thread target / bus subscription runs on its OWN
        # dispatcher thread (services/msgbus.py Subscription), so two
        # different entry roots = two concurrent threads. Compute, per
        # method, which entry roots can reach it through same-class
        # self.m() calls.
        method_roots: dict[str, set] = {}
        for entry in info.thread_entries:
            seen = {entry}
            frontier = [entry]
            while frontier:
                m = frontier.pop()
                method_roots.setdefault(m, set()).add(entry)
                for callee in info.calls.get(m, ()):
                    if callee in info.methods and callee not in seen:
                        seen.add(callee)
                        frontier.append(callee)

        threaded = set(method_roots)
        public = {
            m for m in info.methods
            if not m.startswith("_") and m not in threaded
        }

        by_attr: dict[str, dict] = {}
        for m, writes in info.writes.items():
            side = (
                "thread" if m in threaded
                else "public" if m in public
                else None
            )
            if side is None:
                continue
            for w in writes:
                by_attr.setdefault(
                    w.attr, {"thread": [], "public": []}
                )[side].append(w)

        for attr, sides in sorted(by_attr.items()):
            tw, pw = sides["thread"], sides["public"]
            t_unlocked = [w for w in tw if not w.locked]
            p_unlocked = [w for w in pw if not w.locked]
            t_roots = set()
            for w in tw:
                t_roots |= method_roots.get(w.method, set())
            # Hazard 1: written by a thread AND a public (caller-thread)
            # method, with at least one side not holding a lock.
            hazard = tw and pw and (t_unlocked or p_unlocked)
            detail = "thread context and public method"
            # Hazard 2: unlocked writes reachable from two DIFFERENT
            # thread entries — two dispatcher threads racing each other.
            if not hazard and len(t_roots) >= 2 and t_unlocked:
                hazard = True
                detail = "two different dispatcher threads"
            if not hazard:
                continue
            t_m = sorted({x.method for x in tw})
            p_m = sorted({x.method for x in pw})
            writers = ", ".join(t_m + p_m)
            # One finding PER unlocked write: suppressing one site (the
            # engine applies `# pxlint: disable` per line) must not
            # hide a future unlocked write to the same attribute.
            for w in t_unlocked + p_unlocked:
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=w.line,
                    message=(
                        f"attribute self.{attr} is written from "
                        f"{detail} ({writers}) with at least one write "
                        "not holding a lock"
                    ),
                    symbol=f"{info.qualname}.{w.method}",
                )

    def _scan_method(self, info: _ClassInfo, name: str, fn):
        writes: list[_AttrWrite] = []
        calls: set = set()
        nested_defs = {
            n.name: n for n in ast.walk(fn)
            if isinstance(n, ast.FunctionDef) and n is not fn
        }
        thread_nested: set = set()

        def register_target(arg):
            a = _self_attr(arg)
            if a is not None:
                info.thread_entries.add(a)
            elif isinstance(arg, ast.Name) and arg.id in nested_defs:
                thread_nested.add(arg.id)
            elif isinstance(arg, ast.Call):
                # Wrapped handler: subscribe(t, guard(self._on_x)) /
                # subscribe(t, _guarded(_on_execute)) — the wrapped
                # callable still runs on the dispatcher thread.
                for inner in list(arg.args) + [
                    kw.value for kw in arg.keywords
                ]:
                    register_target(inner)

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                # threading.Thread(target=...) / Thread(target=...)
                is_thread = (
                    isinstance(f, ast.Name) and f.id == "Thread"
                ) or (isinstance(f, ast.Attribute) and f.attr == "Thread")
                if is_thread:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            register_target(kw.value)
                # bus.subscribe(topic, self._on_x): callbacks run on the
                # subscription's dispatcher thread (services/msgbus.py)
                if isinstance(f, ast.Attribute) and f.attr == "subscribe":
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        register_target(arg)
                # self.m(...) intra-class call graph
                a = _self_attr(f)
                if a is not None:
                    calls.add(a)

        self._collect_writes(info, name, fn, writes, under_lock=False)
        info.calls[name] = calls
        info.writes[name] = writes
        for nd in thread_nested:
            # Writes inside a nested thread body count as thread-side.
            nwrites: list = []
            self._collect_writes(
                info, name, nested_defs[nd], nwrites, under_lock=False
            )
            key = f"{name}.<{nd}>"
            info.writes[key] = nwrites
            info.calls[key] = set()
            info.nested_thread_bodies[key] = nd
            # the nested body may call self.m too
            for node in ast.walk(nested_defs[nd]):
                if isinstance(node, ast.Call):
                    a = _self_attr(node.func)
                    if a is not None:
                        info.calls[key].add(a)
            info.thread_entries.add(key)

    def _collect_writes(self, info, method, node, out, under_lock):
        """Record self.X writes, tracking `with self.<lock>:` scopes."""
        if isinstance(node, ast.With):
            locked = under_lock or any(
                _self_attr(item.context_expr) in info.lock_attrs
                or (
                    isinstance(item.context_expr, ast.Call)
                    and _self_attr(item.context_expr.func) in info.lock_attrs
                )
                for item in node.items
            )
            for child in node.body:
                self._collect_writes(info, method, child, out, locked)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._note_write(info, method, t, node.lineno, under_lock,
                                 out)
        elif isinstance(node, ast.AugAssign):
            self._note_write(info, method, node.target, node.lineno,
                             under_lock, out)
        elif isinstance(node, ast.Call):
            # Container mutation anywhere (statement or expression):
            # self.x.append(...) / h = self.x.pop(k, None) / ...
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATOR_METHODS
            ):
                self._note_write(info, method, f.value, node.lineno,
                                 under_lock, out)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs handled separately
            self._collect_writes(info, method, child, out, under_lock)

    def _note_write(self, info, method, target, line, locked, out):
        attr = _self_attr(target)
        # Subscript writes (self.x[k] = v) count against self.x too.
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
        if attr is None or attr in info.lock_attrs:
            return
        out.append(_AttrWrite(attr=attr, line=line, locked=locked,
                              method=method))


# -- rule: lock-order ---------------------------------------------------------

#: Lock constructors that are reentrant for the acquiring thread. A bare
#: ``Condition()`` wraps a fresh RLock; ``Condition(self._lock)`` takes
#: the wrapped lock's reentrancy (aliased in ``_LockClassInfo``).
_REENTRANT_CTORS = frozenset({"RLock"})


@dataclass
class _LockClassInfo:
    name: str
    relpath: str
    qualname: str
    bases: list = field(default_factory=list)  # simple base-class names
    lock_ctors: dict = field(default_factory=dict)  # attr -> ctor name
    # Condition(self._x) shares _x's underlying lock: both attrs are ONE
    # lock node in the order graph.
    lock_aliases: dict = field(default_factory=dict)  # attr -> attr
    attr_types: dict = field(default_factory=dict)  # attr -> class name
    # method -> [(held, kind, data, line)]: held = ((attr, line), ...)
    # for this method's enclosing `with self.<attr>` scopes; kind is
    # "acquire" (data = attr) or "call" (data = ("self", m) |
    # ("attr", (attr, m))).
    methods: dict = field(default_factory=dict)


def _parse_lock_class(ctx: "FileCtx", cls: ast.ClassDef) -> _LockClassInfo:
    info = _LockClassInfo(
        name=cls.name, relpath=ctx.relpath, qualname=ctx.qualname(cls),
    )
    for b in cls.bases:
        if isinstance(b, ast.Name):
            info.bases.append(b.id)
        elif isinstance(b, ast.Attribute):
            info.bases.append(b.attr)
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        vf = node.value.func
        ctor = (
            vf.attr if isinstance(vf, ast.Attribute)
            else vf.id if isinstance(vf, ast.Name) else None
        )
        if ctor is None:
            continue
        for t in node.targets:
            a = _self_attr(t)
            if a is None:
                continue
            if ctor in _LOCK_CTORS:
                info.lock_ctors[a] = ctor
                if ctor == "Condition" and node.value.args:
                    wrapped = _self_attr(node.value.args[0])
                    if wrapped is not None:
                        info.lock_aliases[a] = wrapped
            elif ctor[:1].isupper():
                # Type inference seed: self.X = ClassName(...).
                info.attr_types.setdefault(a, ctor)
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            actions: list = []
            _scan_lock_actions(item, (), actions)
            info.methods[item.name] = actions
            _infer_param_attr_types(item, info.attr_types)
    return info


def _ann_name(ann) -> str | None:
    """Simple class name from an annotation node ('Engine',
    'exec.engine.Engine', '"Engine"', 'Engine | None')."""
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split(".")[-1].strip() or None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        return _ann_name(ann.left) or _ann_name(ann.right)
    if isinstance(ann, ast.Subscript):  # Optional[X]
        return _ann_name(ann.slice)
    return None


def _infer_param_attr_types(fn, attr_types: dict) -> None:
    """``self.X = param`` where the param carries a class annotation
    (and ``self.X: Cls = ...``) seed the cross-module call resolution —
    the ``self.bus = bus`` constructor-injection idiom."""
    params = {}
    args = fn.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        name = _ann_name(a.annotation) if a.annotation is not None else None
        if name is not None and name[:1].isupper():
            params[a.arg] = name
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
            t = params.get(node.value.id)
            if t is None:
                continue
            for tgt in node.targets:
                a = _self_attr(tgt)
                if a is not None:
                    attr_types.setdefault(a, t)
        elif isinstance(node, ast.AnnAssign):
            a = _self_attr(node.target)
            t = _ann_name(node.annotation)
            if a is not None and t is not None and t[:1].isupper():
                attr_types.setdefault(a, t)


def _scan_lock_actions(node, held, out):
    """Collect acquire/call actions with the enclosing held-lock set.
    ``held`` is a tuple of (attr, line) for ``with self.<attr>`` scopes
    currently open in THIS method (filtered to real lock attrs later)."""
    if isinstance(node, ast.With):
        inner = held
        for item in node.items:
            _scan_lock_actions(item.context_expr, inner, out)
            a = _self_attr(item.context_expr)
            if a is not None:
                out.append((inner, "acquire", a, item.context_expr.lineno))
                inner = inner + ((a, item.context_expr.lineno),)
        for child in node.body:
            _scan_lock_actions(child, inner, out)
        return
    if isinstance(node, ast.Call):
        f = node.func
        a = _self_attr(f)
        if a is not None:
            out.append((held, "call", ("self", a), node.lineno))
        elif (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute)
        ):
            recv = _self_attr(f.value)
            if recv is not None:
                out.append(
                    (held, "call", ("attr", (recv, f.attr)), node.lineno)
                )
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue  # nested bodies run on a later call, not here
        _scan_lock_actions(child, held, out)


class LockOrderRule:
    """Whole-program lock-order verification.

    Nodes are (defining class, lock attr); an edge A -> B is recorded
    whenever code may acquire B while holding A — directly via nested
    ``with self.<lock>`` scopes, or transitively through same-class
    ``self.m()`` and typed cross-class ``self.attr.m()`` calls. A cycle
    means two threads taking the locks in opposite orders can deadlock;
    the diagnostic carries one acquisition chain per edge. Re-acquiring
    a held non-reentrant lock is reported as a certain self-deadlock.

    Static blind spots (covered by the runtime validator,
    ``analysis/lockdep.py``): locks stored in containers/locals,
    ``.acquire()`` calls without a ``with``, duck-typed receivers, and
    cross-instance aliasing of one class's lock attr."""

    name = "lock-order"
    description = (
        "cycle in the interprocedural (class, lock-attr) acquisition-"
        "order graph, or a held non-reentrant lock re-acquired on the "
        "same path — a potential deadlock"
    )

    def __init__(self):
        self._by_path: dict = {}

    # -- whole-program analysis (prepare) -------------------------------------
    def prepare(self, ctxs, repo_root=None):
        classes: dict[str, list] = {}
        for ctx in ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    classes.setdefault(node.name, []).append(
                        _parse_lock_class(ctx, node)
                    )
        self._classes = classes
        self._lockmap_memo: dict = {}
        self._methodmap_memo: dict = {}
        self._reach_memo: dict = {}
        edges: dict = {}  # (hkey, akey) -> evidence dict
        self_deadlocks: dict = {}  # dedup key -> finding
        for infos in classes.values():
            for info in infos:
                self._class_edges(info, edges, self_deadlocks)
        findings = list(self_deadlocks.values())
        findings.extend(self._cycle_findings(edges))
        self._by_path = {}
        for f in findings:
            self._by_path.setdefault(f.path, []).append(f)

    def check(self, ctx: FileCtx):
        yield from self._by_path.get(ctx.relpath, ())

    # -- class/attr resolution ------------------------------------------------
    def _resolve_class(self, name: str):
        infos = self._classes.get(name)
        # Ambiguous simple names (two modules, one class name) stay
        # unresolved: merging them would invent cross-module edges.
        return infos[0] if infos and len(infos) == 1 else None

    def _mro(self, info: _LockClassInfo) -> list:
        out, seen = [], set()
        frontier = [info]
        while frontier:
            c = frontier.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            out.append(c)
            for b in c.bases:
                bc = self._resolve_class(b)
                if bc is not None:
                    frontier.append(bc)
        return out

    def _lockmap(self, info: _LockClassInfo) -> dict:
        """attr -> ((relpath, class, attr) node key, reentrant) over the
        class and its resolvable bases, own declarations first.
        ``Condition(self._x)`` aliases to ``_x``'s node (the two attrs
        are ONE underlying lock) — resolved through the MRO, so a
        subclass Condition wrapping a base-class lock still collapses
        onto the base lock's node and takes ITS reentrancy."""
        key = (info.relpath, info.qualname)
        hit = self._lockmap_memo.get(key)
        if hit is not None:
            return hit
        # attr -> (defining class, ctor, alias target) — own-first.
        decl: dict = {}
        for c in self._mro(info):
            for a, ctor in c.lock_ctors.items():
                if a not in decl:
                    decl[a] = (c, ctor, c.lock_aliases.get(a))
        out: dict = {}
        for a, (c, ctor, alias) in decl.items():
            if alias is not None and alias in decl:
                tc, tctor, _ = decl[alias]
                out[a] = (
                    (tc.relpath, tc.name, alias),
                    tctor in _REENTRANT_CTORS
                    or tctor == "Condition",  # bare Condition = RLock
                )
            else:
                # Own node. A bare Condition() wraps a fresh RLock
                # (reentrant); a Condition over an UNKNOWN lock (ctor
                # param, container) cannot be analyzed — treat as
                # reentrant so it never false-positives a self-nest.
                reentrant = (
                    ctor in _REENTRANT_CTORS or ctor == "Condition"
                )
                out[a] = ((c.relpath, c.name, a), reentrant)
        self._lockmap_memo[key] = out
        return out

    def _methodmap(self, info: _LockClassInfo) -> dict:
        key = (info.relpath, info.qualname)
        hit = self._methodmap_memo.get(key)
        if hit is not None:
            return hit
        out: dict = {}
        for c in self._mro(info):
            for m, actions in c.methods.items():
                out.setdefault(m, (c, actions))
        self._methodmap_memo[key] = out
        return out

    def _attr_type(self, info: _LockClassInfo, attr: str):
        for c in self._mro(info):
            t = c.attr_types.get(attr)
            if t is not None:
                return self._resolve_class(t)
        return None

    def _resolve_call(self, info: _LockClassInfo, data):
        """(receiver class info, method name) for a call action, or
        None when the receiver/method cannot be resolved statically."""
        kind, payload = data
        if kind == "self":
            return (info, payload) if payload in self._methodmap(info) \
                else None
        attr, m = payload
        target = self._attr_type(info, attr)
        if target is not None and m in self._methodmap(target):
            return (target, m)
        return None

    # -- interprocedural acquisition summaries --------------------------------
    def _reach(self, info: _LockClassInfo, method: str,
               stack: frozenset = frozenset()) -> dict:
        """{lock node key: (reentrant, chain)} of every lock a call to
        ``info.method`` may acquire, transitively. ``chain`` is a tuple
        of "Class.method" steps ending at the acquiring method."""
        key = (info.relpath, info.qualname, method)
        hit = self._reach_memo.get(key)
        if hit is not None:
            return hit
        if key in stack:
            return {}
        stack = stack | {key}
        entry = self._methodmap(info).get(method)
        if entry is None:
            return {}
        owner, actions = entry
        lm = self._lockmap(info)
        out: dict = {}
        step = f"{info.name}.{method}"
        for _held, kind, data, _line in actions:
            if kind == "acquire":
                node = lm.get(data)
                if node is not None:
                    out.setdefault(node[0], (node[1], (step,)))
            else:
                callee = self._resolve_call(info, data)
                if callee is None:
                    continue
                for k, (reent, chain) in self._reach(
                    callee[0], callee[1], stack
                ).items():
                    if k not in out and len(chain) < 8:
                        out[k] = (reent, (step,) + chain)
        self._reach_memo[key] = out
        return out

    # -- edge + finding generation --------------------------------------------
    @staticmethod
    def _lock_name(node_key) -> str:
        return f"{node_key[1]}.{node_key[2]}"

    def _class_edges(self, info, edges, self_deadlocks):
        lm = self._lockmap(info)
        for method, (owner, actions) in self._methodmap(info).items():
            symbol = f"{info.qualname}.{method}"
            for held, kind, data, line in actions:
                held_nodes = [
                    (lm[a][0], hl) for a, hl in held if a in lm
                ]
                if not held_nodes:
                    continue
                if kind == "acquire":
                    node = lm.get(data)
                    targets = (
                        {node[0]: (node[1], (f"{info.name}.{method}",))}
                        if node is not None else {}
                    )
                else:
                    callee = self._resolve_call(info, data)
                    if callee is None:
                        continue
                    targets = {
                        k: (reent,
                            (f"{info.name}.{method} -> "
                             f"{callee[0].name}.{callee[1]}",) + ch[1:])
                        for k, (reent, ch) in self._reach(
                            callee[0], callee[1]
                        ).items()
                    }
                for k, (reent, chain) in targets.items():
                    for h, _hline in held_nodes:
                        if h == k:
                            if reent:
                                continue
                            dk = (owner.relpath, symbol, k)
                            if dk not in self_deadlocks:
                                self_deadlocks[dk] = Finding(
                                    rule=self.name,
                                    path=owner.relpath,
                                    line=line,
                                    message=(
                                        f"non-reentrant lock "
                                        f"{self._lock_name(k)} re-"
                                        f"acquired while held (via "
                                        f"{' -> '.join(chain)}) — "
                                        "certain self-deadlock"
                                    ),
                                    symbol=symbol,
                                )
                            continue
                        edges.setdefault((h, k), {
                            "path": owner.relpath, "line": line,
                            "symbol": symbol, "chain": chain,
                        })

    def _cycle_findings(self, edges) -> list:
        adj: dict = {}
        for (h, k) in edges:
            adj.setdefault(h, set()).add(k)
        findings = []
        for cycle in self._cycles(adj):
            # Canonical rotation: start at the smallest node so the
            # finding (and its baseline key) is order-stable.
            i = cycle.index(min(cycle))
            cycle = cycle[i:] + cycle[:i]
            names = [self._lock_name(n) for n in cycle]
            parts = []
            for j, n in enumerate(cycle):
                nxt = cycle[(j + 1) % len(cycle)]
                ev = edges[(n, nxt)]
                parts.append(
                    f"{self._lock_name(n)} -> {self._lock_name(nxt)} "
                    f"via {' -> '.join(ev['chain'])}"
                )
            first = edges[(cycle[0], cycle[1 % len(cycle)])]
            findings.append(Finding(
                rule=self.name,
                path=first["path"],
                line=first["line"],
                message=(
                    "potential deadlock: lock-order cycle "
                    + " -> ".join(names + [names[0]])
                    + " [" + "; ".join(parts) + "]"
                ),
                symbol=first["symbol"],
            ))
        findings.sort(key=lambda f: (f.path, f.message))
        return findings

    @staticmethod
    def _cycles(adj) -> list:
        """One shortest cycle per strongly-connected component (Tarjan;
        fixing any edge of it re-exposes whatever remains)."""
        index: dict = {}
        low: dict = {}
        on: set = set()
        order: list = []
        sccs: list = []
        counter = [0]

        def strongconnect(v):
            work = [(v, iter(sorted(adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            order.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        order.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = order.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(set(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        cycles = []
        for scc in sccs:
            # BFS from the smallest node back to itself inside the SCC.
            start = min(scc)
            parent = {start: None}
            frontier = [start]
            found = None
            while frontier and found is None:
                nxt = []
                for u in frontier:
                    for w in sorted(adj.get(u, ())):
                        if w == start:
                            found = u
                            break
                        if w in scc and w not in parent:
                            parent[w] = u
                            nxt.append(w)
                    if found is not None:
                        break
                frontier = nxt
            if found is None:
                continue
            path = [found]
            while parent[path[-1]] is not None:
                path.append(parent[path[-1]])
            cycles.append(list(reversed(path)))
        return cycles


# -- rule: request-from-handler -----------------------------------------------

def _bus_recv_name(f) -> str | None:
    """Receiver of a ``.request`` call when it looks like a message bus
    (``*bus`` / ``RemoteBus``) — shared with blocking-call-under-lock."""
    if not (isinstance(f, ast.Attribute) and f.attr == "request"):
        return None
    recv = f.value
    name = (
        recv.id if isinstance(recv, ast.Name)
        else recv.attr if isinstance(recv, ast.Attribute)
        else None
    )
    if name is not None and (
        name == "RemoteBus" or name.lstrip("_").endswith("bus")
    ):
        return name
    return None


class RequestFromHandlerRule:
    """A bus ``subscribe`` callback that issues a blocking
    ``bus.request`` (directly, through same-class ``self.m()`` calls,
    or through nested defs of the registering method). The callback
    runs on its subscription's dispatcher thread; ``request`` blocks
    that thread up to its timeout — and when the responder (or the
    one-shot reply inbox) is dispatched by the same thread, the handler
    deadlocks outright until the timeout (the netbus close-vs-read-loop
    race PR 3 fixed came from this shape). Move the request onto a
    worker thread, or reply asynchronously."""

    name = "request-from-handler"
    description = (
        "blocking bus.request/RemoteBus.request reachable from a bus "
        "subscribe callback — the dispatcher thread blocks on a reply "
        "it may itself have to dispatch (self-deadlock shape)"
    )

    def prepare(self, ctxs, repo_root=None):
        pass

    def check(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx: FileCtx, cls: ast.ClassDef):
        methods = {
            item.name: item
            for item in cls.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        entries: list = []  # (entry label, start node kind)
        for mname, fn in methods.items():
            nested = {
                n.name: n for n in ast.walk(fn)
                if isinstance(n, ast.FunctionDef) and n is not fn
            }

            def register(arg, _m=mname, _nested=nested):
                a = _self_attr(arg)
                if a is not None:
                    entries.append((a, ("method", a)))
                elif isinstance(arg, ast.Name) and arg.id in _nested:
                    entries.append(
                        (f"{_m}.<{arg.id}>", ("nested", (_m, arg.id)))
                    )
                elif isinstance(arg, ast.Call):
                    for inner in list(arg.args) + [
                        kw.value for kw in arg.keywords
                    ]:
                        register(inner)

            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "subscribe"
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        register(arg)
        if not entries:
            return
        reported: set = set()
        for label, start in entries:
            for site in self._reachable_requests(ctx, cls, methods, start):
                key = (site[0], site[1])
                if key in reported:
                    continue
                reported.add(key)
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=site[0],
                    message=(
                        f"{site[2]}.request() blocks the subscribe "
                        f"callback {label!r}'s dispatcher thread "
                        "(self-deadlock if the reply routes through "
                        "this dispatcher) — move the request off the "
                        "handler"
                    ),
                    symbol=site[1],
                )

    @staticmethod
    def _walk_scoped(root):
        """Walk ``root``'s body WITHOUT descending into nested defs —
        a nested def's body runs only when CALLED (the explicit
        ``nested`` frontier models that), not where it is defined."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _reachable_requests(self, ctx, cls, methods, start):
        """(line, symbol, recv) request sites reachable from ``start``
        through same-class self-calls and CALLED nested defs of the
        enclosing method (a nested def that is merely defined — e.g.
        handed to a worker thread — is not a dispatcher-thread site)."""
        sites: list = []
        seen: set = set()
        frontier = [start]
        while frontier:
            kind, payload = frontier.pop()
            if (kind, payload) in seen:
                continue
            seen.add((kind, payload))
            if kind == "method":
                mname = payload
                fn = methods.get(mname)
                if fn is None:
                    continue
                body, qual = fn, f"{ctx.qualname(cls)}.{mname}"
            else:
                mname, nname = payload
                fn = methods.get(mname)
                if fn is None:
                    continue
                body = next(
                    (n for n in ast.walk(fn)
                     if isinstance(n, ast.FunctionDef) and n is not fn
                     and n.name == nname),
                    None,
                )
                if body is None:
                    continue
                qual = f"{ctx.qualname(cls)}.{mname}.{nname}"
            nested_names = {
                n.name for n in ast.walk(fn)
                if isinstance(n, ast.FunctionDef) and n is not fn
            }
            for node in self._walk_scoped(body):
                if not isinstance(node, ast.Call):
                    continue
                recv = _bus_recv_name(node.func)
                if recv is not None:
                    sites.append((node.lineno, qual, recv))
                a = _self_attr(node.func)
                if a is not None and a in methods:
                    frontier.append(("method", a))
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in nested_names
                    and not (kind == "nested"
                             and node.func.id == payload[1])
                ):
                    frontier.append(("nested", (mname, node.func.id)))
        return sites


# -- rule: blocking-call-under-lock -------------------------------------------

class BlockingCallUnderLockRule:
    """Flag blocking calls made while a ``with self.<lock>:`` scope is
    held. ``bus.request`` / ``RemoteBus.request`` block up to their
    timeout waiting for a remote reply, and ``block_until_ready()`` /
    ``.item()`` fence the device — holding an instance lock across
    either serializes every other thread (bus dispatcher threads, the
    query thread) behind a network/device round trip, and a reply
    handler that needs the same lock deadlocks outright. Move the
    blocking call outside the critical section (snapshot state under
    the lock, call after)."""

    name = "blocking-call-under-lock"
    description = (
        "bus.request/block_until_ready/.item()/time.sleep/timeout-less "
        "queue get-put while holding a `with self.<lock>` — a blocking "
        "call inside a critical section (deadlock-prone; serializes "
        "other threads)"
    )

    def prepare(self, ctxs, repo_root=None):
        pass

    def check(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx: FileCtx, cls: ast.ClassDef):
        locks = _class_lock_attrs(cls)
        if not locks:
            return
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{ctx.qualname(cls)}.{item.name}"
                yield from self._scan(ctx, item, qn, locks, locked=False)

    def _scan(self, ctx, node, qn, locks, locked):
        if isinstance(node, ast.With):
            # Items evaluate in order, each after the previous item's
            # __enter__ — so a context expression AFTER a lock item (or
            # inside a nested `with` header under an outer lock) is a
            # held-lock call site too.
            held = locked
            for item in node.items:
                yield from self._scan(ctx, item.context_expr, qn, locks,
                                      held)
                if item.optional_vars is not None:
                    yield from self._scan(ctx, item.optional_vars, qn,
                                          locks, held)
                if (
                    _self_attr(item.context_expr) in locks
                    or (
                        isinstance(item.context_expr, ast.Call)
                        and _self_attr(item.context_expr.func) in locks
                    )
                ):
                    held = True
            for child in node.body:
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue  # defined, not called, under the lock
                yield from self._scan(ctx, child, qn, locks, held)
            return
        if locked and isinstance(node, ast.Call):
            msg = self._blocking_msg(node)
            if msg:
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=node.lineno,
                    message=f"{msg} while holding a lock",
                    symbol=qn,
                )
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A nested def does not RUN here; its body executes on
                # whatever thread later calls it (scanned unlocked via
                # the class walk only if it's a method — nested-def
                # bodies under a lock are not held-lock call sites).
                continue
            yield from self._scan(ctx, child, qn, locks, locked)

    @staticmethod
    def _blocking_msg(node: ast.Call) -> str | None:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        # bus.request / self.bus.request / self._bus.request /
        # RemoteBus.request — the message-bus request/reply round trip.
        # Receiver must look like a bus so `requests`-style libraries
        # don't false-positive.
        bus = _bus_recv_name(f)
        if bus is not None:
            return f"{bus}.request() (blocks up to its timeout)"
        if f.attr == "block_until_ready":
            return "block_until_ready() (device fence)"
        if f.attr == "item" and not node.args:
            return ".item() (device-to-host readback)"
        if (
            f.attr == "sleep"
            and isinstance(f.value, ast.Name)
            and f.value.id == "time"
        ):
            return "time.sleep() (unconditional stall)"
        if f.attr in ("get", "put"):
            # Timeout-less Queue.get blocks forever on an empty queue,
            # and put on a full bounded one — inside a critical section
            # that is a deadlock waiting for its producer/consumer to
            # need the same lock. Receiver must look like a queue
            # (q / _q / *queue / *_q) so dict.get etc. don't
            # false-positive; any positional arg or timeout/block
            # keyword makes get non-blocking-or-bounded.
            recv = f.value
            name = (
                recv.id if isinstance(recv, ast.Name)
                else recv.attr if isinstance(recv, ast.Attribute)
                else None
            )
            if name is None:
                return None
            base = name.lstrip("_").lower()
            queueish = (
                base in ("q", "queue", "inbox")
                or base.endswith("queue") or name.endswith("_q")
            )
            if not queueish:
                return None
            kwargs = {kw.arg for kw in node.keywords}
            if kwargs & {"timeout", "block"}:
                return None
            if f.attr == "get" and node.args:
                return None  # get(False) / get(timeout) forms
            if f.attr == "put" and len(node.args) >= 2:
                return None  # put(item, False) / put(item, True, t)
            return (
                f"{name}.{f.attr}() without a timeout (may block "
                "indefinitely)"
            )
        return None


# -- rule: metrics-naming -----------------------------------------------------

class MetricsNamingRule:
    name = "metrics-naming"
    description = (
        "metric names registered via .counter/.gauge/.histogram must "
        "match ^pixie_[a-z0-9_]+$ and avoid histogram-series suffixes; "
        "bounded-cardinality label keys (tenant) must take values from "
        "their registered-set resolver, never raw client strings"
    )

    _KINDS = frozenset({"counter", "gauge", "histogram"})
    #: Label keys whose value space is an operator-registered set: a
    #: raw client string here makes Prometheus series cardinality
    #: unbounded (services/tenancy.py). The value at a ``.labels()``
    #: call site must visibly come from the resolver — a direct
    #: ``resolve_tenant(...)`` call, a name assigned from one in an
    #: enclosing scope, or ``DEFAULT_TENANT``. Reviewed pass-through
    #: sites (the resolver ran in the caller) live in the counted
    #: baseline, so any NEW unreviewed site fails the --analyze gate.
    _BOUNDED_LABELS = {"tenant": "resolve_tenant"}

    def prepare(self, ctxs, repo_root=None):
        pass

    def check(self, ctx: FileCtx):
        yield from self._check_names(ctx)
        yield from self._check_bounded_labels(ctx)

    def _check_names(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr in self._KINDS):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            name = arg.value
            qn = ctx.qualname(node)
            if not METRIC_RE.match(name):
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=node.lineno,
                    message=(
                        f"metric name {name!r} violates "
                        "^pixie_[a-z0-9_]+$"
                    ),
                    symbol=qn,
                )
            elif f.attr != "histogram" and name.endswith(
                RESERVED_SUFFIXES
            ):
                yield Finding(
                    rule=self.name,
                    path=ctx.relpath,
                    line=node.lineno,
                    message=(
                        f"{f.attr} name {name!r} ends in a reserved "
                        "Prometheus histogram-series suffix"
                    ),
                    symbol=qn,
                )

    @classmethod
    def _resolver_bindings(cls, scope_node, resolver: str) -> set:
        """Names assigned from ``resolver(...)`` directly in ``scope``
        — nested function/class scopes are NOT searched (they carry
        their own bindings on the visit stack), so a pass-through
        parameter that merely shares a name with some other function's
        resolved variable does not silently pass."""
        names: set = set()
        stack = list(ast.iter_child_nodes(scope_node))
        while stack:
            n = stack.pop()
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # scope boundary
            stack.extend(ast.iter_child_nodes(n))
            # Any assignment form that binds a name to resolver(...):
            # plain, annotated (`tenant: str = resolve_tenant(x)`), or
            # walrus (`if (t := resolve_tenant(x)):`).
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                targets, call = n.targets, n.value
            elif (isinstance(n, ast.AnnAssign)
                    and isinstance(n.value, ast.Call)):
                targets, call = [n.target], n.value
            elif (isinstance(n, ast.NamedExpr)
                    and isinstance(n.value, ast.Call)):
                targets, call = [n.target], n.value
            else:
                continue
            f = call.func
            fname = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            if fname != resolver:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        return names

    def _value_is_resolved(self, value, resolver: str, bound: set) -> bool:
        if isinstance(value, ast.Call):
            f = value.func
            fname = (
                f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else None
            )
            return fname == resolver
        if isinstance(value, ast.Name):
            return value.id == "DEFAULT_TENANT" or value.id in bound
        if isinstance(value, ast.Attribute):
            return value.attr == "DEFAULT_TENANT"
        return False

    def _check_bounded_labels(self, ctx: FileCtx):
        findings = []

        # Resolver bindings are collected per scope and carried on a
        # stack: module-level bindings apply everywhere, a function's
        # bindings apply inside it (and its nested functions).
        def visit_scoped(node, stack):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
            ):
                resolved = set()
                for r in {v for v in self._BOUNDED_LABELS.values()}:
                    resolved |= self._resolver_bindings(node, r)
                stack = stack + [resolved]
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                bound = set().union(*stack) if stack else set()
                for kw in node.keywords:
                    resolver = self._BOUNDED_LABELS.get(kw.arg or "")
                    if resolver is None:
                        continue
                    if not self._value_is_resolved(
                        kw.value, resolver, bound
                    ):
                        findings.append(Finding(
                            rule=self.name,
                            path=ctx.relpath,
                            line=node.lineno,
                            message=(
                                f"label {kw.arg}=... must be derived "
                                f"from {resolver}() (bounded metric-"
                                "label cardinality: tenants come from "
                                "the registered set, not raw client "
                                "strings) — resolve in this scope, or "
                                "baseline the reviewed pass-through "
                                "site"
                            ),
                            symbol=ctx.qualname(node),
                        ))
            for child in ast.iter_child_nodes(node):
                visit_scoped(child, stack)

        visit_scoped(ctx.tree, [])
        yield from findings


ALL_RULES = (
    HostSyncHotPathRule,
    JitRecompileHazardRule,
    ThreadSharedStateRule,
    LockOrderRule,
    RequestFromHandlerRule,
    BlockingCallUnderLockRule,
    MetricsNamingRule,
)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> dict:
    """key -> allowed occurrence count. Counts matter: a key whose
    occurrences GROW has gained a new violation (same rule, same
    function, same message) and must fail, not hide behind the old
    grandfathered finding."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError:
            return {}  # empty/garbage baseline = no baseline
    out: dict = {}
    for e in data.get("findings", []):
        key = (e["rule"], e["path"], e["symbol"], e["message"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def save_baseline(findings, path: str | None = None) -> None:
    path = path or default_baseline_path()
    counts: dict = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    with open(path, "w") as fh:
        json.dump(
            {
                "version": 1,
                "findings": [
                    {
                        "rule": r, "path": p, "symbol": s, "message": m,
                        "count": c,
                    }
                    for (r, p, s, m), c in sorted(counts.items())
                ],
            },
            fh,
            indent=2,
        )
        fh.write("\n")


@dataclass
class LintReport:
    findings: list  # non-suppressed, non-baselined
    baselined: list
    suppressed: int
    files: int

    @property
    def ok(self) -> bool:
        return not self.findings


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            ]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def run_lint(paths, rules=None, baseline_path=None,
             repo_root=None) -> LintReport:
    """Lint ``paths`` (files or directories) with ``rules`` (rule name
    list or None = all), applying inline suppressions and the baseline.
    """
    repo_root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    rule_objs = []
    for cls in ALL_RULES:
        r = cls()
        if rules is None or r.name in rules:
            rule_objs.append(r)
    ctxs = []
    for path in _iter_py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, repo_root)
        try:
            with open(ap) as f:
                src = f.read()
            ctxs.append(FileCtx(ap, rel, src))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # not lintable python (templates, fixtures)
    for r in rule_objs:
        r.prepare(ctxs, repo_root)
    baseline = load_baseline(baseline_path)
    budget = dict(baseline)  # remaining allowed occurrences per key
    findings, baselined, suppressed = [], [], 0
    for ctx in ctxs:
        for r in rule_objs:
            for f in r.check(ctx):
                if ctx.suppressed(f.rule, f.line):
                    suppressed += 1
                elif budget.get(f.key(), 0) > 0:
                    budget[f.key()] -= 1
                    baselined.append(f)
                else:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintReport(
        findings=findings, baselined=baselined, suppressed=suppressed,
        files=len(ctxs),
    )
