"""px-style command-line client.

Reference parity: ``/root/reference/src/pixie_cli`` (the ``px`` binary:
``px run <script>``, ``px script list``, ``px get viziers`` ...). The
transport is the framed-TCP netbus to a broker running ``serve()``
(VizierService.ExecuteScript analog); ``--local`` runs scripts against
an in-process engine instead (useful for replays and development).

Usage:
  python -m pixie_tpu.cli run px/http_stats [--broker HOST:PORT]
  python -m pixie_tpu.cli run my_query.pxl --local --replay events.npz
  python -m pixie_tpu.cli script list | script show px/http_stats
  python -m pixie_tpu.cli explain px/http_stats
  python -m pixie_tpu.cli tables|agents --broker HOST:PORT
  python -m pixie_tpu.cli debug queries --broker HOST:PORT [-v]
  python -m pixie_tpu.cli cancel QID --broker HOST:PORT
  python -m pixie_tpu.cli docs
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_query(name_or_path: str) -> str:
    from .scripts import list_scripts, load_script

    if name_or_path in list_scripts():
        return load_script(name_or_path).pxl
    if os.path.exists(name_or_path):
        with open(name_or_path) as f:
            return f.read()
    raise SystemExit(
        f"no script named {name_or_path!r} (library: "
        f"{', '.join(list_scripts())}) and no such file"
    )


def _print_batch(name: str, hb, fmt: str) -> None:
    d = hb.to_pydict()
    cols = list(d)
    if fmt == "json":
        rows = [
            {c: _py(d[c][i]) for c in cols} for i in range(hb.length)
        ]
        print(json.dumps({"table": name, "rows": rows}))
        return
    if fmt == "csv":
        # The reference's CSV surface (carnot_executable.cc CSV-out /
        # `px run -o csv`): header then rows, stdlib-quoted. Each table
        # is prefixed with a `# table: <name>` comment line so
        # multi-output scripts stay parseable (split on the marker).
        import csv as _csv

        print(f"# table: {name}")
        w = _csv.writer(sys.stdout, lineterminator="\n")
        w.writerow(cols)
        for i in range(hb.length):
            w.writerow([_py(d[c][i]) for c in cols])
        return
    widths = {
        c: max(len(c), *(len(str(v)) for v in d[c][:200]), 1) if hb.length else len(c)
        for c in cols
    }
    print(f"== {name} ({hb.length} rows) ==")
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for i in range(min(hb.length, 200)):
        print("  ".join(str(_py(d[c][i])).ljust(widths[c]) for c in cols))
    if hb.length > 200:
        print(f"... {hb.length - 200} more rows")


def _py(v):
    from .api import _py as api_py

    return api_py(v)


def _client(addr: str):
    from .api import Client

    host, _, port = addr.rpartition(":")
    return Client(host or "127.0.0.1", int(port))


def cmd_run(args) -> int:
    query = _load_query(args.script)
    if args.broker:
        from .api import ScriptExecutionError

        req = {"query": query, "timeout_s": args.timeout,
               "max_output_rows": args.max_rows}
        if args.require_complete:
            req["require_complete"] = True
        if args.tenant:
            req["tenant"] = args.tenant
        if args.priority:
            req["priority"] = args.priority
        if args.deadline_ms:
            req["deadline_ms"] = args.deadline_ms
        with _client(args.broker) as client:
            try:
                res = client._request(
                    "broker.execute", req, timeout_s=args.timeout + 5,
                )
            except ScriptExecutionError as e:
                print(f"error: {e}", file=sys.stderr)
                return 1
        for name, hb in sorted(res["tables"].items()):
            _print_batch(name, hb, args.output)
        if res.get("partial"):
            reasons = res.get("missing_reasons", {})
            if set(reasons.values()) <= {"deadline", "cancelled"} and reasons:
                why = "/".join(sorted(set(reasons.values())))
                # Keys are agent ids except the broker's "_query"
                # sentinel (query stopped with no agent outstanding).
                agents = sorted(k for k in reasons if not k.startswith("_"))
                suffix = f" ({', '.join(agents)})" if agents else ""
                print(
                    f"warning: PARTIAL results — query {why} before "
                    f"completion{suffix}",
                    file=sys.stderr,
                )
            else:
                missing = ", ".join(res.get("missing_agents", []))
                print(
                    f"warning: PARTIAL results — data agent(s) lost "
                    f"mid-query: {missing}",
                    file=sys.stderr,
                )
        if args.output == "table":
            stats = res.get("agent_stats", {})
            if stats:
                worst = max(s["exec_time_s"] for s in stats.values())
                print(f"[{len(stats)} agents, slowest {worst * 1e3:.1f}ms]")
        return 0
    if not args.local:
        print(
            "error: no target — pass --broker HOST:PORT for a cluster "
            "or --local for an in-process engine",
            file=sys.stderr,
        )
        return 2
    # Local mode: one in-process engine over replays.
    from .exec.engine import Engine
    from .ingest.schemas import init_schemas

    eng = Engine()
    init_schemas(eng)
    if args.synthetic:
        from .ingest.replay import replay_into

        replay_into(eng, args.synthetic)
    for path in args.replay or []:
        from .ingest.replay import load_npz

        for records in load_npz(path):
            eng.append_data("http_events", records)
    out = eng.execute_query(query, max_output_rows=args.max_rows)
    for name, hb in sorted(out.items()):
        _print_batch(name, hb, args.output)
    return 0


def cmd_live(args) -> int:
    """Live view: subscribe to a streaming query, reprinting the result
    as it updates (the reference UI's live-view flow over StreamResults)
    until interrupted or --rounds updates have arrived."""
    import threading

    query = _load_query(args.script)
    done = threading.Event()
    seen = {"n": 0, "failed": False}

    def on_update(u):
        if "error" in u:
            print(f"error: {u['error']}", file=sys.stderr)
            seen["failed"] = True
            done.set()
            return
        if u.get("stream_degraded"):
            missing = ", ".join(u.get("missing_agents", []))
            print(
                f"warning: live view degraded — {u.get('reason', '')} "
                f"(missing: {missing})",
                file=sys.stderr,
            )
            return
        seen["n"] += 1
        mode = u.get("mode", "")
        print(f"-- update {seen['n']} ({mode}) --")
        rows = u["rows"]
        cols = list(rows)
        for i in range(len(rows[cols[0]]) if cols else 0):
            print({c: rows[c][i] for c in cols})
        if args.rounds and seen["n"] >= args.rounds:
            done.set()

    with _client(args.broker) as client:
        sub = client.stream_script(
            query, on_update, poll_interval_s=args.interval,
            require_complete=args.require_complete or None,
        )
        try:
            done.wait(timeout=args.timeout if args.timeout else None)
        except KeyboardInterrupt:
            pass
        finally:
            sub.cancel()
    return 1 if seen["failed"] else 0


def cmd_script(args) -> int:
    from .scripts import list_scripts, load_script

    if args.action == "list":
        for n in list_scripts():
            s = load_script(n)
            print(f"{n:28s} {s.manifest.get('short', '')}")
        return 0
    if not args.name:
        print("usage: px script show <name>", file=sys.stderr)
        return 2
    s = load_script(args.name)
    print(s.pxl)
    return 0


def cmd_explain(args) -> int:
    from .planner.debug import explain_pxl
    from .types.dtypes import DataType
    from .types.relation import Relation

    query = _load_query(args.script)
    if args.broker:
        with _client(args.broker) as client:
            schemas = client.schemas()
    else:
        # Offline explain: synthesize schemas for the canonical tables the
        # script references (shipped output-table relations).
        from .ingest.schemas import CANONICAL_SCHEMAS

        schemas = dict(CANONICAL_SCHEMAS)
        schemas.setdefault(
            "t", Relation([("time_", DataType.TIME64NS)])
        )
    print(explain_pxl(query, schemas))
    return 0


def cmd_tables(args) -> int:
    with _client(args.broker) as client:
        schemas = client.schemas()
    for name, rel in sorted(schemas.items()):
        print(f"{name}: {rel}")
    return 0


def cmd_agents(args) -> int:
    with _client(args.broker) as client:
        status = client.agents_status()
    agents = status["agents"]
    if status.get("broker"):
        # Broker HA: WHICH replica answered (the current leader).
        print(f"broker: {status['broker']}")
    for a in agents:
        q = "  QUARANTINED" if a.get("quarantined") else ""
        print(
            f"{a['agent_id']:14s} asid={a['asid']:<4d} {a['kind']:6s} "
            f"hb={a['last_heartbeat_s']:.1f}s tables={a['num_tables']}{q}"
        )
    return 0


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TB"


def _fmt_lag(ms) -> str:
    ms = float(ms or 0.0)
    if ms <= 0:
        return "-"
    if ms < 1000:
        return f"{ms:.0f}ms"
    if ms < 3600_000:
        return f"{ms / 1000:.1f}s"
    return f"{ms / 3600_000:.1f}h"


def cmd_debug(args) -> int:
    """`px debug queries`: recent query traces from the broker with
    per-query resource usage and per-agent attribution (the self-
    observability surface — docs/OBSERVABILITY.md)."""
    with _client(args.broker) as client:
        res = client.debug_queries(limit=args.limit)
    rows = res["queries"]
    if args.output == "json":
        print(json.dumps(res, default=str))
        return 0
    if not rows and not res["in_flight"]:
        print("no recent queries")
        return 0
    hdr = (f"{'qid':12s} {'tenant':8s} {'status':8s} {'cache':6s} "
           f"{'ms':>9s} "
           f"{'rows':>9s} {'staged':>9s} {'pred':>9s} {'pred/obs':>8s} "
           f"{'device':>9s} {'wire':>9s} {'fresh':>9s} agents")
    print(hdr)
    for row in res["in_flight"] + rows:
        u = row.get("usage", {})
        agents = sorted(row.get("agent_usage", {}))
        # pxbound predicted staged bytes next to the observed column —
        # the admission-control signal, auditable per query (a observed
        # > predicted row is a soundness bug; see docs/ANALYSIS.md).
        pred = row.get("predicted") or {}
        pb = pred.get("bytes_staged_hi")
        # Calibration ratio: how far the plan-time prediction over-
        # shoots reality (>= 1 is pxbound's soundness contract; huge =
        # the over-conservatism the observed floor narrows). Blank when
        # either side is unknown — a sketch-less prediction, a fully
        # device-resident run with zero staged bytes — or when the
        # "prediction" IS observed history (origin contains
        # "observed"): that number is yesterday's max, not a pxbound
        # bound, and a < 1 ratio there is growth, not unsoundness.
        obs = u.get("bytes_staged", 0)
        floored = "observed" in str(pred.get("origin", ""))
        ratio = (
            f"{pb / obs:.2f}" if pb is not None and obs and not floored
            else "-"
        )
        print(
            f"{row.get('qid') or row['id'][:12]:12s} "
            f"{row.get('tenant', '-') or '-':8s} "
            f"{row['status']:8s} "
            # Result-cache disposition ("-" = cache not in play).
            f"{row.get('cache') or '-':6s} "
            f"{row['duration_ms']:>9.1f} "
            f"{row.get('rows_out', u.get('rows_out', 0)):>9d} "
            f"{_fmt_bytes(u.get('bytes_staged', 0)):>9s} "
            f"{'-' if pb is None else _fmt_bytes(pb):>9s} "
            f"{ratio:>8s} "
            f"{u.get('device_ms', 0.0):>8.1f}ms "
            f"{_fmt_bytes(u.get('wire_bytes', 0)):>9s} "
            # Result staleness: worst scanned-table watermark lag at
            # execute time ("-" = no time-indexed scan recorded).
            f"{_fmt_lag(u.get('freshness_lag_ms', 0.0)):>9s} "
            f"{','.join(agents)}"
        )
        if args.verbose:
            for aid, au in sorted(row.get("agent_usage", {}).items()):
                print(
                    f"  {aid:14s} staged={_fmt_bytes(au.get('bytes_staged', 0))} "
                    f"device={au.get('device_ms', 0.0):.1f}ms "
                    f"wire={_fmt_bytes(au.get('wire_bytes', 0))} "
                    f"rows={au.get('rows_out', 0)} "
                    f"windows={au.get('windows', 0)}"
                )
    return 0


def cmd_profile(args) -> int:
    """`px profile`: cluster-merged CPU flames from the broker —
    agents' heartbeat folded-stack summaries plus the broker's own
    sampler, attributed with qid/script hash/tenant/phase. ``--diff A
    B`` renders the per-frame differential profile between two script
    hashes (services/telemetry.py profile_diff)."""
    from .services.telemetry import profile_counts, profile_diff

    with _client(args.broker) as client:
        if args.diff:
            base_hash, cmp_hash = args.diff
            base = client.profile(
                agent=args.agent, tenant=args.tenant,
                script=base_hash, limit=4096,
            )["stacks"]
            cmp_ = client.profile(
                agent=args.agent, tenant=args.tenant,
                script=cmp_hash, limit=4096,
            )["stacks"]
            rows = profile_diff(
                profile_counts(base), profile_counts(cmp_)
            )[:args.limit]
            if args.output == "json":
                print(json.dumps(rows))
                return 0
            print(f"{'frame':48s} {'self Δ':>8s} {'self a':>7s} "
                  f"{'self b':>7s} {'total Δ':>8s}")
            for r in rows:
                print(
                    f"{r['frame'][:48]:48s} {r['self_delta']:>+8d} "
                    f"{r['self_base']:>7d} {r['self_cmp']:>7d} "
                    f"{r['total_delta']:>+8d}"
                )
            return 0
        res = client.profile(
            agent=args.agent, tenant=args.tenant,
            script=args.script, limit=args.limit,
        )
    if args.output == "json":
        print(json.dumps(res))
        return 0
    stacks = res["stacks"]
    if not stacks:
        print("no profile samples (is self_profiling on?)")
        return 0
    print(f"agents: {', '.join(res['agents']) or '-'}")
    print(f"{'samples':>8s} {'tenant':8s} {'phase':12s} "
          f"{'script':12s} stack (leaf last)")
    for r in stacks:
        stack = r["stack"]
        if args.output == "collapsed":
            print(f"{stack} {r['count']}")
            continue
        frames = stack.split(";")
        tail = ";".join(frames[-3:]) if len(frames) > 3 else stack
        print(
            f"{r['count']:>8d} {r.get('tenant') or '-':8s} "
            f"{r.get('phase') or '-':12s} "
            f"{(r.get('script_hash') or '-')[:12]:12s} "
            f"{'...' if len(frames) > 3 else ''}{tail}"
        )
    return 0


def cmd_cancel(args) -> int:
    """`px cancel <qid>`: cooperative cancellation — the broker stops
    the query's agents at their next window boundary and the original
    caller gets a partial result (reason "cancelled")."""
    with _client(args.broker) as client:
        if client.cancel_query(args.qid):
            print(f"query {args.qid} cancelled")
            return 0
    print(f"no running query {args.qid!r}", file=sys.stderr)
    return 1


def cmd_docs(args) -> int:
    from .metadata.funcs import register_metadata_funcs
    from .metadata.state import MetadataState
    from .udf.docgen import generate_markdown
    from .udf.registry import default_registry

    # Include the metadata family (bound to an empty state): `px docs >
    # docs/FUNCTIONS.md` must regenerate the committed reference exactly.
    reg = default_registry().clone("docs")
    register_metadata_funcs(reg, MetadataState())
    print(generate_markdown(reg))
    return 0


def cmd_version(args) -> int:
    from .version import version_info

    print(json.dumps(version_info(), indent=1))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="px", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="execute a PxL script")
    run.add_argument("script", help="library script name or .pxl path")
    run.add_argument("--broker", help="broker netbus HOST:PORT")
    run.add_argument("--local", action="store_true", help="in-process engine")
    run.add_argument("--replay", action="append",
                     help="saved http_events replay .npz (local)")
    run.add_argument("--synthetic", type=int, metavar="N",
                     help="generate an N-row synthetic replay (local)")
    run.add_argument("--timeout", type=float, default=30.0)
    run.add_argument("--max-rows", type=int, default=10_000)
    run.add_argument("--require-complete", action="store_true",
                     help="fail instead of returning partial results "
                          "when a data agent is lost mid-query")
    run.add_argument("--tenant",
                     help="tenant to admit the query under (registered "
                          "via admission_tenant_weights; unknown names "
                          "run as the shared tenant)")
    run.add_argument("--priority", type=int, default=0,
                     help="admission-queue priority (higher first)")
    run.add_argument("--deadline-ms", type=float, default=0.0,
                     help="query deadline: shed while queued / abort "
                          "cooperatively once dispatched, returning "
                          "partial results")
    run.add_argument("-o", "--output", choices=("table", "json", "csv"),
                     default="table")
    run.set_defaults(fn=cmd_run)

    cn = sub.add_parser(
        "cancel", help="cooperatively cancel a running query by qid"
    )
    cn.add_argument("qid")
    cn.add_argument("--broker", required=True)
    cn.set_defaults(fn=cmd_cancel)

    lv = sub.add_parser("live", help="subscribe to a live (streaming) view")
    lv.add_argument("script", help="library script name or .pxl path")
    lv.add_argument("--broker", required=True, help="broker netbus HOST:PORT")
    lv.add_argument("--interval", type=float, default=0.5,
                    help="agent poll cadence (seconds)")
    lv.add_argument("--rounds", type=int, default=0,
                    help="stop after N updates (0 = until interrupted)")
    lv.add_argument("--require-complete", action="store_true",
                    help="abort the live view instead of degrading "
                         "when a data agent is lost")
    lv.add_argument("--timeout", type=float, default=0.0,
                    help="stop after this many seconds (0 = none)")
    lv.set_defaults(fn=cmd_live)

    sc = sub.add_parser("script", help="script library")
    sc.add_argument("action", choices=("list", "show"))
    sc.add_argument("name", nargs="?")
    sc.set_defaults(fn=cmd_script)

    ex = sub.add_parser("explain", help="render a script's physical plan")
    ex.add_argument("script")
    ex.add_argument("--broker", help="use live schemas from this broker")
    ex.set_defaults(fn=cmd_explain)

    tb = sub.add_parser("tables", help="list cluster table schemas")
    tb.add_argument("--broker", required=True)
    tb.set_defaults(fn=cmd_tables)

    ag = sub.add_parser("agents", help="list live agents")
    ag.add_argument("--broker", required=True)
    ag.set_defaults(fn=cmd_agents)

    db = sub.add_parser(
        "debug", help="self-observability surfaces (debug queries)"
    )
    db.add_argument("what", choices=("queries",),
                    help="queries: recent query traces + resource usage")
    db.add_argument("--broker", required=True)
    db.add_argument("--limit", type=int, default=20)
    db.add_argument("-v", "--verbose", action="store_true",
                    help="per-agent usage breakdown under each query")
    db.add_argument("-o", "--output", choices=("table", "json"),
                    default="table")
    db.set_defaults(fn=cmd_debug)

    pf = sub.add_parser(
        "profile",
        help="cluster-merged CPU flames (top folded stacks, attributed)",
    )
    pf.add_argument("--broker", required=True)
    pf.add_argument("--agent", default=None,
                    help="only this agent's stacks (default: cluster merge)")
    pf.add_argument("--tenant", default=None,
                    help="only samples attributed to this tenant")
    pf.add_argument("--script", default=None, metavar="HASH",
                    help="only samples attributed to this script hash")
    pf.add_argument("--diff", nargs=2, metavar=("BASE", "CMP"),
                    help="differential profile between two script hashes")
    pf.add_argument("-n", "--limit", type=int, default=20)
    pf.add_argument("-o", "--output",
                    choices=("table", "json", "collapsed"), default="table")
    pf.set_defaults(fn=cmd_profile)

    dc = sub.add_parser("docs", help="dump the function reference (markdown)")
    dc.set_defaults(fn=cmd_docs)

    vr = sub.add_parser("version", help="print build/version metadata")
    vr.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
