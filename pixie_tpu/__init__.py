"""pixie_tpu — a TPU-native observability query engine.

A ground-up rebuild of the capabilities of Pixie's Carnot query engine
(reference: deprov447/pixie, ``src/carnot``), designed TPU-first:

- Columnar tables live in HBM as fixed-capacity column blocks with validity
  masks (reference: ``src/table_store/schema/row_batch.h:40``).
- Whole plan fragments (Map/Filter/BlockingAgg/Join) compile to a single
  jitted XLA program instead of a push-based exec-node graph
  (reference: ``src/carnot/exec/exec_graph.cc:295``).
- The PEM×N → Kelvin distributed reduction becomes ``shard_map`` over a
  ``jax.sharding.Mesh`` with ``psum``/``all_gather`` collectives over ICI
  (reference: ``src/carnot/planner/distributed/splitter/splitter.h:75``).
- Sketch aggregates (t-digest quantiles, HLL count-distinct) are mergeable
  carry pytrees with Pallas kernels on the hot path
  (reference: ``src/carnot/funcs/builtins/math_sketches.h:34``).

Strings are dictionary-encoded at staging time; regex/JSON UDFs run host-side
as staging transforms (the "host UDF" escape hatch).
"""

# Int64 timestamps (TIME64NS) and counts require 64-bit semantics end to end.
# TPUs emulate i64 adds cheaply; f64 is avoided on the hot path via the
# compute-dtype knob in pixie_tpu.types.dtypes.
import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
