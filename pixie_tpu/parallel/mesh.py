"""Device mesh construction — per-query, elastic.

Reference parity: Pixie replans every query against the currently-live
agent set (``query_executor.go:415``, ``prune_unavailable_sources_rule``);
here the analog is cheap mesh (re)construction from ``jax.devices()`` —
an engine is bound to one mesh, and degrading after a device-set change
means constructing a fresh engine over a fresh mesh.

Mesh axes:
- ``agents``: the data-parallel axis — each device is a virtual PEM
  holding a row shard of every table. All bulk-data collectives
  (partial-agg merge, union gather, repartition) ride this axis over ICI.
- ``kelvin`` (optional, size>1 for 2D meshes): a second axis for
  hierarchical reduction on multi-slice topologies — partial-agg merges
  first within an ``agents`` group (ICI), then across ``kelvin`` (DCN),
  mirroring PEM->Kelvin->query-broker two-level reduction.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AGENTS = "agents"
KELVIN = "kelvin"


def agent_mesh(n_agents: int | None = None, n_kelvin: int = 1, devices=None) -> Mesh:
    """Build an (agents[, kelvin]) mesh from the live device set."""
    devices = list(devices if devices is not None else jax.devices())
    if n_agents is None:
        n_agents = len(devices) // n_kelvin
    need = n_agents * n_kelvin
    if n_agents < 1 or need > len(devices):
        raise ValueError(
            f"mesh {n_agents}x{n_kelvin} needs {max(need, n_kelvin)} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(n_kelvin, n_agents)
    return Mesh(arr, (KELVIN, AGENTS))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over every mesh axis (agents x kelvin jointly)."""
    return NamedSharding(mesh, P(mesh.axis_names))


def pad_to_multiple(n: int, m: int) -> int:
    return int(math.ceil(n / m)) * m if n else m
