"""Distributed execution over a JAX device mesh.

Reference parity: Pixie's distributed plan fans a query out across
per-node PEM agents and reduces on Kelvin compute nodes via gRPC
``ResultSinkService.TransferResultChunk`` streams
(``src/carnot/planner/distributed/``, ``src/carnot/exec/grpc_router.h:53``).
The TPU-native equivalent (SURVEY.md §2.7):

- each mesh device is a "virtual PEM" holding a row shard of every table;
- plan fragments run under ``shard_map`` over the ``agents`` mesh axis;
- the PEM->Kelvin GRPC bridge becomes an XLA collective chosen by
  pattern: partial-agg finalize -> ``all_gather`` + associative state
  merge (or ``psum`` for keyless aggregates), union -> gather of row
  shards, broadcast join -> replicated build side.

Control-plane messaging (plan dispatch, heartbeats) stays host-side —
see ``pixie_tpu.service``.
"""

from .mesh import agent_mesh, row_sharding  # noqa: F401
from .executor import DistributedEngine, distributed_agg_step  # noqa: F401
