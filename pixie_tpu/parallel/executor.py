"""Distributed fragment execution: shard_map + collectives.

The reference's distributed query path (SURVEY.md §3.1) ships partial-agg
carries PEM->Kelvin over gRPC (``src/carnot/exec/grpc_sink_node.cc``,
``grpc_router.h:53``) and finalizes on the Kelvin fragment. Here the whole
topology compiles into ONE XLA program per window:

    window rows, sharded over the mesh
      └─ per-device: Map/Filter + local group state   (the PEM fragment)
      └─ all_gather(states) over ``agents`` + associative fold merge
         — the GRPC bridge become an ICI collective
      └─ (2D mesh) second fold over ``kelvin``        (the Kelvin tier)
      └─ merge into the running replicated query state

Elasticity: an engine is bound to one mesh at construction; after a
device-set change, construct a fresh engine over a fresh ``agent_mesh``
— the moral equivalent of replanning around live agents
(``prune_unavailable_sources_rule``).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..exec.engine import Engine
from ..types.batch import bucket_capacity
from .mesh import AGENTS, KELVIN, agent_mesh, pad_to_multiple, row_sharding


def _axis_fold_merge(state, axis_name: str, axis_size: int, merge):
    """all_gather per-device states along an axis and tree-merge them.

    The merge is associative (the UDA contract), so the reduction is a
    balanced tree: ceil(log2(D)) merge DEPTH instead of D-1 sequential
    steps (VERDICT r02 weak #6) — on dense-domain states each level is
    pure elementwise, and on sort-space states the per-level [2G] regroup
    sorts at the same level run data-parallel inside one fused program.
    Odd tails carry over unmerged to the next level.
    """
    gathered = jax.lax.all_gather(state, axis_name)  # leaves: [axis_size, ...]
    level = [
        jax.tree_util.tree_map(lambda x, i=i: x[i], gathered)
        for i in range(axis_size)
    ]
    while len(level) > 1:
        nxt = [
            merge(level[j], level[j + 1])
            for j in range(0, len(level) - 1, 2)
        ]
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    return level[0]


def _global_row_mask(cols, lo, hi, sizes):
    """Per-shard validity mask from GLOBAL row-range bounds.

    Device-resident windows arrive as (lo, hi) row bounds over the
    window's global capacity; inside shard_map each device holds a
    [cap / D] slice, so the mask rebuilds from the shard's flat index
    (kelvin-major, matching ``row_sharding``'s P((kelvin, agents))).
    """
    import jax.numpy as jnp

    local_n = next(
        p.shape[0]
        for c, planes in cols.items()
        if c != "__side__"
        for p in planes
    )
    flat = (
        jax.lax.axis_index(KELVIN) * sizes[AGENTS]
        + jax.lax.axis_index(AGENTS)
    )
    idx = flat * local_n + jax.lax.iota(jnp.int32, local_n)
    return (idx >= lo) & (idx < hi)


def distributed_agg_step(frag, mesh: Mesh, range_valid: bool = False):
    """Compile the distributed window step for an aggregating fragment.

    Returns jitted ``step(state, cols, side, valid) -> state``: ``state``
    and the fused-lookup-join ``side`` tables are replicated, ``cols``
    row-sharded. ``range_valid=True`` compiles the device-resident-window
    form, where ``valid`` is a replicated (lo, hi) scalar pair instead of
    a row-sharded mask.
    """
    axes = mesh.axis_names
    sizes = dict(zip(axes, mesh.devices.shape))

    def step(state, cols, side, valid):
        if range_valid:
            valid = _global_row_mask(cols, valid[0], valid[1], sizes)
        if side:
            cols = {**cols, "__side__": side}
        local = frag.window_state(cols, valid)
        merged = _axis_fold_merge(local, AGENTS, sizes[AGENTS], frag.merge_states)
        if sizes.get(KELVIN, 1) > 1:
            merged = _axis_fold_merge(merged, KELVIN, sizes[KELVIN], frag.merge_states)
        return frag.merge_states(state, merged)

    valid_spec = (P(), P()) if range_valid else P(axes)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(axes), P(), valid_spec),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=0)


def distributed_rows_step(frag, mesh: Mesh, range_valid: bool = False):
    """Compile the distributed step for a non-aggregating (map/filter)
    fragment: pure elementwise work, no collectives — output stays
    row-sharded (each virtual PEM keeps its shard, like MemorySink)."""
    axes = mesh.axis_names
    sizes = dict(zip(axes, mesh.devices.shape))

    def step(cols, side, valid):
        if range_valid:
            valid = _global_row_mask(cols, valid[0], valid[1], sizes)
        if side:
            cols = {**cols, "__side__": side}
        return frag.apply_rows(cols, valid)

    valid_spec = (P(), P()) if range_valid else P(axes)
    sharded = jax.shard_map(
        step, mesh=mesh, in_specs=(P(axes), P(), valid_spec),
        out_specs=P(axes), check_vma=False,
    )
    return jax.jit(sharded)


class DistributedEngine(Engine):
    """Engine whose fragment materialization runs over a device mesh.

    Joins/unions still reduce on host (they consume post-agg, small
    inputs); all per-row work and partial-agg merging is on-mesh.
    """

    # Fused lookup joins ride replicated side-table shardings through the
    # distributed steps' P() specs (r5: VERDICT item 5).
    fused_lookup_join = True
    # Folding happens INSIDE shard_map over the mesh; neither the
    # single-device CPU thread-parallel fold nor the TPU scan-fold
    # batching (update_all — a single-logical-device jit) may bypass
    # the distributed steps.
    cpu_parallel_fold = False
    scan_fold = False

    def __init__(self, registry=None, window_rows: int | None = None,
                 mesh: Mesh | None = None, n_agents: int | None = None,
                 n_kelvin: int = 1, distributed_state=None):
        super().__init__(registry=registry, window_rows=window_rows)
        self.mesh = mesh if mesh is not None else agent_mesh(n_agents, n_kelvin)
        self.n_devices = int(np.prod(self.mesh.devices.shape))
        self._base_mesh = self.mesh
        self.distributed_state = distributed_state
        self.last_distributed_plan = None
        self._step_cache: dict = {}

    @property
    def device_residency(self):
        """Mesh residency (r5): table windows stage row-sharded over the
        BASE mesh at append time; queries on that mesh consume them with
        zero transfer. Degraded-mesh queries (agent loss replanned onto
        a sub-mesh) stage per window instead — their shard layout
        differs from the resident windows'."""
        return self.mesh is self._base_mesh

    def execute_plan(self, plan, bridge_inputs=None, analyze=False,
                     materialize=True, cancel=None, trace=None):
        """Replan against the live agent set before executing (the
        reference pulls DistributedState fresh per query —
        ``query_executor.go:415``).

        The DistributedPlan drives execution: when the coordinator prunes
        agents, the query runs on a *degraded mesh* whose ``agents`` axis
        is the surviving shard count (the reference's pruned per-agent
        plan), and bridges are stitched against that executing mesh.
        """
        if self.distributed_state is None:
            return super().execute_plan(
                plan, bridge_inputs=bridge_inputs, analyze=analyze,
                materialize=materialize, cancel=cancel, trace=trace,
            )

        from ..exec.engine import QueryError
        from ..planner.distributed import DistributedPlanner
        from ..planner.distributed.coordinator import PlanningError

        # The replan mutates engine-scoped mesh state (self.mesh /
        # n_devices / last_distributed_plan) that in-flight window staging
        # reads, so it must happen inside the engine's one-query-at-a-time
        # guard (reentrant: super().execute_plan re-acquires).
        with self._exec_guard:
            planner = DistributedPlanner()
            try:
                split = planner.splitter.split(plan)
                dplan = planner.coordinator.assign(split, self.distributed_state)
            except PlanningError as e:
                raise QueryError(str(e)) from e

            n_kelvin = self.mesh.devices.shape[0]  # (kelvin, agents) layout
            max_agents = self.mesh.devices.size // n_kelvin
            n_shards = min(dplan.n_data_shards or max_agents, max_agents)
            if n_shards < max_agents:
                mesh = agent_mesh(
                    n_shards, n_kelvin, devices=self.mesh.devices.flatten()
                )
            else:
                mesh = self.mesh
            planner.stitch(dplan, self.distributed_state, mesh=mesh)
            self.last_distributed_plan = dplan

            saved = (self.mesh, self.n_devices)
            self.mesh, self.n_devices = mesh, int(np.prod(mesh.devices.shape))
            try:
                return super().execute_plan(
                    plan, bridge_inputs=bridge_inputs, analyze=analyze,
                    materialize=materialize, cancel=cancel, trace=trace,
                )
            finally:
                self.mesh, self.n_devices = saved

    def append_data(self, name, data, time_cols=("time_",)):
        t = self.table_store.ensure_table(
            name, device_window_rows=self.window_rows
        )
        t.stage_sharding = row_sharding(self._base_mesh)
        t.stage_capacity_multiple = int(np.prod(self._base_mesh.devices.shape))
        return super().append_data(name, data, time_cols=time_cols)

    def create_table(self, name, relation=None, max_bytes: int = -1):
        t = super().create_table(name, relation, max_bytes=max_bytes)
        t.stage_sharding = row_sharding(self._base_mesh)
        t.stage_capacity_multiple = int(np.prod(self._base_mesh.devices.shape))
        return t

    def _window_capacity(self, length: int) -> int:
        cap = super()._window_capacity(length)
        return pad_to_multiple(cap, self.n_devices)

    def _stage(self, hb, capacity: int):
        """Pad a host batch to capacity and place it row-sharded."""
        db = hb.to_device(capacity, sharding=row_sharding(self.mesh))
        return db.cols, db.valid

    def _put_side(self, v):
        """Fused-join side tables replicate over the mesh (the steps'
        P() in_spec); a device-0-committed array would conflict."""
        return jax.device_put(v, jax.sharding.NamedSharding(self.mesh, P()))

    def _dist_step(self, frag, range_valid: bool, agg: bool):
        """Per-(fragment, mesh, valid-form) compiled step — fresh jits
        per query would recompile the same program every execute."""
        key = (id(frag), self.mesh, range_valid, agg)
        fn = self._step_cache.get(key)
        if fn is None:
            fn = (
                distributed_agg_step(frag, self.mesh, range_valid)
                if agg
                else distributed_rows_step(frag, self.mesh, range_valid)
            )
            if len(self._step_cache) > 128:
                self._step_cache.clear()
            self._step_cache[key] = fn
        return fn

    @staticmethod
    def _split_side(cols):
        side = cols.get("__side__") or {}
        if side:
            cols = {k: v for k, v in cols.items() if k != "__side__"}
        return cols, side

    def _compile_steps(self, frag):
        if frag.is_agg:
            def init_state():
                return jax.device_put(
                    frag.init_state(), jax.sharding.NamedSharding(self.mesh, P())
                )

            def agg_step(state, cols, valid):
                cols, side = self._split_side(cols)
                fn = self._dist_step(frag, isinstance(valid, tuple), True)
                return fn(state, cols, side, valid)

            return init_state, agg_step, None

        def rows_step(cols, valid):
            cols, side = self._split_side(cols)
            fn = self._dist_step(frag, isinstance(valid, tuple), False)
            return fn(cols, side, valid)

        return None, None, rows_step
