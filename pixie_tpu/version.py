"""Build/version metadata.

Reference parity: ``/root/reference/src/shared/version`` (version.h
``VersionInfo``: semver + git commit + build time, surfaced on statusz
and the artifacts API). Populated from the environment at build/deploy
time; falls back to the dev defaults.
"""

from __future__ import annotations

import os
import time

VERSION = os.environ.get("PIXIE_TPU_VERSION", "0.3.0-dev")
BUILD_TIME_S = int(os.environ.get("PIXIE_TPU_BUILD_TIME", "0")) or None
_PROCESS_START_S = time.time()


def _git_commit() -> str:
    """Dev fallback, lazy + cached: ask git for the SOURCE CHECKOUT's
    HEAD (container builds stamp PIXIE_TPU_GIT_COMMIT instead — the
    linkstamp analog). Only fires when the package parent directory is
    itself a git checkout — a wheel installed inside some unrelated
    repo must report "unknown", not that repo's HEAD."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.exists(os.path.join(root, ".git")):
        return "unknown"
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=5,
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except (OSError, subprocess.TimeoutExpired):
        pass
    return "unknown"


_GIT_COMMIT_CACHE: str | None = os.environ.get("PIXIE_TPU_GIT_COMMIT") or None


def git_commit() -> str:
    global _GIT_COMMIT_CACHE
    if _GIT_COMMIT_CACHE is None:
        _GIT_COMMIT_CACHE = _git_commit()
    return _GIT_COMMIT_CACHE


def version_info() -> dict:
    """The VersionInfo struct: shipped on statusz and the CLI."""
    return {
        "version": VERSION,
        "git_commit": git_commit(),
        "build_time_s": BUILD_TIME_S,
        "uptime_s": round(time.time() - _PROCESS_START_S, 1),
    }
