"""Build/version metadata.

Reference parity: ``/root/reference/src/shared/version`` (version.h
``VersionInfo``: semver + git commit + build time, surfaced on statusz
and the artifacts API). Populated from the environment at build/deploy
time; falls back to the dev defaults.
"""

from __future__ import annotations

import os
import time

VERSION = os.environ.get("PIXIE_TPU_VERSION", "0.3.0-dev")
GIT_COMMIT = os.environ.get("PIXIE_TPU_GIT_COMMIT", "unknown")
BUILD_TIME_S = int(os.environ.get("PIXIE_TPU_BUILD_TIME", "0")) or None
_PROCESS_START_S = time.time()


def version_info() -> dict:
    """The VersionInfo struct: shipped on statusz and the CLI."""
    return {
        "version": VERSION,
        "git_commit": GIT_COMMIT,
        "build_time_s": BUILD_TIME_S,
        "uptime_s": round(time.time() - _PROCESS_START_S, 1),
    }
