"""Control-plane KV datastore with pluggable backends.

Reference parity: ``src/vizier/utils/datastore/datastore.go:65`` — the
interface the metadata service persists agents/tracepoints/cron scripts
through, with pebble (default) and etcd backends. Telemetry data is
deliberately NOT stored here (SURVEY.md §5: the table store is a bounded
in-memory ring); this is durable control-plane state only. Backends:
in-memory (tests, the reference's buntdb role) and sqlite3 (stdlib —
the single-file persistent default, pebble's role).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterable, Optional


class Datastore:
    """KV interface (Get/Set/Delete/GetWithPrefix/DeleteWithPrefix)."""

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def get_with_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def delete_with_prefix(self, prefix: str) -> None:
        for k, _ in self.get_with_prefix(prefix):
            self.delete(k)

    def close(self) -> None:
        pass


class MemoryDatastore(Datastore):
    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, bytes] = {}

    def get(self, key):
        with self._lock:
            return self._data.get(key)

    def set(self, key, value):
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key):
        with self._lock:
            self._data.pop(key, None)

    def get_with_prefix(self, prefix):
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )


class SqliteDatastore(Datastore):
    """Single-file persistent backend (the pebble-default analog)."""

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB)"
        )
        self._db.commit()

    def get(self, key):
        with self._lock:
            row = self._db.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)
            ).fetchone()
        return bytes(row[0]) if row else None

    def set(self, key, value):
        with self._lock:
            self._db.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, bytes(value)),
            )
            self._db.commit()

    def delete(self, key):
        with self._lock:
            self._db.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._db.commit()

    def get_with_prefix(self, prefix):
        # Range scan [prefix, prefix+0x10FFFF) — the ordered-KV idiom.
        with self._lock:
            rows = self._db.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
                (prefix, prefix + "\U0010ffff"),
            ).fetchall()
        return [(k, bytes(v)) for k, v in rows]

    def close(self):
        with self._lock:
            self._db.close()
