"""UPID: the 128-bit unique process id joining traces to k8s metadata.

Reference parity: ``src/shared/upid`` — {ASID (agent), PID, process
start ticks} packed into a u128. XLA has no native u128 (SURVEY.md §7),
so device columns carry (hi, lo) uint64 planes (DataType.UINT128) and
this class is the host-side pack/unpack + formatting surface.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True, order=True)
class UPID:
    asid: int  # agent short id (u32)
    pid: int  # process id (u32)
    start_ts: int  # process start time in ticks (u64)

    # Packing: hi = (asid << 32) | pid, lo = start_ts (upid.h layout).
    @property
    def hi(self) -> int:
        return ((self.asid & 0xFFFFFFFF) << 32) | (self.pid & 0xFFFFFFFF)

    @property
    def lo(self) -> int:
        return self.start_ts & 0xFFFFFFFFFFFFFFFF

    def value(self) -> int:
        return (self.hi << 64) | self.lo

    @classmethod
    def from_parts(cls, hi: int, lo: int) -> "UPID":
        return cls(asid=(hi >> 32) & 0xFFFFFFFF, pid=hi & 0xFFFFFFFF, start_ts=lo)

    @classmethod
    def from_value(cls, v: int) -> "UPID":
        return cls.from_parts((v >> 64) & (2**64 - 1), v & (2**64 - 1))

    def __str__(self) -> str:
        return f"{self.asid}:{self.pid}:{self.start_ts}"

    @classmethod
    def parse(cls, s: str) -> "UPID":
        asid, pid, ts = s.split(":")
        return cls(int(asid), int(pid), int(ts))


def pack_planes(upids) -> tuple[np.ndarray, np.ndarray]:
    """[UPID] -> (hi, lo) uint64 planes, the device UINT128 layout."""
    hi = np.fromiter((u.hi for u in upids), dtype=np.uint64, count=len(upids))
    lo = np.fromiter((u.lo for u in upids), dtype=np.uint64, count=len(upids))
    return hi, lo


def unpack_planes(hi: np.ndarray, lo: np.ndarray) -> list[UPID]:
    return [UPID.from_parts(int(h), int(l)) for h, l in zip(hi, lo)]
