"""Bloom filter with serialization.

Reference parity: ``src/shared/bloomfilter`` — the metadata filter uses
it to advertise which agents own which metadata entities
(``metadata_filter.h``), shipped in agent registration/heartbeat protos.
Vectorized numpy double-hashing (Kirsch-Mitzenmacher) over a byte array.
"""

from __future__ import annotations

import hashlib
import json
import math

import numpy as np


class BloomFilter:
    def __init__(self, max_entries: int, error_rate: float = 0.01):
        if not 0 < error_rate < 1 or max_entries < 1:
            raise ValueError("need max_entries >= 1 and 0 < error_rate < 1")
        n_bits = int(-max_entries * math.log(error_rate) / (math.log(2) ** 2))
        self.n_bits = max(8, n_bits)
        self.n_hashes = max(1, round(self.n_bits / max_entries * math.log(2)))
        self.bits = np.zeros((self.n_bits + 7) // 8, dtype=np.uint8)
        self.max_entries = max_entries
        self.error_rate = error_rate

    def _positions(self, item: str) -> np.ndarray:
        d = hashlib.sha256(item.encode()).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:16], "little") | 1
        i = np.arange(self.n_hashes, dtype=np.uint64)
        return (h1 + i * h2) % np.uint64(self.n_bits)

    def insert(self, item: str) -> None:
        pos = self._positions(item)
        np.bitwise_or.at(
            self.bits, (pos // 8).astype(np.int64), (1 << (pos % 8)).astype(np.uint8)
        )

    def contains(self, item: str) -> bool:
        pos = self._positions(item)
        return bool(
            np.all(self.bits[(pos // 8).astype(np.int64)] & (1 << (pos % 8)).astype(np.uint8))
        )

    # -- serialization (proto round-trip analog) -----------------------------
    def to_bytes(self) -> bytes:
        header = json.dumps(
            {
                "n_bits": self.n_bits,
                "n_hashes": self.n_hashes,
                "max_entries": self.max_entries,
                "error_rate": self.error_rate,
            }
        ).encode()
        return len(header).to_bytes(4, "little") + header + self.bits.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        hlen = int.from_bytes(data[:4], "little")
        meta = json.loads(data[4 : 4 + hlen])
        bf = cls.__new__(cls)
        bf.n_bits = meta["n_bits"]
        bf.n_hashes = meta["n_hashes"]
        bf.max_entries = meta["max_entries"]
        bf.error_rate = meta["error_rate"]
        bf.bits = np.frombuffer(data[4 + hlen :], dtype=np.uint8).copy()
        return bf
