"""Minimal DWARF (v4/v5) reader: function prototypes + struct layouts.

Reference parity: ``/root/reference/src/stirling/obj_tools/
dwarf_reader.h:148`` — ``GetFunctionArgInfo`` / ``GetStructMemberInfo``
/ ``GetStructSpec``, the debug-info layer the dynamic tracer's
"dwarvifier" rests on (``dynamic_tracer/.../dwarvifier.h``): resolving a
probed function's argument names, types, sizes and frame offsets so a
tracepoint can capture them. The reference links LLVM's DWARF library;
this is a self-contained pure-Python parser for the subset that powers
those three calls, for 64-bit little-endian ELF with 32-bit DWARF as
emitted by gcc/clang at -g.

Parsed sections: .debug_abbrev (abbreviation tables), .debug_info (DIE
trees), .debug_str/.debug_line_str (string pools), .debug_str_offsets +
.debug_addr (v5 indexed forms). Indexed DIEs: subprograms (name,
low_pc, formal parameters with frame offsets from simple
DW_OP_fbreg/DW_OP_call_frame_cfa locations), base/pointer/typedef/
const/volatile type chains, and structure types with member offsets.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from .elf import ELFError, _EHDR, _SHDR

# DWARF tags (spec §7.5.1).
TAG_compile_unit = 0x11
TAG_subprogram = 0x2E
TAG_formal_parameter = 0x05
TAG_base_type = 0x24
TAG_pointer_type = 0x0F
TAG_typedef = 0x16
TAG_const_type = 0x26
TAG_volatile_type = 0x35
TAG_structure_type = 0x13
TAG_class_type = 0x02
TAG_member = 0x0D

# Attributes.
AT_name = 0x03
AT_byte_size = 0x0B
AT_low_pc = 0x11
AT_type = 0x49
AT_data_member_location = 0x38
AT_location = 0x02
AT_linkage_name = 0x6E
AT_specification = 0x47
AT_str_offsets_base = 0x72
AT_addr_base = 0x73

DW_OP_fbreg = 0x91


class DwarfError(ELFError):
    pass


@dataclass(frozen=True)
class ArgInfo:
    """One formal parameter (dwarf_reader.h ArgInfo analog)."""

    name: str
    type_name: str
    byte_size: int
    #: Frame-base-relative offset from a simple DW_OP_fbreg location
    #: (None when the location is register-allocated or complex).
    frame_offset: int | None = None


@dataclass(frozen=True)
class FunctionInfo:
    name: str
    low_pc: int
    args: tuple


@dataclass(frozen=True)
class MemberInfo:
    """Struct member (GetStructMemberInfo analog)."""

    name: str
    offset: int
    type_name: str
    byte_size: int


def _uleb(d: bytes, pos: int):
    v = shift = 0
    while True:
        b = d[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return v, pos


def _sleb(d: bytes, pos: int):
    v = shift = 0
    while True:
        b = d[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            if b & 0x40:
                v -= 1 << shift
            return v, pos


def _cstr(d: bytes, pos: int) -> tuple[str, int]:
    end = d.find(b"\0", pos)
    return d[pos:end].decode("utf-8", "replace"), end + 1


class _Sections:
    """ELF section extraction (shares the elf.py header structs)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            d = f.read()
        if len(d) < _EHDR.size or d[:4] != b"\x7fELF":
            raise DwarfError(f"{path}: not an ELF file")
        if d[4] != 2 or d[5] != 1:
            raise DwarfError(f"{path}: only 64-bit little-endian supported")
        (*_h, shoff, _flags, _ehsize, _phes, _phnum, shentsize, shnum,
         shstrndx) = _EHDR.unpack_from(d, 0)
        str_sh = _SHDR.unpack_from(d, shoff + shstrndx * shentsize)
        strtab_off = str_sh[4]
        self.sections: dict[str, bytes] = {}
        for i in range(shnum):
            (nm, _ty, _fl, _addr, off, size, _lnk, _inf, _al,
             _ent) = _SHDR.unpack_from(d, shoff + i * shentsize)
            name, _ = _cstr(d, strtab_off + nm)
            if name.startswith(".debug_"):
                self.sections[name] = d[off:off + size]


class _Abbrev:
    """One abbreviation table: code -> (tag, children, attr specs)."""

    def __init__(self, data: bytes, offset: int):
        self.entries: dict[int, tuple] = {}
        pos = offset
        while pos < len(data):
            code, pos = _uleb(data, pos)
            if code == 0:
                break
            tag, pos = _uleb(data, pos)
            children = data[pos]
            pos += 1
            specs = []
            while True:
                attr, pos = _uleb(data, pos)
                form, pos = _uleb(data, pos)
                iconst = None
                if form == 0x21:  # implicit_const
                    iconst, pos = _sleb(data, pos)
                if attr == 0 and form == 0:
                    break
                specs.append((attr, form, iconst))
            self.entries[code] = (tag, bool(children), tuple(specs))


class DwarfReader:
    """Indexes subprograms, types and structs from .debug_info.

    API mirror of the reference DwarfReader: ``get_function_arg_info``,
    ``get_struct_member_info``, ``get_struct_spec``, plus the function
    index itself (``functions``).
    """

    def __init__(self, path: str):
        s = _Sections(path)
        self._info = s.sections.get(".debug_info", b"")
        self._abbrev_data = s.sections.get(".debug_abbrev", b"")
        self._str = s.sections.get(".debug_str", b"")
        self._line_str = s.sections.get(".debug_line_str", b"")
        self._str_offsets = s.sections.get(".debug_str_offsets", b"")
        self._addr = s.sections.get(".debug_addr", b"")
        if not self._info or not self._abbrev_data:
            raise DwarfError(f"{path}: no DWARF debug info (compile with -g)")
        self.functions: dict[str, FunctionInfo] = {}
        self.structs: dict[str, tuple] = {}  # name -> tuple[MemberInfo]
        self._types: dict[int, tuple] = {}  # DIE offset -> (kind, payload)
        # Type refs may point FORWARD in the DIE stream; function/struct
        # payloads collect raw attrs during the walk and resolve here
        # once every type DIE is indexed.
        self._pending_fns: list = []
        self._pending_structs: list = []
        self._parse_all()
        for payload in self._pending_fns:
            self._finish_fn(payload)
        for payload in self._pending_structs:
            self._finish_struct(payload)
        del self._pending_fns, self._pending_structs

    # -- parsing -------------------------------------------------------------
    def _parse_all(self):
        pos = 0
        while pos + 11 <= len(self._info):
            pos = self._parse_cu(pos)

    def _parse_cu(self, cu_off: int) -> int:
        d = self._info
        (unit_len,) = struct.unpack_from("<I", d, cu_off)
        if unit_len in (0, 0xFFFFFFFF):
            return len(d)  # 64-bit DWARF / padding: stop
        end = cu_off + 4 + unit_len
        (version,) = struct.unpack_from("<H", d, cu_off + 4)
        if version == 5:
            unit_type = d[cu_off + 6]
            addr_size = d[cu_off + 7]
            (abbrev_off,) = struct.unpack_from("<I", d, cu_off + 8)
            pos = cu_off + 12
            if unit_type not in (1, 3):  # compile/partial units only
                return end
        elif version == 4 or version == 3 or version == 2:
            (abbrev_off,) = struct.unpack_from("<I", d, cu_off + 6)
            addr_size = d[cu_off + 10]
            pos = cu_off + 11
        else:
            return end
        abbrev = _Abbrev(self._abbrev_data, abbrev_off)
        # v5 indexed-form bases (defaults per spec: header-sized offsets).
        ctx = {
            "version": version, "addr_size": addr_size, "cu_off": cu_off,
            "str_offsets_base": 8, "addr_base": 8,
        }
        stack: list = []  # parent DIE frames: (tag, payload)
        pending_fn: list = []  # subprogram frames awaiting pop
        while pos < end:
            die_off = pos
            code, pos = _uleb(d, pos)
            if code == 0:
                if stack:
                    tag, payload = stack.pop()
                    if tag == TAG_subprogram and payload is not None:
                        self._pending_fns.append(payload)
                    elif (tag in (TAG_structure_type, TAG_class_type)
                          and payload is not None):
                        self._pending_structs.append(payload)
                continue
            entry = abbrev.entries.get(code)
            if entry is None:
                break  # malformed: abandon this CU
            tag, children, specs = entry
            attrs = {}
            for attr, form, iconst in specs:
                val, pos = self._read_form(d, pos, form, iconst, ctx)
                if attr in (AT_name, AT_byte_size, AT_low_pc, AT_type,
                            AT_data_member_location, AT_location,
                            AT_linkage_name, AT_specification,
                            AT_str_offsets_base, AT_addr_base):
                    attrs[attr] = val
            if tag == TAG_compile_unit:
                if AT_str_offsets_base in attrs:
                    ctx["str_offsets_base"] = attrs[AT_str_offsets_base]
                if AT_addr_base in attrs:
                    ctx["addr_base"] = attrs[AT_addr_base]
            self._index_die(die_off, tag, attrs, ctx, stack)
            if children:
                payload = None
                if tag == TAG_subprogram:
                    payload = {"attrs": attrs, "ctx": ctx, "params": []}
                elif tag in (TAG_structure_type, TAG_class_type):
                    payload = {"attrs": attrs, "ctx": ctx, "members": [],
                               "off": die_off}
                stack.append((tag, payload))
            elif tag == TAG_subprogram:
                self._pending_fns.append(
                    {"attrs": attrs, "ctx": ctx, "params": []}
                )
        return end

    def _index_die(self, off, tag, attrs, ctx, stack):
        if tag == TAG_base_type:
            self._types[off] = ("base", attrs.get(AT_name),
                                attrs.get(AT_byte_size, 0))
        elif tag == TAG_pointer_type:
            self._types[off] = ("ptr", attrs.get(AT_type), 8)
        elif tag in (TAG_typedef, TAG_const_type, TAG_volatile_type):
            self._types[off] = ("alias", attrs.get(AT_type),
                                attrs.get(AT_name))
        elif tag in (TAG_structure_type, TAG_class_type):
            self._types[off] = ("struct", attrs.get(AT_name),
                                attrs.get(AT_byte_size, 0))
        elif tag == TAG_formal_parameter and stack:
            for ptag, payload in reversed(stack):
                if ptag == TAG_subprogram and payload is not None:
                    payload["params"].append(attrs)
                    break
        elif tag == TAG_member and stack:
            ptag, payload = stack[-1]
            if ptag in (TAG_structure_type, TAG_class_type) and payload:
                payload["members"].append(attrs)

    def _finish_fn(self, payload):
        attrs = payload["attrs"]
        name = attrs.get(AT_name) or attrs.get(AT_linkage_name)
        if not name or AT_low_pc not in attrs:
            return
        args = []
        for p in payload["params"]:
            tname, tsize = self._resolve_type(p.get(AT_type))
            args.append(ArgInfo(
                name=p.get(AT_name) or f"arg{len(args)}",
                type_name=tname, byte_size=tsize,
                frame_offset=_fbreg_offset(p.get(AT_location)),
            ))
        self.functions[name] = FunctionInfo(
            name=name, low_pc=int(attrs[AT_low_pc] or 0), args=tuple(args)
        )

    def _finish_struct(self, payload):
        attrs = payload["attrs"]
        name = attrs.get(AT_name)
        if not name:
            return
        members = []
        for m in payload["members"]:
            tname, tsize = self._resolve_type(m.get(AT_type))
            off = m.get(AT_data_member_location)
            members.append(MemberInfo(
                name=m.get(AT_name) or "", offset=int(off or 0),
                type_name=tname, byte_size=tsize,
            ))
        self.structs[name] = tuple(members)

    def _resolve_type(self, ref, depth: int = 0) -> tuple[str, int]:
        """Follow a DW_AT_type reference chain to (type name, size)."""
        if ref is None or depth > 16:
            return ("void", 0)
        t = self._types.get(ref)
        if t is None:
            return ("?", 0)
        kind = t[0]
        if kind == "base":
            return (t[1] or "?", int(t[2] or 0))
        if kind == "ptr":
            inner, _sz = self._resolve_type(t[1], depth + 1)
            return (inner + "*", 8)
        if kind == "alias":
            inner, sz = self._resolve_type(t[1], depth + 1)
            return (t[2] or inner, sz)
        if kind == "struct":
            return ("struct " + (t[1] or "?"), int(t[2] or 0))
        return ("?", 0)

    # -- form decoding --------------------------------------------------------
    def _read_form(self, d, pos, form, iconst, ctx):
        asz = ctx["addr_size"]
        if form == 0x01:  # addr
            v = int.from_bytes(d[pos:pos + asz], "little")
            return v, pos + asz
        if form in (0x0B, 0x21):  # data1 / implicit_const
            if form == 0x21:
                return iconst, pos
            return d[pos], pos + 1
        if form == 0x05:
            return int.from_bytes(d[pos:pos + 2], "little"), pos + 2
        if form == 0x06:
            return int.from_bytes(d[pos:pos + 4], "little"), pos + 4
        if form == 0x07:
            return int.from_bytes(d[pos:pos + 8], "little"), pos + 8
        if form == 0x0D:
            return _sleb(d, pos)
        if form == 0x0F:
            return _uleb(d, pos)
        if form == 0x08:  # string (inline)
            return _cstr(d, pos)
        if form == 0x0E:  # strp
            (off,) = struct.unpack_from("<I", d, pos)
            return _cstr(self._str, off)[0], pos + 4
        if form == 0x1F:  # line_strp
            (off,) = struct.unpack_from("<I", d, pos)
            return _cstr(self._line_str, off)[0], pos + 4
        if form == 0x11:  # ref1
            return ctx["cu_off"] + d[pos], pos + 1
        if form == 0x12:
            return ctx["cu_off"] + int.from_bytes(d[pos:pos + 2], "little"), pos + 2
        if form == 0x13:  # ref4
            return ctx["cu_off"] + int.from_bytes(d[pos:pos + 4], "little"), pos + 4
        if form == 0x14:  # ref8
            return ctx["cu_off"] + int.from_bytes(d[pos:pos + 8], "little"), pos + 8
        if form == 0x15:  # ref_udata
            v, pos = _uleb(d, pos)
            return ctx["cu_off"] + v, pos
        if form == 0x10:  # ref_addr (section-relative, already absolute)
            return int.from_bytes(d[pos:pos + 4], "little"), pos + 4
        if form == 0x17:  # sec_offset
            return int.from_bytes(d[pos:pos + 4], "little"), pos + 4
        if form == 0x18:  # exprloc
            n, pos = _uleb(d, pos)
            return d[pos:pos + n], pos + n
        if form == 0x0C:  # flag
            return bool(d[pos]), pos + 1
        if form == 0x19:  # flag_present
            return True, pos
        if form in (0x1A, 0x25, 0x26, 0x27, 0x28):  # strx*
            if form == 0x1A:
                idx, pos = _uleb(d, pos)
            else:
                n = form - 0x24
                idx = int.from_bytes(d[pos:pos + n], "little")
                pos += n
            base = ctx["str_offsets_base"]
            so = base + idx * 4
            if so + 4 <= len(self._str_offsets):
                (off,) = struct.unpack_from("<I", self._str_offsets, so)
                return _cstr(self._str, off)[0], pos
            return "", pos
        if form in (0x1B, 0x29, 0x2A, 0x2B, 0x2C):  # addrx*
            if form == 0x1B:
                idx, pos = _uleb(d, pos)
            else:
                n = form - 0x28
                idx = int.from_bytes(d[pos:pos + n], "little")
                pos += n
            base = ctx["addr_base"]
            ao = base + idx * asz
            if ao + asz <= len(self._addr):
                return int.from_bytes(self._addr[ao:ao + asz], "little"), pos
            return 0, pos
        if form in (0x22, 0x23):  # loclistx / rnglistx
            return _uleb(d, pos)
        if form == 0x0A:  # block1
            n = d[pos]
            return d[pos + 1:pos + 1 + n], pos + 1 + n
        if form == 0x03:  # block2
            n = int.from_bytes(d[pos:pos + 2], "little")
            return d[pos + 2:pos + 2 + n], pos + 2 + n
        if form == 0x04:  # block4
            n = int.from_bytes(d[pos:pos + 4], "little")
            return d[pos + 4:pos + 4 + n], pos + 4 + n
        if form == 0x09:  # block
            n, pos = _uleb(d, pos)
            return d[pos:pos + n], pos + n
        if form == 0x1E:  # data16
            return d[pos:pos + 16], pos + 16
        if form == 0x20:  # ref_sig8
            return int.from_bytes(d[pos:pos + 8], "little"), pos + 8
        raise DwarfError(f"unsupported DWARF form {form:#x}")

    # -- reference-API surface ------------------------------------------------
    def get_function_arg_info(self, name: str) -> tuple:
        """ArgInfo tuple for a function (dwarf_reader.h GetFunctionArgInfo)."""
        fn = self.functions.get(name)
        if fn is None:
            raise KeyError(f"no DWARF subprogram named {name!r}")
        return fn.args

    def get_struct_member_info(self, struct_name: str, member: str) -> MemberInfo:
        for m in self.structs.get(struct_name, ()):
            if m.name == member:
                return m
        raise KeyError(f"no member {member!r} in struct {struct_name!r}")

    def get_struct_spec(self, struct_name: str) -> tuple:
        """Flat member layout (GetStructSpec analog)."""
        if struct_name not in self.structs:
            raise KeyError(f"no struct named {struct_name!r}")
        return self.structs[struct_name]


def _fbreg_offset(loc) -> int | None:
    """Frame offset from a simple DW_OP_fbreg exprloc, else None."""
    if not isinstance(loc, (bytes, bytearray)) or not loc:
        return None
    if loc[0] != DW_OP_fbreg:
        return None
    off, _ = _sleb(bytes(loc), 1)
    return off
