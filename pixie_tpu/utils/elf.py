"""Minimal ELF symbol reader: address -> symbol resolution.

Reference parity: the object tools the continuous profiler uses to
symbolize native frames (``/root/reference/src/stirling/obj_tools/
elf_reader.h`` — parse .symtab/.dynsym, binary-search FUNC symbols by
address). Pure-Python struct parsing, 64-bit little-endian ELF (the
only flavor this framework deploys on); no DWARF line info — symbol
granularity is what flamegraphs need.
"""

from __future__ import annotations

import bisect
import struct
from dataclasses import dataclass

_EHDR = struct.Struct("<16sHHIQQQIHHHHHH")
_SHDR = struct.Struct("<IIQQQQIIQQ")
_SYM = struct.Struct("<IBBHQQ")

_SHT_SYMTAB = 2
_SHT_DYNSYM = 11
_STT_FUNC = 2


class ELFError(ValueError):
    pass


@dataclass(frozen=True)
class Symbol:
    name: str
    addr: int
    size: int


class ELFReader:
    """Parses symbols once; ``addr_to_symbol`` binary-searches FUNCs."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self._data = f.read()
        d = self._data
        if len(d) < _EHDR.size or d[:4] != b"\x7fELF":
            raise ELFError(f"{path}: not an ELF file")
        if d[4] != 2 or d[5] != 1:
            raise ELFError(f"{path}: only 64-bit little-endian supported")
        (_ident, _type, _machine, _ver, _entry, _phoff, shoff, _flags,
         _ehsize, _phes, _phnum, shentsize, shnum, _shstrndx) = _EHDR.unpack_from(d, 0)
        self.symbols: list[Symbol] = []
        seen = set()
        for i in range(shnum):
            off = shoff + i * shentsize
            (_name, sh_type, _fl, _addr, sh_off, sh_size, sh_link, _info,
             _align, sh_entsize) = _SHDR.unpack_from(d, off)
            if sh_type not in (_SHT_SYMTAB, _SHT_DYNSYM) or sh_entsize == 0:
                continue
            # linked string table section
            stroff = shoff + sh_link * shentsize
            (_n, _t, _f, _a, str_off, str_size, _l, _i2, _al, _es) = _SHDR.unpack_from(d, stroff)
            strtab = d[str_off:str_off + str_size]
            for j in range(sh_size // sh_entsize):
                name_i, info, _other, _shndx, value, size = _SYM.unpack_from(
                    d, sh_off + j * sh_entsize
                )
                if info & 0xF != _STT_FUNC or value == 0:
                    continue
                end = strtab.find(b"\0", name_i)
                name = strtab[name_i:end].decode("latin-1")
                if not name or (value, name) in seen:
                    continue
                seen.add((value, name))
                self.symbols.append(Symbol(name, value, size))
        self.symbols.sort(key=lambda s: s.addr)
        self._addrs = [s.addr for s in self.symbols]

    def addr_to_symbol(self, addr: int) -> str | None:
        """Symbol containing ``addr`` (ElfReader::AddrToSymbol)."""
        i = bisect.bisect_right(self._addrs, addr) - 1
        if i < 0:
            return None
        s = self.symbols[i]
        if s.size and addr >= s.addr + s.size:
            return None
        return s.name

    def symbol_addr(self, name: str) -> int | None:
        for s in self.symbols:
            if s.name == name:
                return s.addr
        return None
