"""Shared utilities: datastore, bloom filter, UPID."""

from .bloomfilter import BloomFilter
from .datastore import Datastore, MemoryDatastore, SqliteDatastore
from .upid import UPID

__all__ = [
    "BloomFilter",
    "Datastore",
    "MemoryDatastore",
    "SqliteDatastore",
    "UPID",
]
