"""Persistent-XLA-cache location, keyed by host CPU features.

XLA:CPU AOT results embed target machine features; loading a cache entry
compiled on a different host warns "could lead to execution errors such
as SIGILL". Benchmark/driver entry points in this repo may run on
different machines that share /tmp, so the cache directory name includes
a hash of the host's CPU flags — a foreign-host cache simply misses.
"""

from __future__ import annotations

import hashlib
import os


def jax_cache_dir() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            flags = next((ln for ln in f if ln.startswith("flags")), "")
    except OSError:
        flags = ""
    key = hashlib.md5(flags.encode()).hexdigest()[:10]
    return f"/tmp/pixie_tpu_jax_cache_{key}"


def configure_jax_cache(env: dict | None = None) -> str:
    """Point JAX's persistent compilation cache at the host-keyed dir.

    Mutates ``env`` (default ``os.environ``); call before jax init.
    """
    env = os.environ if env is None else env
    d = jax_cache_dir()
    env["JAX_COMPILATION_CACHE_DIR"] = d
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    return d


def scrubbed_cpu_env(n_devices: int | None = None, base: dict | None = None) -> dict:
    """A fresh-subprocess env that runs jax on CPU with axon disabled.

    The axon TPU-tunnel plugin registers at interpreter boot via
    sitecustomize and claims an exclusive relay session in every process
    that initializes jax — even under JAX_PLATFORMS=cpu — so CPU-only
    subprocesses must clear PALLAS_AXON_POOL_IPS BEFORE the interpreter
    starts (run_tests.sh / tests/conftest.py document the same rule).
    """
    env = dict(os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    if "JAX_COMPILATION_CACHE_DIR" not in env:
        configure_jax_cache(env)
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    if n_devices is not None:
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
        env["XLA_FLAGS"] = " ".join(flags)
    return env
