// Native N:M hash equijoin over packed i64 key ids.
//
// Reference parity: Carnot's EquijoinNode build+probe hash join
// (src/carnot/exec/equijoin_node.cc) — the engine's CPU-backend N:M
// path previously used numpy argsort + searchsorted, which pays
// O(n log n) sorts and several full passes; this is the classic
// open-addressing build+probe at O(n), one core.
//
// C ABI (ctypes), single call, two internal passes:
//   needed = hash_join(bk, nb, pk, np, left_outer, l_idx, r_idx, cap)
// - bk/pk: i64 key planes (the engine packs multi-column keys to dense
//   i64 ids first, joins._packed_key_ids).
// - Returns the total number of output pairs. When needed <= cap the
//   outputs are filled: l_idx/r_idx i32 row indices (r_idx -1 for an
//   unmatched probe kept by left_outer). When needed > cap nothing is
//   written — the caller re-allocates and calls again.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint64_t mix64(uint64_t x) {
  // splitmix64 finalizer: cheap, well-distributed for table indexing.
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

}  // namespace

extern "C" {

long long hash_join(const long long* bk, long long nb, const long long* pk,
                    long long np, int left_outer, int32_t* l_idx,
                    int32_t* r_idx, long long cap) {
  // Table size: power of two >= 2 * nb (load factor <= 0.5).
  uint64_t tsize = 16;
  while (tsize < uint64_t(nb) * 2) tsize <<= 1;
  const uint64_t mask = tsize - 1;
  std::vector<int32_t> heads(tsize, -1);
  std::vector<int32_t> next(size_t(nb > 0 ? nb : 1), -1);
  // Build: duplicate keys chain through next[]; insert in REVERSE so
  // probing walks the chain in ascending build-row order.
  for (long long i = nb - 1; i >= 0; --i) {
    uint64_t h = mix64(uint64_t(bk[i])) & mask;
    while (heads[h] != -1 && bk[heads[h]] != bk[i]) h = (h + 1) & mask;
    next[i] = heads[h];
    heads[h] = int32_t(i);
  }
  // Pass 1: count output pairs.
  long long total = 0;
  for (long long i = 0; i < np; ++i) {
    uint64_t h = mix64(uint64_t(pk[i])) & mask;
    while (heads[h] != -1 && bk[heads[h]] != pk[i]) h = (h + 1) & mask;
    int32_t j = heads[h];
    if (j == -1) {
      if (left_outer) ++total;
      continue;
    }
    for (; j != -1; j = next[j]) ++total;
  }
  if (total > cap || l_idx == nullptr) return total;
  // Pass 2: fill.
  long long k = 0;
  for (long long i = 0; i < np; ++i) {
    uint64_t h = mix64(uint64_t(pk[i])) & mask;
    while (heads[h] != -1 && bk[heads[h]] != pk[i]) h = (h + 1) & mask;
    int32_t j = heads[h];
    if (j == -1) {
      if (left_outer) {
        l_idx[k] = int32_t(i);
        r_idx[k] = -1;
        ++k;
      }
      continue;
    }
    for (; j != -1; j = next[j]) {
      l_idx[k] = int32_t(i);
      r_idx[k] = j;
      ++k;
    }
  }
  return total;
}

}  // extern "C"
