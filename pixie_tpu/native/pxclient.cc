// Native (C++) netbus client: execute PxL scripts against a deployed
// broker and print the result tables.
//
// Reference parity: the Go client library + CLI
// (/root/reference/src/api/go/pxapi/client.go:41-54 Client.ExecuteScript;
// src/pixie_cli) — the reference ships native clients alongside the
// Python API; this is that surface for this runtime. Speaks the framed-
// TCP netbus (services/netbus.py: 4-byte LE length + versioned wire
// codec, services/wire.py) including the bearer-token handshake
// (services/auth.py sign_token: HMAC-SHA256 over a base64url JSON
// payload).
//
// Build:  g++ -O3 -std=c++17 -pthread -o pxclient pxclient.cc
// Usage:  pxclient [--host H] [--port P] [--secret S|--token T]
//                  [--timeout SEC] [--stream [--updates N]]
//                  (--pxl CODE | --script FILE | --list)
//
// --stream runs the query live (broker.execute_stream, the reference's
// StreamResults flow): updates print as they arrive, and after N
// updates (default 3) the client cancels server-side and exits.
//
// No dependencies beyond libc/libstdc++ (SHA-256 is implemented here so
// auth works without OpenSSL).

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <fstream>
#include <iostream>
#include <map>
#include <memory>
#include <random>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

// ---------------------------------------------------------------------------
// SHA-256 + HMAC (FIPS 180-4), for auth.py-compatible token signing.
// ---------------------------------------------------------------------------
namespace sha256 {

struct Ctx {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;
};

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void init(Ctx* c) {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(c->h, H0, sizeof(H0));
  c->len = 0;
  c->buf_len = 0;
}

static void block(Ctx* c, const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1; d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx* c, const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  c->len += n;
  while (n > 0) {
    size_t take = std::min(n, sizeof(c->buf) - c->buf_len);
    memcpy(c->buf + c->buf_len, p, take);
    c->buf_len += take;
    p += take;
    n -= take;
    if (c->buf_len == 64) {
      block(c, c->buf);
      c->buf_len = 0;
    }
  }
}

static void final(Ctx* c, uint8_t out[32]) {
  uint64_t bits = c->len * 8;
  uint8_t pad = 0x80;
  update(c, &pad, 1);
  uint8_t zero = 0;
  while (c->buf_len != 56) update(c, &zero, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
  c->len -= 8;  // length bytes don't count
  update(c, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(c->h[i] >> 24);
    out[4 * i + 1] = uint8_t(c->h[i] >> 16);
    out[4 * i + 2] = uint8_t(c->h[i] >> 8);
    out[4 * i + 3] = uint8_t(c->h[i]);
  }
}

static void digest(const void* data, size_t n, uint8_t out[32]) {
  Ctx c;
  init(&c);
  update(&c, data, n);
  final(&c, out);
}

}  // namespace sha256

static std::string hmac_sha256_hex(const std::string& key,
                                   const std::string& msg) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    sha256::digest(key.data(), key.size(), k);
  } else {
    memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  sha256::Ctx c;
  uint8_t inner[32], outer[32];
  sha256::init(&c);
  sha256::update(&c, ipad, 64);
  sha256::update(&c, msg.data(), msg.size());
  sha256::final(&c, inner);
  sha256::init(&c);
  sha256::update(&c, opad, 64);
  sha256::update(&c, inner, 32);
  sha256::final(&c, outer);
  static const char* hex = "0123456789abcdef";
  std::string out;
  for (int i = 0; i < 32; i++) {
    out += hex[outer[i] >> 4];
    out += hex[outer[i] & 15];
  }
  return out;
}

static std::string b64url_nopad(const std::string& in) {
  static const char* tbl =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";
  std::string out;
  size_t i = 0;
  while (i + 3 <= in.size()) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8) |
                 uint8_t(in[i + 2]);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  size_t rem = in.size() - i;
  if (rem == 1) {
    uint32_t v = uint8_t(in[i]) << 16;
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
  } else if (rem == 2) {
    uint32_t v = (uint8_t(in[i]) << 16) | (uint8_t(in[i + 1]) << 8);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
  }
  return out;
}

// auth.py sign_token parity: base64url(JSON{sub,exp,claims}) + "." +
// HMAC-SHA256-hex. JSON must be compact + sort_keys to match the
// verifier's canonical form (it re-signs the body, so any valid JSON
// works — but keep the same shape for clarity).
static std::string sign_token(const std::string& secret,
                              const std::string& subject, double ttl_s) {
  double exp = double(time(nullptr)) + ttl_s;
  std::ostringstream js;
  js.precision(10);
  js << "{\"claims\":{},\"exp\":" << std::fixed << exp << ",\"sub\":\""
     << subject << "\"}";
  std::string body = b64url_nopad(js.str());
  return body + "." + hmac_sha256_hex(secret, body);
}

// ---------------------------------------------------------------------------
// Wire codec (services/wire.py v4): tag-prefixed recursive values.
// ---------------------------------------------------------------------------
struct Value;
using ValuePtr = std::shared_ptr<Value>;

struct NdArray {
  std::string dtype;  // numpy dtype.str, e.g. "<i8"
  bool scalar = false;
  std::vector<uint64_t> shape;
  std::string data;            // raw bytes (numeric)
  std::vector<ValuePtr> objs;  // object arrays ("G")
  bool is_object = false;
  size_t n_elems() const {
    size_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
};

struct Value {
  enum Kind { NUL, BOOL, INT, BIGINT, REAL, STR, BYTES, ARR, LIST, MAP, ENUM,
              OBJ } kind = NUL;
  bool b = false;
  int64_t i = 0;
  double d = 0;
  std::string s;  // STR/BYTES/ENUM/BIGINT(decimal text)
  NdArray arr;
  std::vector<ValuePtr> list;  // LIST (and tuples)
  std::vector<std::pair<ValuePtr, ValuePtr>> map;
  uint16_t obj_tid = 0;
  ValuePtr obj_fields;  // MAP value

  const Value* get(const std::string& key) const {
    for (auto& kv : map)
      if (kv.first->kind == STR && kv.first->s == key) return kv.second.get();
    return nullptr;
  }
};

class Decoder {
 public:
  Decoder(const uint8_t* p, size_t n) : p_(p), n_(n) {}

  ValuePtr decode() {
    ValuePtr v = one();
    if (pos_ != n_) throw std::runtime_error("trailing bytes after value");
    return v;
  }

 private:
  const uint8_t* p_;
  size_t n_, pos_ = 0;

  uint8_t byte() {
    need(1);
    return p_[pos_++];
  }
  void need(size_t k) {
    if (pos_ + k > n_) throw std::runtime_error("wire truncated");
  }
  uint16_t u16() {
    need(2);
    uint16_t v;
    memcpy(&v, p_ + pos_, 2);
    pos_ += 2;
    return v;  // little-endian host assumed (x86/arm64)
  }
  uint32_t u32() {
    need(4);
    uint32_t v;
    memcpy(&v, p_ + pos_, 4);
    pos_ += 4;
    return v;
  }
  int64_t i64() {
    need(8);
    int64_t v;
    memcpy(&v, p_ + pos_, 8);
    pos_ += 8;
    return v;
  }
  double f64() {
    need(8);
    double v;
    memcpy(&v, p_ + pos_, 8);
    pos_ += 8;
    return v;
  }
  std::string bytes(size_t k) {
    need(k);
    std::string s(reinterpret_cast<const char*>(p_ + pos_), k);
    pos_ += k;
    return s;
  }

  ValuePtr one() {
    auto v = std::make_shared<Value>();
    uint8_t tag = byte();
    switch (tag) {
      case 'N': v->kind = Value::NUL; break;
      case 'T': v->kind = Value::BOOL; v->b = true; break;
      case 'F': v->kind = Value::BOOL; v->b = false; break;
      case 'I': v->kind = Value::INT; v->i = i64(); break;
      case 'J': v->kind = Value::BIGINT; v->s = bytes(u32()); break;
      case 'D': v->kind = Value::REAL; v->d = f64(); break;
      case 'S': v->kind = Value::STR; v->s = bytes(u32()); break;
      case 'B': v->kind = Value::BYTES; v->s = bytes(u32()); break;
      case 'E': v->kind = Value::ENUM; v->s = bytes(u16()); break;
      case 'A': {
        v->kind = Value::ARR;
        v->arr.dtype = bytes(u16());
        v->arr.scalar = byte() != 0;
        uint16_t nd = u16();
        for (int k = 0; k < nd; k++) v->arr.shape.push_back(u32());
        size_t itemsize = 0;
        // dtype.str: <i8 <f8 <u8(=uint64) |b1 <i4 <u4 <f4 |u1 <M8[ns] ...
        const std::string& dt = v->arr.dtype;
        if (dt.size() >= 3) {
          char num = dt[2];
          itemsize = (num >= '0' && num <= '9') ? size_t(num - '0') : 0;
        }
        if (itemsize == 0) throw std::runtime_error("bad dtype " + dt);
        v->arr.data = bytes(v->arr.n_elems() * itemsize);
        break;
      }
      case 'G': {
        v->kind = Value::ARR;
        v->arr.is_object = true;
        uint16_t nd = u16();
        for (int k = 0; k < nd; k++) v->arr.shape.push_back(u32());
        size_t n = v->arr.n_elems();
        for (size_t k = 0; k < n; k++) v->arr.objs.push_back(one());
        break;
      }
      case 'U':
      case 'L': {
        v->kind = Value::LIST;
        uint32_t n = u32();
        for (uint32_t k = 0; k < n; k++) v->list.push_back(one());
        break;
      }
      case 'M': {
        v->kind = Value::MAP;
        uint32_t n = u32();
        for (uint32_t k = 0; k < n; k++) {
          ValuePtr key = one();
          ValuePtr val = one();
          v->map.emplace_back(key, val);
        }
        break;
      }
      case 'O': {
        v->kind = Value::OBJ;
        v->obj_tid = u16();
        v->obj_fields = one();
        if (v->obj_fields->kind != Value::MAP)
          throw std::runtime_error("object fields not a map");
        break;
      }
      default:
        throw std::runtime_error("unknown wire tag " + std::to_string(tag));
    }
    return v;
  }
};

// Minimal encoder: exactly the shapes client requests need.
class Encoder {
 public:
  std::string out;
  void enc_str(const std::string& s) {
    out += 'S';
    u32(s.size());
    out += s;
  }
  void enc_int(int64_t v) {
    out += 'I';
    out.append(reinterpret_cast<const char*>(&v), 8);
  }
  void enc_real(double v) {
    out += 'D';
    out.append(reinterpret_cast<const char*>(&v), 8);
  }
  void map_header(uint32_t n) {
    out += 'M';
    u32(n);
  }

 private:
  void u32(uint32_t v) { out.append(reinterpret_cast<const char*>(&v), 4); }
};

// ---------------------------------------------------------------------------
// Framed-TCP netbus client (netbus.py parity).
// ---------------------------------------------------------------------------
static constexpr uint8_t WIRE_VERSION = 4;  // services/wire.py

class NetbusClient {
 public:
  NetbusClient(const std::string& host, int port, double timeout_s) {
    struct addrinfo hints = {}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res)
      throw std::runtime_error("cannot resolve " + host);
    fd_ = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0 || connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      freeaddrinfo(res);
      throw std::runtime_error("cannot connect to " + host + ":" + port_s);
    }
    freeaddrinfo(res);
    struct timeval tv;
    tv.tv_sec = long(timeout_s);
    tv.tv_usec = long((timeout_s - double(tv.tv_sec)) * 1e6);
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }
  ~NetbusClient() {
    if (fd_ >= 0) close(fd_);
  }

  // payload = encoded VALUE; the codec prepends its version byte
  // (services/wire.py WIRE_VERSION).
  void send_frame(const std::string& value_bytes) {
    std::string payload;
    payload += char(WIRE_VERSION);
    payload += value_bytes;
    uint32_t len = payload.size();
    std::string frame(reinterpret_cast<const char*>(&len), 4);
    frame += payload;
    size_t off = 0;
    while (off < frame.size()) {
      ssize_t n = ::send(fd_, frame.data() + off, frame.size() - off, 0);
      if (n <= 0) throw std::runtime_error("send failed");
      off += size_t(n);
    }
  }

  ValuePtr recv_frame() {
    std::string hdr = recv_exact(4);
    uint32_t len;
    memcpy(&len, hdr.data(), 4);
    if (len > (1u << 30)) throw std::runtime_error("oversized frame");
    std::string payload = recv_exact(len);
    if (payload.empty() || uint8_t(payload[0]) != WIRE_VERSION)
      throw std::runtime_error("wire version mismatch");
    Decoder dec(reinterpret_cast<const uint8_t*>(payload.data()) + 1,
                payload.size() - 1);
    return dec.decode();
  }

  void auth(const std::string& token) {
    Encoder e;
    e.map_header(2);
    e.enc_str("op");
    e.enc_str("auth");
    e.enc_str("token");
    e.enc_str(token);
    send_frame(e.out);
    ValuePtr reply = recv_frame();
    const Value* op = reply->get("op");
    if (!op || op->s != "auth_ok") {
      const Value* err = reply->get("error");
      throw std::runtime_error("auth failed: " +
                               (err ? err->s : std::string("?")));
    }
  }

  void subscribe(const std::string& topic, int64_t sid) {
    Encoder e;
    e.map_header(3);
    e.enc_str("op");
    e.enc_str("sub");
    e.enc_str("topic");
    e.enc_str(topic);
    e.enc_str("sid");
    e.enc_int(sid);
    send_frame(e.out);
  }

  // Publish a {str: str|int|double} request with a _reply_to inbox.
  void publish_request(const std::string& topic,
                       const std::vector<std::pair<std::string, ValuePtr>>& kv,
                       const std::string& inbox) {
    Encoder msg;
    msg.map_header(kv.size() + 1);
    for (auto& [k, v] : kv) {
      msg.enc_str(k);
      switch (v->kind) {
        case Value::STR: msg.enc_str(v->s); break;
        case Value::INT: msg.enc_int(v->i); break;
        case Value::REAL: msg.enc_real(v->d); break;
        default: throw std::runtime_error("unsupported request value");
      }
    }
    msg.enc_str("_reply_to");
    msg.enc_str(inbox);
    Encoder e;
    e.map_header(3);
    e.enc_str("op");
    e.enc_str("pub");
    e.enc_str("topic");
    e.enc_str(topic);
    e.enc_str("msg");
    e.out += msg.out;
    send_frame(e.out);
  }

  // Wait for the op=="msg" frame carrying our sid.
  ValuePtr wait_reply(int64_t sid) {
    for (;;) {
      ValuePtr f = recv_frame();
      const Value* op = f->get("op");
      const Value* fsid = f->get("sid");
      if (op && op->kind == Value::STR && op->s == "msg" && fsid &&
          fsid->i == sid) {
        for (auto& kv : f->map)
          if (kv.first->s == "msg") return kv.second;
      }
    }
  }

 private:
  int fd_ = -1;
  std::string recv_exact(size_t n) {
    std::string buf;
    buf.resize(n);
    size_t off = 0;
    while (off < n) {
      ssize_t k = ::recv(fd_, buf.data() + off, n - off, 0);
      if (k <= 0) throw std::runtime_error("connection closed/timeout");
      off += size_t(k);
    }
    return buf;
  }
};

// ---------------------------------------------------------------------------
// Result printing: HostBatch (wire type id 2; Relation=0, StringDict=1 —
// the _registered_types order in services/wire.py).
// ---------------------------------------------------------------------------
static constexpr uint16_t TID_RELATION = 0;
static constexpr uint16_t TID_STRINGDICT = 1;
static constexpr uint16_t TID_HOSTBATCH = 2;

template <typename T>
static T elem(const NdArray& a, size_t i) {
  T v;
  memcpy(&v, a.data.data() + i * sizeof(T), sizeof(T));
  return v;
}

static void print_batch(const std::string& name, const Value& hb) {
  if (hb.kind != Value::OBJ || hb.obj_tid != TID_HOSTBATCH) {
    std::cout << "[" << name << "] <unexpected payload>\n";
    return;
  }
  const Value& f = *hb.obj_fields;
  const Value* rel = f.get("relation");
  const Value* cols = f.get("cols");
  const Value* dicts = f.get("dicts");
  const Value* len_v = f.get("length");
  if (!rel || !cols || !len_v || rel->kind != Value::OBJ ||
      rel->obj_tid != TID_RELATION) {
    std::cout << "[" << name << "] <malformed batch>\n";
    return;
  }
  int64_t n = len_v->i;
  // relation items: [(name, dtype-string), ...]
  std::vector<std::pair<std::string, std::string>> schema;
  const Value* items = rel->obj_fields->get("items");
  for (auto& it : items->list)
    schema.emplace_back(it->list[0]->s, it->list[1]->s);
  // per-column dictionaries
  std::map<std::string, const Value*> dict_of;
  if (dicts && dicts->kind == Value::MAP)
    for (auto& kv : dicts->map)
      if (kv.second->kind == Value::OBJ &&
          kv.second->obj_tid == TID_STRINGDICT)
        dict_of[kv.first->s] = kv.second.get();

  std::cout << "[" << name << "] " << n << " rows\n";
  for (auto& [cn, ct] : schema) std::cout << cn << "\t";
  std::cout << "\n";
  // Hoist per-column plane + dictionary resolution out of the row loop
  // (the cols map is linear-scan; doing it per cell is O(rows*cols^2)).
  struct Col {
    std::string type;
    const Value* planes = nullptr;
    const Value* strs = nullptr;  // dictionary strings list
  };
  std::vector<Col> cs;
  for (auto& [cn, ct] : schema) {
    Col c;
    c.type = ct;
    for (auto& kv : cols->map)
      if (kv.first->s == cn) c.planes = kv.second.get();
    if (c.planes && c.planes->list.empty()) c.planes = nullptr;
    auto it = dict_of.find(cn);
    if (it != dict_of.end()) c.strs = it->second->obj_fields->get("strings");
    cs.push_back(c);
  }
  for (int64_t r = 0; r < n; r++) {
    for (auto& c : cs) {
      if (!c.planes) {
        std::cout << "?\t";
        continue;
      }
      const NdArray& p0 = c.planes->list[0]->arr;
      if (c.type == "string") {
        if (p0.is_object) {  // already-decoded object column
          std::cout << p0.objs[r]->s << "\t";
        } else {
          int32_t id = elem<int32_t>(p0, r);
          if (c.strs && id >= 0 && size_t(id) < c.strs->list.size())
            std::cout << c.strs->list[id]->s << "\t";
          else
            std::cout << "<" << id << ">\t";
        }
      } else if (c.type == "uint128") {
        uint64_t hi = elem<uint64_t>(p0, r);
        uint64_t lo = elem<uint64_t>(c.planes->list[1]->arr, r);
        // UPID display form asid:pid:start (utils/upid.py layout)
        std::cout << (hi >> 32) << ":" << (hi & 0xffffffffu) << ":" << lo
                  << "\t";
      } else if (c.type == "float64") {
        std::cout << elem<double>(p0, r) << "\t";
      } else if (c.type == "boolean") {
        std::cout << (p0.data[r] ? "true" : "false") << "\t";
      } else {  // int64 / time64ns
        std::cout << elem<int64_t>(p0, r) << "\t";
      }
    }
    std::cout << "\n";
  }
}

// ---------------------------------------------------------------------------
int main(int argc, char** argv) {
  std::string host = "127.0.0.1", secret, token, pxl, script_path;
  int port = 6100;
  double timeout_s = 30.0;
  bool do_list = false, do_stream = false;
  int max_updates = 3;
  try {
    for (int i = 1; i < argc; i++) {
      std::string a = argv[i];
      auto next = [&]() -> std::string {
        if (i + 1 >= argc) throw std::runtime_error("missing value for " + a);
        return argv[++i];
      };
      if (a == "--host") host = next();
      else if (a == "--port") port = std::stoi(next());
      else if (a == "--secret") secret = next();
      else if (a == "--token") token = next();
      else if (a == "--timeout") timeout_s = std::stod(next());
      else if (a == "--pxl") pxl = next();
      else if (a == "--script") script_path = next();
      else if (a == "--list") do_list = true;
      else if (a == "--stream") do_stream = true;
      else if (a == "--updates") max_updates = std::stoi(next());
      else throw std::runtime_error("unknown arg: " + a);
    }
  } catch (const std::exception& e) {
    std::cerr << "pxclient: " << e.what() << "\n";
    return 2;
  }
  if (!script_path.empty()) {
    std::ifstream f(script_path);
    if (!f) {
      std::cerr << "cannot read " << script_path << "\n";
      return 2;
    }
    std::stringstream ss;
    ss << f.rdbuf();
    pxl = ss.str();
  }
  if (pxl.empty() && !do_list) {
    std::cerr << "usage: pxclient [--host H] [--port P] [--secret S|"
                 "--token T] [--timeout SEC] [--stream [--updates N]] "
                 "(--pxl CODE | --script FILE | --list)\n";
    return 2;
  }

  try {
    NetbusClient bus(host, port, timeout_s + 5.0);
    if (!secret.empty() && token.empty())
      token = sign_token(secret, "pxclient", 3600.0);
    if (!token.empty()) bus.auth(token);

    std::random_device rd;
    std::ostringstream inbox;
    inbox << "_inbox.native." << std::hex << rd() << rd();
    bus.subscribe(inbox.str(), 1);

    std::vector<std::pair<std::string, ValuePtr>> req;
    auto sv = [](const std::string& s) {
      auto v = std::make_shared<Value>();
      v->kind = Value::STR;
      v->s = s;
      return v;
    };
    auto dv = [](double d) {
      auto v = std::make_shared<Value>();
      v->kind = Value::REAL;
      v->d = d;
      return v;
    };
    if (do_stream && !do_list) {
      // Live query (broker.execute_stream): updates arrive on a
      // client-chosen topic as {table, batch, seq, mode} messages.
      std::ostringstream up;
      up << "client.stream.native." << std::hex << rd();
      bus.subscribe(up.str(), 2);
      req.emplace_back("query", sv(pxl));
      req.emplace_back("update_topic", sv(up.str()));
      req.emplace_back("poll_interval_s", dv(0.25));
      if (!token.empty()) req.emplace_back("token", sv(token));
      bus.publish_request("broker.execute_stream", req, inbox.str());
      std::string qid;
      bool have_reply = false;
      int updates = 0;
      while (!have_reply || updates < max_updates) {
        ValuePtr f = bus.recv_frame();
        const Value* op = f->get("op");
        if (!op || op->kind != Value::STR || op->s != "msg") continue;
        const Value* fsid = f->get("sid");
        const Value* msg = nullptr;
        for (auto& kv : f->map)
          if (kv.first->s == "msg") msg = kv.second.get();
        if (!msg) continue;
        if (fsid && fsid->i == 1) {
          const Value* ok2 = msg->get("ok");
          if (!ok2 || ok2->kind != Value::BOOL || !ok2->b) {
            const Value* err = msg->get("error");
            std::cerr << "error: " << (err ? err->s : "unknown") << "\n";
            return 1;
          }
          const Value* q = msg->get("qid");
          if (q) qid = q->s;
          have_reply = true;
        } else if (fsid && fsid->i == 2) {
          const Value* err = msg->get("error");
          if (err) {
            std::cerr << "stream error: " << err->s << "\n";
            return 1;
          }
          const Value* tbl = msg->get("table");
          const Value* seq = msg->get("seq");
          const Value* mode = msg->get("mode");
          std::cout << "-- update seq=" << (seq ? seq->i : -1) << " mode="
                    << (mode ? mode->s : "?") << "\n";
          for (auto& kv : msg->map)
            if (kv.first->s == "batch")
              print_batch(tbl ? tbl->s : "?", *kv.second);
          updates++;
        }
      }
      if (!qid.empty()) {
        std::vector<std::pair<std::string, ValuePtr>> c;
        c.emplace_back("qid", sv(qid));
        if (!token.empty()) c.emplace_back("token", sv(token));
        bus.publish_request("broker.stream_cancel", c, inbox.str());
        bus.wait_reply(1);
      }
      return 0;
    }
    std::string topic;
    if (do_list) {
      topic = "broker.scripts";
    } else {
      topic = "broker.execute";
      req.emplace_back("query", sv(pxl));
      req.emplace_back("timeout_s", dv(timeout_s));
    }
    if (!token.empty()) req.emplace_back("token", sv(token));
    bus.publish_request(topic, req, inbox.str());
    ValuePtr res = bus.wait_reply(1);

    const Value* ok = res->get("ok");
    if (!ok || ok->kind != Value::BOOL || !ok->b) {
      const Value* err = res->get("error");
      std::cerr << "error: " << (err ? err->s : "unknown") << "\n";
      return 1;
    }
    if (do_list) {
      const Value* scripts = res->get("scripts");
      if (scripts)
        for (auto& s : scripts->list) std::cout << s->s << "\n";
      return 0;
    }
    const Value* tables = res->get("tables");
    if (!tables || tables->kind != Value::MAP) {
      std::cerr << "error: reply carries no tables\n";
      return 1;
    }
    for (auto& kv : tables->map) print_batch(kv.first->s, *kv.second);
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "pxclient: " << e.what() << "\n";
    return 1;
  }
}
