// Native hot/cold columnar table store.
//
// Reference parity: src/table_store/table/table.h:104 (hot/cold Table with
// unique row-id accounting, time index, byte-budget expiry, compaction) and
// table_store.h:79 (AppendData push target). The reference keeps hot writes
// in ColumnWrapper batches and compacts to Arrow cold batches; here both
// stores are plain per-column slabs sized for zero-conversion staging into
// pinned host buffers (the HBM transfer path wants contiguous fixed-width
// columns, not Arrow framing).
//
// Concurrency: one writer (ingest) + many readers (queries). A single
// mutex guards batch lists; reads copy out under the lock (bulk memcpy),
// so no view can dangle across compaction/expiry — the zero-copy-unsafe
// alternative is why reads here are copy-out by design.
//
// C ABI only (consumed via ctypes from pixie_tpu/table_store/table.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <vector>

namespace {

struct Batch {
  int64_t first_row_id = 0;
  int64_t n = 0;
  int64_t min_time = 0;
  int64_t max_time = 0;
  int64_t bytes = 0;
  // One slab per column, each n * elem_size bytes.
  std::vector<std::unique_ptr<char[]>> cols;

  int64_t end_row_id() const { return first_row_id + n; }
};

struct Stats {
  int64_t batches_added = 0;
  int64_t batches_expired = 0;
  int64_t bytes_added = 0;
  int64_t bytes_expired = 0;
  int64_t compacted_batches = 0;
};

struct Table {
  std::vector<int32_t> elem_sizes;
  int64_t row_bytes = 0;
  int64_t compacted_rows = 0;  // target rows per cold batch
  int64_t max_bytes = -1;      // -1 = unbounded
  bool has_time = false;

  std::mutex mu;
  std::deque<Batch> hot;
  std::deque<Batch> cold;
  int64_t hot_bytes = 0;
  int64_t cold_bytes = 0;
  int64_t next_row_id = 0;
  Stats stats;

  int64_t first_row_id_locked() const {
    if (!cold.empty()) return cold.front().first_row_id;
    if (!hot.empty()) return hot.front().first_row_id;
    return next_row_id;
  }

  // Expire oldest batches until under budget. Oldest data lives at the cold
  // front; once cold is empty the hot front is oldest (reference
  // Table::ExpireBatch ordering).
  void expire_locked(int64_t incoming_bytes) {
    if (max_bytes < 0) return;
    while (hot_bytes + cold_bytes + incoming_bytes > max_bytes) {
      std::deque<Batch>* q = !cold.empty() ? &cold : (!hot.empty() ? &hot : nullptr);
      if (q == nullptr) break;
      Batch& b = q->front();
      (q == &cold ? cold_bytes : hot_bytes) -= b.bytes;
      stats.batches_expired++;
      stats.bytes_expired += b.bytes;
      q->pop_front();
    }
  }
};

// Copy rows [row_id, ...) from b into out at out_row, up to max rows total.
int64_t copy_from_batch(const Table& t, const Batch& b, int64_t row_id,
                        int64_t out_row, int64_t max_rows, void** out_cols) {
  int64_t start = std::max<int64_t>(0, row_id - b.first_row_id);
  int64_t take = std::min(b.n - start, max_rows - out_row);
  if (take <= 0) return 0;
  for (size_t c = 0; c < t.elem_sizes.size(); ++c) {
    int32_t es = t.elem_sizes[c];
    std::memcpy(static_cast<char*>(out_cols[c]) + out_row * es,
                b.cols[c].get() + start * es, take * es);
  }
  return take;
}

}  // namespace

extern "C" {

Table* pxt_table_create(int32_t ncols, const int32_t* elem_sizes,
                        int32_t has_time_col, int64_t compacted_rows,
                        int64_t max_bytes) {
  auto* t = new Table();
  t->elem_sizes.assign(elem_sizes, elem_sizes + ncols);
  for (int32_t es : t->elem_sizes) t->row_bytes += es;
  t->compacted_rows = compacted_rows > 0 ? compacted_rows : 64 * 1024;
  t->max_bytes = max_bytes;
  t->has_time = has_time_col != 0;
  return t;
}

void pxt_table_destroy(Table* t) { delete t; }

// Append n rows. cols[i] points at n*elem_sizes[i] bytes of column data;
// times points at n int64 values (ignored when the table has no time
// column). Returns the first assigned row id, or -1 on error.
int64_t pxt_table_append(Table* t, int64_t n, const void** cols,
                         const int64_t* times) {
  if (n <= 0) return -1;
  Batch b;
  b.n = n;
  b.bytes = n * t->row_bytes;
  b.cols.reserve(t->elem_sizes.size());
  for (size_t c = 0; c < t->elem_sizes.size(); ++c) {
    int64_t nbytes = n * t->elem_sizes[c];
    auto slab = std::make_unique<char[]>(nbytes);
    std::memcpy(slab.get(), cols[c], nbytes);
    b.cols.push_back(std::move(slab));
  }
  if (t->has_time && times != nullptr) {
    b.min_time = *std::min_element(times, times + n);
    b.max_time = *std::max_element(times, times + n);
  }
  std::lock_guard<std::mutex> lock(t->mu);
  t->expire_locked(b.bytes);
  b.first_row_id = t->next_row_id;
  t->next_row_id += n;
  t->hot_bytes += b.bytes;
  t->stats.batches_added++;
  t->stats.bytes_added += b.bytes;
  t->hot.push_back(std::move(b));
  return t->next_row_id - n;
}

// Merge hot batches into cold batches of ~compacted_rows rows each.
// Returns the number of cold batches created.
int64_t pxt_table_compact(Table* t) {
  std::lock_guard<std::mutex> lock(t->mu);
  int64_t created = 0;
  while (!t->hot.empty()) {
    // Gather a run of hot batches totalling >= compacted_rows (or all of
    // them — a final undersized cold batch is fine; the reference keeps
    // undersized remainders hot, but that starves low-rate tables).
    int64_t rows = 0;
    size_t take = 0;
    while (take < t->hot.size() && rows < t->compacted_rows) {
      rows += t->hot[take].n;
      take++;
    }
    Batch merged;
    merged.n = rows;
    merged.bytes = rows * t->row_bytes;
    merged.first_row_id = t->hot.front().first_row_id;
    merged.min_time = t->hot.front().min_time;
    merged.max_time = t->hot.front().max_time;
    merged.cols.reserve(t->elem_sizes.size());
    for (size_t c = 0; c < t->elem_sizes.size(); ++c)
      merged.cols.push_back(std::make_unique<char[]>(rows * t->elem_sizes[c]));
    int64_t off = 0;
    for (size_t i = 0; i < take; ++i) {
      Batch& h = t->hot[i];
      for (size_t c = 0; c < t->elem_sizes.size(); ++c) {
        int32_t es = t->elem_sizes[c];
        std::memcpy(merged.cols[c].get() + off * es, h.cols[c].get(), h.n * es);
      }
      off += h.n;
      merged.min_time = std::min(merged.min_time, h.min_time);
      merged.max_time = std::max(merged.max_time, h.max_time);
    }
    t->hot.erase(t->hot.begin(), t->hot.begin() + take);
    t->hot_bytes -= merged.bytes;
    t->cold_bytes += merged.bytes;
    t->stats.compacted_batches++;
    t->cold.push_back(std::move(merged));
    created++;
  }
  return created;
}

// Drop every row with id < row_id. This is the cold-tier demotion handoff
// (tier.py): the caller has already copied these rows into the encoded
// cold store, so the drop is NOT expiry — batches_expired / bytes_expired
// do not move (they are reserved for true data loss). Row-granular: a
// batch straddling row_id is split and its tail kept, so the invariant
// "cold tier end == hot first_row_id" holds exactly. Returns the new
// first row id.
int64_t pxt_table_drop_before(Table* t, int64_t row_id) {
  std::lock_guard<std::mutex> lock(t->mu);
  for (std::deque<Batch>* q : {&t->cold, &t->hot}) {
    int64_t& qbytes = (q == &t->cold) ? t->cold_bytes : t->hot_bytes;
    while (!q->empty()) {
      Batch& b = q->front();
      if (b.end_row_id() <= row_id) {
        qbytes -= b.bytes;
        q->pop_front();
        continue;
      }
      if (b.first_row_id < row_id) {
        int64_t drop = row_id - b.first_row_id;
        int64_t keep = b.n - drop;
        Batch tail;
        tail.first_row_id = row_id;
        tail.n = keep;
        tail.bytes = keep * t->row_bytes;
        tail.cols.reserve(t->elem_sizes.size());
        for (size_t c = 0; c < t->elem_sizes.size(); ++c) {
          int32_t es = t->elem_sizes[c];
          auto slab = std::make_unique<char[]>(keep * es);
          std::memcpy(slab.get(), b.cols[c].get() + drop * es, keep * es);
          tail.cols.push_back(std::move(slab));
        }
        if (t->has_time) {
          const int64_t* times =
              reinterpret_cast<const int64_t*>(tail.cols[0].get());
          tail.min_time = *std::min_element(times, times + keep);
          tail.max_time = *std::max_element(times, times + keep);
        }
        qbytes += tail.bytes - b.bytes;
        q->front() = std::move(tail);
      }
      // Front batch now starts at or after row_id; later batches are
      // strictly newer, so the sweep is complete.
      return t->first_row_id_locked();
    }
  }
  return t->first_row_id_locked();
}

int64_t pxt_table_first_row_id(Table* t) {
  std::lock_guard<std::mutex> lock(t->mu);
  return t->first_row_id_locked();
}

int64_t pxt_table_end_row_id(Table* t) {
  std::lock_guard<std::mutex> lock(t->mu);
  return t->next_row_id;
}

// First row id whose time is >= time (strict > when strictly_greater).
// Scans batch min/max time summaries, then the row times within the
// boundary batch. Assumes times are non-decreasing across appends (true of
// telemetry streams; matches the reference's sorted time index).
int64_t pxt_table_row_id_for_time(Table* t, int64_t time,
                                  int32_t strictly_greater) {
  std::lock_guard<std::mutex> lock(t->mu);
  if (!t->has_time) return t->first_row_id_locked();
  auto scan = [&](const std::deque<Batch>& q) -> int64_t {
    for (const Batch& b : q) {
      bool hit = strictly_greater ? (b.max_time > time) : (b.max_time >= time);
      if (!hit) continue;
      // Times are column 0 by convention when has_time (see table.py).
      const int64_t* times = reinterpret_cast<const int64_t*>(b.cols[0].get());
      for (int64_t i = 0; i < b.n; ++i) {
        if (strictly_greater ? times[i] > time : times[i] >= time)
          return b.first_row_id + i;
      }
    }
    return -1;
  };
  int64_t r = scan(t->cold);
  if (r >= 0) return r;
  r = scan(t->hot);
  if (r >= 0) return r;
  return t->next_row_id;
}

// Copy up to max_rows rows starting at start_row_id (or the first still-
// unexpired row after it) into out_cols. Returns rows copied; stores the
// id of the first copied row in *out_first_row_id (so cursors detect
// expiry skips).
int64_t pxt_table_read(Table* t, int64_t start_row_id, int64_t max_rows,
                       void** out_cols, int64_t* out_first_row_id) {
  std::lock_guard<std::mutex> lock(t->mu);
  int64_t row_id = std::max(start_row_id, t->first_row_id_locked());
  *out_first_row_id = row_id;
  int64_t copied = 0;
  for (const std::deque<Batch>* q : {&t->cold, &t->hot}) {
    for (const Batch& b : *q) {
      if (b.end_row_id() <= row_id) continue;
      int64_t take =
          copy_from_batch(*t, b, row_id + copied, copied, max_rows, out_cols);
      copied += take;
      if (copied >= max_rows) return copied;
    }
  }
  return copied;
}

// out[10] = {bytes, hot_bytes, cold_bytes, num_batches, batches_added,
//            batches_expired, bytes_added, compacted_batches, min_time,
//            num_rows}
void pxt_table_stats(Table* t, int64_t* out) {
  std::lock_guard<std::mutex> lock(t->mu);
  out[0] = t->hot_bytes + t->cold_bytes;
  out[1] = t->hot_bytes;
  out[2] = t->cold_bytes;
  out[3] = static_cast<int64_t>(t->hot.size() + t->cold.size());
  out[4] = t->stats.batches_added;
  out[5] = t->stats.batches_expired;
  out[6] = t->stats.bytes_added;
  out[7] = t->stats.compacted_batches;
  out[8] = !t->cold.empty() ? t->cold.front().min_time
                            : (!t->hot.empty() ? t->hot.front().min_time : -1);
  out[9] = t->next_row_id - t->first_row_id_locked();
}

}  // extern "C"
