// Multi-core segmented fold: the CPU-backend scatter accelerator.
//
// Reference parity: Carnot's blocking aggregate hot loop
// (src/carnot/exec/agg_node.cc / blocking_agg_benchmark.cc) is C++ over
// a hash table; here the dense-domain fragment already reduced group
// keys to int32 slot ids on the XLA side (elementwise, cheap), and this
// kernel does the bandwidth-bound scatter passes with one local table
// per thread + an associative reduction — XLA:CPU executes scatters
// single-threaded, which caps bincount-class aggregations at one core.
//
// C ABI (ctypes):
//   seg_fold(gids, n, g, n_out, ops, val_ty, out_ty, vals, outs, threads)
// - gids: int32[n], values in [0, g]; slot g is the trash slot for
//   masked rows (still accumulated, dropped by the caller).
// - per output k: ops[k] in {0 count, 1 sum, 2 min, 3 max};
//   val_ty[k] in {0 none, 1 i64, 2 f64, 3 f32, 4 u8/bool, 5 i32};
//   out_ty[k] in {1 i64, 2 f64, 3 f32};
//   vals[k] points at the value column (nullptr for count);
//   outs[k] points at a (g+1)-entry table PRE-INITIALIZED to the op's
//   neutral value (the caller hands the UDA's init carry) — results
//   accumulate in place so multiple windows chain without merging.

#include <algorithm>
#include <limits>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

enum Op : uint8_t { kCount = 0, kSum = 1, kMin = 2, kMax = 3 };
enum Ty : uint8_t { kNone = 0, kI64 = 1, kF64 = 2, kF32 = 3, kU8 = 4, kI32 = 5 };

template <typename OutT>
void count_rows(const int32_t* g, int64_t lo, int64_t hi, OutT* t) {
  for (int64_t i = lo; i < hi; ++i) t[g[i]] += OutT(1);
}

template <typename OutT, typename ValT>
void sum_rows(const int32_t* g, int64_t lo, int64_t hi, const void* v,
              OutT* t) {
  const ValT* vv = static_cast<const ValT*>(v);
  for (int64_t i = lo; i < hi; ++i) t[g[i]] += static_cast<OutT>(vv[i]);
}

// Float min/max must PROPAGATE NaN (jnp.minimum semantics — the XLA
// fold this kernel replaces); std::min would discard it and the same
// query would answer differently per backend. The `x != x` test sets
// NaN; an accumulated NaN then survives because no comparison beats it.
template <typename OutT, typename ValT>
void min_rows(const int32_t* g, int64_t lo, int64_t hi, const void* v,
              OutT* t) {
  const ValT* vv = static_cast<const ValT*>(v);
  for (int64_t i = lo; i < hi; ++i) {
    OutT x = static_cast<OutT>(vv[i]);
    if (x < t[g[i]] || x != x) t[g[i]] = x;
  }
}

template <typename OutT, typename ValT>
void max_rows(const int32_t* g, int64_t lo, int64_t hi, const void* v,
              OutT* t) {
  const ValT* vv = static_cast<const ValT*>(v);
  for (int64_t i = lo; i < hi; ++i) {
    OutT x = static_cast<OutT>(vv[i]);
    if (x > t[g[i]] || x != x) t[g[i]] = x;
  }
}

// One output's fold over [lo, hi) into table t (type-erased).
void fold_one(uint8_t op, uint8_t vt, uint8_t ot, const int32_t* gids,
              int64_t lo, int64_t hi, const void* val, void* out) {
  switch (op) {
    case kCount:
      if (ot == kI64) count_rows(gids, lo, hi, static_cast<int64_t*>(out));
      else if (ot == kF64) count_rows(gids, lo, hi, static_cast<double*>(out));
      return;
    case kSum:
      if (ot == kI64) {
        if (vt == kI64) sum_rows<int64_t, int64_t>(gids, lo, hi, val, static_cast<int64_t*>(out));
        else if (vt == kU8) sum_rows<int64_t, uint8_t>(gids, lo, hi, val, static_cast<int64_t*>(out));
        else if (vt == kI32) sum_rows<int64_t, int32_t>(gids, lo, hi, val, static_cast<int64_t*>(out));
      } else if (ot == kF64) {
        if (vt == kF64) sum_rows<double, double>(gids, lo, hi, val, static_cast<double*>(out));
        else if (vt == kF32) sum_rows<double, float>(gids, lo, hi, val, static_cast<double*>(out));
        else if (vt == kI64) sum_rows<double, int64_t>(gids, lo, hi, val, static_cast<double*>(out));
      } else if (ot == kF32 && vt == kF32) {
        sum_rows<float, float>(gids, lo, hi, val, static_cast<float*>(out));
      }
      return;
    case kMin:
      if (ot == kI64 && vt == kI64) min_rows<int64_t, int64_t>(gids, lo, hi, val, static_cast<int64_t*>(out));
      else if (ot == kF64 && vt == kF64) min_rows<double, double>(gids, lo, hi, val, static_cast<double*>(out));
      else if (ot == kF64 && vt == kF32) min_rows<double, float>(gids, lo, hi, val, static_cast<double*>(out));
      else if (ot == kF32 && vt == kF32) min_rows<float, float>(gids, lo, hi, val, static_cast<float*>(out));
      return;
    case kMax:
      if (ot == kI64 && vt == kI64) max_rows<int64_t, int64_t>(gids, lo, hi, val, static_cast<int64_t*>(out));
      else if (ot == kF64 && vt == kF64) max_rows<double, double>(gids, lo, hi, val, static_cast<double*>(out));
      else if (ot == kF64 && vt == kF32) max_rows<double, float>(gids, lo, hi, val, static_cast<double*>(out));
      else if (ot == kF32 && vt == kF32) max_rows<float, float>(gids, lo, hi, val, static_cast<float*>(out));
      return;
  }
}

size_t ty_size(uint8_t ot) { return ot == kF32 ? 4 : 8; }

// Merge a thread-local table into the shared output with the op's
// associative combine.
void reduce_one(uint8_t op, uint8_t ot, int64_t rows, const void* local,
                void* out) {
  if (op == kSum || op == kCount) {
    if (ot == kI64) {
      auto* o = static_cast<int64_t*>(out);
      auto* l = static_cast<const int64_t*>(local);
      for (int64_t i = 0; i < rows; ++i) o[i] += l[i];
    } else if (ot == kF64) {
      auto* o = static_cast<double*>(out);
      auto* l = static_cast<const double*>(local);
      for (int64_t i = 0; i < rows; ++i) o[i] += l[i];
    } else {
      auto* o = static_cast<float*>(out);
      auto* l = static_cast<const float*>(local);
      for (int64_t i = 0; i < rows; ++i) o[i] += l[i];
    }
  } else if (op == kMin) {
    if (ot == kI64) {
      auto* o = static_cast<int64_t*>(out);
      auto* l = static_cast<const int64_t*>(local);
      for (int64_t i = 0; i < rows; ++i) o[i] = std::min(o[i], l[i]);
    } else if (ot == kF64) {
      auto* o = static_cast<double*>(out);
      auto* l = static_cast<const double*>(local);
      for (int64_t i = 0; i < rows; ++i)
        if (l[i] < o[i] || l[i] != l[i]) o[i] = l[i];  // NaN-propagating
    } else {
      auto* o = static_cast<float*>(out);
      auto* l = static_cast<const float*>(local);
      for (int64_t i = 0; i < rows; ++i)
        if (l[i] < o[i] || l[i] != l[i]) o[i] = l[i];
    }
  } else {
    if (ot == kI64) {
      auto* o = static_cast<int64_t*>(out);
      auto* l = static_cast<const int64_t*>(local);
      for (int64_t i = 0; i < rows; ++i) o[i] = std::max(o[i], l[i]);
    } else if (ot == kF64) {
      auto* o = static_cast<double*>(out);
      auto* l = static_cast<const double*>(local);
      for (int64_t i = 0; i < rows; ++i)
        if (l[i] > o[i] || l[i] != l[i]) o[i] = l[i];
    } else {
      auto* o = static_cast<float*>(out);
      auto* l = static_cast<const float*>(local);
      for (int64_t i = 0; i < rows; ++i)
        if (l[i] > o[i] || l[i] != l[i]) o[i] = l[i];
    }
  }
}

// The op's neutral element for a fresh thread-local table comes from the
// caller's pre-initialized out table? No — outs accumulate across
// windows, so locals need their own neutral. Sum/count: 0. Min/max: copy
// the neutral the caller seeded is NOT recoverable after window 1, so
// min/max locals seed from extreme limits instead.
void seed_local(uint8_t op, uint8_t ot, int64_t rows, void* local) {
  if (op == kSum || op == kCount) {
    std::memset(local, 0, rows * ty_size(ot));
    return;
  }
  if (ot == kI64) {
    auto* l = static_cast<int64_t*>(local);
    int64_t v = (op == kMin) ? INT64_MAX : INT64_MIN;
    std::fill(l, l + rows, v);
  } else if (ot == kF64) {
    auto* l = static_cast<double*>(local);
    // ±infinity, not DBL_MAX: an input of +inf must survive a min fold
    // (inf < DBL_MAX seed is false -> would be lost in the reduction).
    double v = (op == kMin) ? std::numeric_limits<double>::infinity()
                            : -std::numeric_limits<double>::infinity();
    std::fill(l, l + rows, v);
  } else {
    auto* l = static_cast<float*>(local);
    float v = (op == kMin) ? std::numeric_limits<float>::infinity()
                           : -std::numeric_limits<float>::infinity();
    std::fill(l, l + rows, v);
  }
}

}  // namespace

extern "C" {

void seg_fold(const int32_t* gids, long long n, long long g, int n_out,
              const uint8_t* ops, const uint8_t* val_ty,
              const uint8_t* out_ty, const void** vals, void** outs,
              int n_threads) {
  const int64_t rows = g + 1;  // incl. trash slot
  if (n_threads < 1) n_threads = 1;
  // Local-table memory guard: big domains fall back to fewer threads.
  while (n_threads > 1 &&
         int64_t(n_threads - 1) * n_out * rows * 8 > (int64_t(512) << 20)) {
    n_threads /= 2;
  }
  if (n_threads == 1 || n < (int64_t(1) << 16)) {
    for (int k = 0; k < n_out; ++k) {
      fold_one(ops[k], val_ty[k], out_ty[k], gids, 0, n, vals[k], outs[k]);
    }
    return;
  }
  // Thread 0 folds into the shared outs directly (they carry prior
  // windows' partials); threads 1..T-1 fold into fresh locals.
  std::vector<std::vector<uint8_t>> locals;
  locals.reserve(size_t(n_threads - 1) * n_out);
  for (int t = 1; t < n_threads; ++t) {
    for (int k = 0; k < n_out; ++k) {
      locals.emplace_back(rows * ty_size(out_ty[k]));
      seed_local(ops[k], out_ty[k], rows, locals.back().data());
    }
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = int64_t(t) * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    threads.emplace_back([&, t, lo, hi]() {
      for (int k = 0; k < n_out; ++k) {
        void* out = (t == 0)
                        ? outs[k]
                        : static_cast<void*>(locals[size_t(t - 1) * n_out + k].data());
        fold_one(ops[k], val_ty[k], out_ty[k], gids, lo, hi, vals[k], out);
      }
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 1; t < n_threads; ++t) {
    for (int k = 0; k < n_out; ++k) {
      reduce_one(ops[k], out_ty[k], rows,
                 locals[size_t(t - 1) * n_out + k].data(), outs[k]);
    }
  }
}

// Raw-plane fold: computes slot ids from the staged key planes in the
// same pass (dict codes / bool / strided int keys), so the common dense
// group-by needs NO device program at all. Rows outside [lo, hi) are
// skipped; out-of-domain integer keys (appends racing the compile-time
// stats) go to the trash slot and count into *oob_out so the engine's
// rebucket retry fires.
//
// key_kind: 0 = int32 dictionary codes (NULL -1 -> dom-1, the string
// sub-slot encoding); 1 = bool/u8; 2 = int64 with offset/stride.

void seg_fold_raw(const void** keys, const uint8_t* key_kind,
                  const long long* key_dom, const long long* key_off,
                  const long long* key_stride, int n_keys, long long lo,
                  long long hi, long long g, int n_out, const uint8_t* ops,
                  const uint8_t* val_ty, const uint8_t* out_ty,
                  const void** vals, void** outs, long long* oob_out,
                  int n_threads) {
  const int64_t rows = g + 1;
  const int64_t n = hi - lo;
  if (n <= 0) {
    *oob_out = 0;
    return;
  }
  if (n_threads < 1) n_threads = 1;
  while (n_threads > 1 &&
         int64_t(n_threads - 1) * n_out * rows * 8 > (int64_t(512) << 20)) {
    n_threads /= 2;
  }
  if (n < (int64_t(1) << 16)) n_threads = 1;
  std::vector<std::vector<uint8_t>> locals;
  locals.reserve(size_t(n_threads > 1 ? n_threads - 1 : 0) * n_out);
  for (int t = 1; t < n_threads; ++t) {
    for (int k = 0; k < n_out; ++k) {
      locals.emplace_back(rows * ty_size(out_ty[k]));
      seed_local(ops[k], out_ty[k], rows, locals.back().data());
    }
  }
  std::vector<int64_t> oobs(n_threads, 0);
  int64_t chunk = (n + n_threads - 1) / n_threads;
  // Monomorphic fused loops for the dominant shapes (single dict-code
  // key): no gid scratch, no dispatch — one pass at memory speed. These
  // matter most on low-core hosts where thread parallelism can't hide
  // the extra scratch traffic of the generic two-pass form.
  const bool k1_dict = (n_keys == 1 && key_kind[0] == 0);
  auto tag_of = [&](int k) {
    return (uint32_t(ops[k]) << 8) | (uint32_t(out_ty[k]) << 4) |
           uint32_t(val_ty[k]);
  };
  const uint32_t kSumI64 = (1u << 8) | (1u << 4) | 1u;
  const uint32_t kSumF64fromI64 = (1u << 8) | (2u << 4) | 1u;
  const uint32_t kCountI64 = (0u << 8) | (1u << 4) | 0u;
  auto run_fused = [&](int t, int64_t clo, int64_t chi) -> bool {
    if (!k1_dict) return false;
    const int32_t* kc = static_cast<const int32_t*>(keys[0]);
    const int64_t dom = key_dom[0];
    auto out_at = [&](int k) {
      return (t == 0 || n_threads == 1)
                 ? outs[k]
                 : static_cast<void*>(locals[size_t(t - 1) * n_out + k].data());
    };
    if (n_out == 2 && tag_of(0) == kSumI64 && tag_of(1) == kCountI64) {
      const int64_t* v = static_cast<const int64_t*>(vals[0]);
      int64_t* sum_t = static_cast<int64_t*>(out_at(0));
      int64_t* cnt_t = static_cast<int64_t*>(out_at(1));
      for (int64_t i = clo; i < chi; ++i) {
        int32_t c = kc[i];
        int64_t s = (c < 0 || c >= dom) ? dom - 1 : c;
        sum_t[s] += v[i];
        cnt_t[s] += 1;
      }
      return true;
    }
    if (n_out == 2 && tag_of(0) == kSumF64fromI64 && tag_of(1) == kCountI64) {
      const int64_t* v = static_cast<const int64_t*>(vals[0]);
      double* sum_t = static_cast<double*>(out_at(0));
      int64_t* cnt_t = static_cast<int64_t*>(out_at(1));
      for (int64_t i = clo; i < chi; ++i) {
        int32_t c = kc[i];
        int64_t s = (c < 0 || c >= dom) ? dom - 1 : c;
        sum_t[s] += static_cast<double>(v[i]);
        cnt_t[s] += 1;
      }
      return true;
    }
    if (n_out == 1 && tag_of(0) == kCountI64) {
      int64_t* cnt_t = static_cast<int64_t*>(out_at(0));
      for (int64_t i = clo; i < chi; ++i) {
        int32_t c = kc[i];
        cnt_t[(c < 0 || c >= dom) ? dom - 1 : c] += 1;
      }
      return true;
    }
    return false;
  };
  auto run = [&](int t, int64_t clo, int64_t chi) {
    if (run_fused(t, clo, chi)) return;
    // Two passes over a per-thread chunk: slot ids into an L2-resident
    // scratch, then one tight monomorphic loop per output (fold_one).
    // A fused per-row dispatch was measured SLOWER — the compiler
    // optimizes the typed loops far better than a per-row switch, and
    // the chunk-sized scratch re-reads stay in cache.
    std::vector<int32_t> gids(chi - clo);
    int64_t bad = 0;
    for (int64_t i = clo; i < chi; ++i) {
      int64_t slot = 0;
      bool oob_row = false;
      for (int k = 0; k < n_keys; ++k) {
        int64_t dom = key_dom[k];
        int64_t code;
        if (key_kind[k] == 0) {
          int32_t c = static_cast<const int32_t*>(keys[k])[i];
          code = (c < 0 || c >= dom) ? dom - 1 : c;
        } else if (key_kind[k] == 1) {
          code = static_cast<const uint8_t*>(keys[k])[i] ? 1 : 0;
        } else {
          int64_t raw = static_cast<const int64_t*>(keys[k])[i] - key_off[k];
          int64_t st = key_stride[k];
          if (raw < 0 || raw >= dom * st || (st > 1 && raw % st != 0)) {
            oob_row = true;
            code = 0;
          } else {
            code = st > 1 ? raw / st : raw;
          }
        }
        slot = slot * dom + code;
      }
      if (oob_row) {
        ++bad;
        slot = g;
      }
      gids[i - clo] = static_cast<int32_t>(slot);
    }
    oobs[t] = bad;
    for (int k = 0; k < n_out; ++k) {
      void* out = (t == 0 || n_threads == 1)
                      ? outs[k]
                      : static_cast<void*>(
                            locals[size_t(t - 1) * n_out + k].data());
      const void* val = vals[k];
      if (val != nullptr) {
        const char* base = static_cast<const char*>(val);
        size_t vsz = val_ty[k] == 3 ? 4 : (val_ty[k] == 5 ? 4 : (val_ty[k] == 4 ? 1 : 8));
        val = base + size_t(clo) * vsz;
      }
      fold_one(ops[k], val_ty[k], out_ty[k], gids.data(), 0, chi - clo, val,
               out);
    }
  };
  if (n_threads == 1) {
    run(0, lo, hi);
  } else {
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
      int64_t clo = lo + int64_t(t) * chunk;
      int64_t chi = std::min<int64_t>(clo + chunk, hi);
      if (clo >= chi) break;
      threads.emplace_back(run, t, clo, chi);
    }
    for (auto& th : threads) th.join();
    for (int t = 1; t < n_threads; ++t) {
      for (int k = 0; k < n_out; ++k) {
        reduce_one(ops[k], out_ty[k], rows,
                   locals[size_t(t - 1) * n_out + k].data(), outs[k]);
      }
    }
  }
  int64_t total = 0;
  for (int64_t b : oobs) total += b;
  *oob_out = total;
}

// t-digest histogram fold: the quantile sketch's hot loop. Rows land
// in B log-spaced bins per group via the order-monotone f32 bit
// pattern (ops/tdigest.py batch_to_digest's transform, bit-exact), and
// BOTH histograms (weight + weighted value) accumulate in one pass.
// Accumulating the global histogram across all windows and compressing
// ONCE at finalize does strictly less work than the XLA path's
// per-window compress-and-merge, and loses no accuracy (histogram
// addition is exact; compression is the only lossy step).
void tdigest_hist(const int32_t* gids, const float* vals, long long n,
                  long long g, int shift /* bin = u32(v) >> shift */,
                  float* w, float* mw, int n_threads) {
  const int64_t bins = int64_t(1) << (32 - shift);
  const int64_t rows = g * bins;
  if (n_threads < 1) n_threads = 1;
  while (n_threads > 1 &&
         int64_t(n_threads - 1) * rows * 8 > (int64_t(256) << 20)) {
    n_threads /= 2;
  }
  // Per-thread locals must be zeroed AND merged (2 * rows floats per
  // extra thread) every call: only worth it when the fold itself is
  // bigger than that bookkeeping.
  if (n < (int64_t(1) << 16) || n < rows) n_threads = 1;
  auto fold = [&](int64_t lo, int64_t hi, float* wt, float* mwt) {
    for (int64_t i = lo; i < hi; ++i) {
      int32_t gid = gids[i];
      if (gid < 0 || gid >= g) continue;  // masked / trash rows
      float v = vals[i];
      if (!(v - v == 0.0f)) continue;  // NaN/inf: sketch is over finites
      uint32_t u;
      std::memcpy(&u, &v, 4);
      u = (v < 0.0f) ? ~u : (u | 0x80000000u);
      int64_t slot = int64_t(gid) * bins + int64_t(u >> shift);
      wt[slot] += 1.0f;
      mwt[slot] += v;
    }
  };
  if (n_threads == 1) {
    fold(0, n, w, mw);
    return;
  }
  std::vector<std::vector<float>> locals(
      size_t(n_threads - 1) * 2, std::vector<float>(rows, 0.0f));
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = int64_t(t) * chunk;
    int64_t hi = std::min<int64_t>(lo + chunk, n);
    if (lo >= hi) break;
    threads.emplace_back([&, t, lo, hi]() {
      float* wt = t == 0 ? w : locals[size_t(t - 1) * 2].data();
      float* mwt = t == 0 ? mw : locals[size_t(t - 1) * 2 + 1].data();
      fold(lo, hi, wt, mwt);
    });
  }
  for (auto& th : threads) th.join();
  for (int t = 1; t < n_threads; ++t) {
    const float* wt = locals[size_t(t - 1) * 2].data();
    const float* mwt = locals[size_t(t - 1) * 2 + 1].data();
    for (int64_t i = 0; i < rows; ++i) {
      w[i] += wt[i];
      mw[i] += mwt[i];
    }
  }
}

}  // extern "C"
