"""Native (C++) runtime components, built on demand with g++.

The reference keeps its table store, agent shells, and data plane in C++
(SURVEY.md L0-L2); here the host-side hot/cold table slab store is native,
loaded via ctypes. Build is lazy and cached next to the source; when no
toolchain is available, callers fall back to pure-numpy backends.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS: dict[str, object] = {}


def _build(name: str) -> str:
    src = os.path.join(_DIR, f"{name}.cc")
    out = os.path.join(_DIR, f"lib{name}.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", out, src]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def load(name: str):
    """Load (building if needed) libpixie native component ``name``.

    Returns the ctypes CDLL, or None when the toolchain/build fails —
    callers must degrade to their Python fallback.
    """
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        try:
            lib = ctypes.CDLL(_build(name))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            lib = None
        _LIBS[name] = lib
        return lib
