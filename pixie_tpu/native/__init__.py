"""Native (C++) runtime components, built on demand with g++.

The reference keeps its table store, agent shells, and data plane in C++
(SURVEY.md L0-L2); here the host-side hot/cold table slab store is native,
loaded via ctypes. Build is lazy and cached next to the source; when no
toolchain is available, callers fall back to pure-numpy backends.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS: dict[str, object] = {}


def _build(name: str) -> str:
    src = os.path.join(_DIR, f"{name}.cc")
    out = os.path.join(_DIR, f"lib{name}.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", out, src,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def build_executable(name: str) -> str | None:
    """Build native/<name>.cc as a standalone binary (the client CLI
    path, vs ``load``'s shared-object path). Returns the binary path or
    None when the toolchain is unavailable."""
    src = os.path.join(_DIR, f"{name}.cc")
    out = os.path.join(_DIR, name)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-pthread", "-o", out, src],
            check=True, capture_output=True,
        )
    except FileNotFoundError:
        return None  # no toolchain: callers skip/degrade
    except subprocess.CalledProcessError as e:
        # A COMPILE error must fail loudly — swallowing it would turn
        # every native-client test into a silent skip.
        raise RuntimeError(
            f"native client build failed:\n{e.stderr.decode(errors='replace')}"
        ) from None
    return out


def load(name: str):
    """Load (building if needed) libpixie native component ``name``.

    Returns the ctypes CDLL, or None when the toolchain/build fails —
    callers must degrade to their Python fallback.
    """
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        try:
            lib = ctypes.CDLL(_build(name))
        except (OSError, subprocess.CalledProcessError, FileNotFoundError):
            lib = None
        _LIBS[name] = lib
        return lib


# -- seg_fold: multi-core segmented fold (engine CPU-backend scatters) -------

#: numpy dtype -> seg_fold value-type code (0 none, 1 i64, 2 f64, 3 f32,
#: 4 u8/bool, 5 i32).
_VAL_TY = {"int64": 1, "float64": 2, "float32": 3, "bool": 4, "uint8": 4,
           "int32": 5}
#: numpy dtype -> output-table type code (tables are i64/f64/f32 only).
_OUT_TY = {"int64": 1, "float64": 2, "float32": 3}

#: (op, out_ty, val_ty) combos implemented by the kernel (fold_one).
_SUPPORTED = frozenset(
    [(0, 1, 0), (0, 2, 0)]  # count
    + [(1, 1, 1), (1, 1, 4), (1, 1, 5), (1, 2, 2), (1, 2, 3), (1, 2, 1),
       (1, 3, 3)]  # sum
    + [(op, ot, vt) for op in (2, 3)
       for ot, vt in ((1, 1), (2, 2), (2, 3), (3, 3))]  # min/max
)


def np_view(a) -> np.ndarray:
    """Zero-copy numpy view of a CPU jax array.

    Both ``np.asarray`` and jax's dlpack export COPY the buffer
    (~9ms per 16MB plane on this class of host); the raw buffer pointer
    shares it. SAFETY: the view aliases the jax buffer — callers must
    keep the source array referenced for the view's (short) lifetime and
    only READ through it, which the fold kernel guarantees.
    """
    if isinstance(a, np.ndarray):
        return a
    try:
        # jax dispatch is async: fence before aliasing the buffer, or the
        # kernel races XLA still writing it (garbage slot ids -> OOB).
        a.block_until_ready()
        ptr = a.unsafe_buffer_pointer()
        dt = np.dtype(str(a.dtype))
        buf = (ctypes.c_char * (a.size * dt.itemsize)).from_address(ptr)
        return np.frombuffer(buf, dtype=dt).reshape(a.shape)
    except Exception:
        return np.ascontiguousarray(np.asarray(a))


def seg_fold_threads() -> int:
    import os as _os

    from ..config import get_flag

    t = get_flag("cpu_fold_threads")
    return t if t > 0 else min(_os.cpu_count() or 1, 16)


def seg_fold_call(gids, g: int, specs, vals, outs) -> bool:
    """Accumulate one window into the output tables.

    ``specs`` is [(op, out_dtype, arg_index|None)] per output; ``vals``
    the per-output contiguous value arrays (None for count); ``outs``
    the (g+1)-row tables accumulated in place. Returns False when the
    kernel is unavailable or a dtype combo is unsupported (caller falls
    back to the XLA fold).
    """
    lib = load("seg_fold")
    if lib is None:
        return False
    n_out = len(specs)
    ops = (ctypes.c_uint8 * n_out)()
    vts = (ctypes.c_uint8 * n_out)()
    ots = (ctypes.c_uint8 * n_out)()
    vptrs = (ctypes.c_void_p * n_out)()
    optrs = (ctypes.c_void_p * n_out)()
    for k, ((op, dt, _a), v, o) in enumerate(zip(specs, vals, outs)):
        ot = _OUT_TY.get(str(np.dtype(dt)))
        vt = 0 if v is None else _VAL_TY.get(str(v.dtype))
        if ot is None or vt is None or (op, ot, vt) not in _SUPPORTED:
            return False
        ops[k], vts[k], ots[k] = op, vt, ot
        vptrs[k] = 0 if v is None else v.ctypes.data
        optrs[k] = o.ctypes.data
    lib.seg_fold(
        gids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_longlong(len(gids)), ctypes.c_longlong(g),
        ctypes.c_int(n_out), ops, vts, ots, vptrs, optrs,
        ctypes.c_int(seg_fold_threads()),
    )
    return True


def tdigest_hist_call(gids, vals, g: int, shift: int, w, mw) -> bool:
    """Accumulate the dual t-digest histogram for one window in place.

    ``gids`` i32[n] (>= g rows skipped), ``vals`` f32[n] (non-finite
    skipped, matching batch_to_digest's isfinite mask), ``w``/``mw``
    f32[g * bins] tables; ``bin = monotone_u32(v) >> shift``."""
    lib = load("seg_fold")
    if lib is None:
        return False
    if str(gids.dtype) != "int32" or str(vals.dtype) != "float32":
        return False
    lib.tdigest_hist(
        gids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_longlong(len(gids)), ctypes.c_longlong(g),
        ctypes.c_int(shift),
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        mw.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_int(seg_fold_threads()),
    )
    return True


def hash_join_call(build_keys, probe_keys, left_outer: bool):
    """(l_idx, r_idx) i32 arrays for an N:M equijoin over packed i64
    keys, or None when the native library is unavailable. r_idx is -1
    for unmatched probes kept by ``left_outer``."""
    lib = load("hash_join")
    if lib is None:
        return None
    bk = np.ascontiguousarray(build_keys, dtype=np.int64)
    pk = np.ascontiguousarray(probe_keys, dtype=np.int64)
    if len(bk) > (1 << 31) - 2 or len(pk) > (1 << 31) - 2:
        return None  # i32 row-index outputs
    args = [
        bk.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        ctypes.c_longlong(len(bk)),
        pk.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        ctypes.c_longlong(len(pk)),
        ctypes.c_int(1 if left_outer else 0),
    ]
    lib.hash_join.restype = ctypes.c_longlong
    # Speculative capacity: 1:1/N:1 joins (the common case) fit in
    # len(pk) pairs, finishing in ONE build+probe; only a fan-out
    # blowup pays the second call at the exact size.
    cap = max(len(pk), 1)
    l_idx = np.empty(cap, dtype=np.int32)
    r_idx = np.empty(cap, dtype=np.int32)
    total = lib.hash_join(
        *args,
        l_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        r_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_longlong(cap),
    )
    if total > cap:
        l_idx = np.empty(total, dtype=np.int32)
        r_idx = np.empty(total, dtype=np.int32)
        lib.hash_join(
            *args,
            l_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            r_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_longlong(total),
        )
    return l_idx[:total], r_idx[:total]


def seg_fold_raw_call(key_planes, key_specs, lo: int, hi: int, g: int,
                      specs, vals, outs):
    """Raw-plane fold: slot ids computed in-kernel from the staged key
    planes. ``key_specs`` is [(kind, dom, off, stride)] per key (kind 0
    i32 dict codes, 1 bool, 2 strided i64). Returns the out-of-domain
    row count, or None when unsupported (caller falls back)."""
    lib = load("seg_fold")
    if lib is None:
        return None
    nk = len(key_specs)
    kptrs = (ctypes.c_void_p * nk)()
    kinds = (ctypes.c_uint8 * nk)()
    doms = (ctypes.c_longlong * nk)()
    offs = (ctypes.c_longlong * nk)()
    strides = (ctypes.c_longlong * nk)()
    for k, (plane, (kind, dom, off, stride)) in enumerate(
        zip(key_planes, key_specs)
    ):
        want = {0: "int32", 1: "bool", 2: "int64"}[kind]
        if str(plane.dtype) != want and not (kind == 1 and str(plane.dtype) == "uint8"):
            return None
        kptrs[k] = plane.ctypes.data
        kinds[k], doms[k], offs[k], strides[k] = kind, dom, off, stride
    n_out = len(specs)
    ops = (ctypes.c_uint8 * n_out)()
    vts = (ctypes.c_uint8 * n_out)()
    ots = (ctypes.c_uint8 * n_out)()
    vptrs = (ctypes.c_void_p * n_out)()
    optrs = (ctypes.c_void_p * n_out)()
    for k, ((op, dt, _a), v, o) in enumerate(zip(specs, vals, outs)):
        ot = _OUT_TY.get(str(np.dtype(dt)))
        vt = 0 if v is None else _VAL_TY.get(str(v.dtype))
        if ot is None or vt is None or (op, ot, vt) not in _SUPPORTED:
            return None
        ops[k], vts[k], ots[k] = op, vt, ot
        vptrs[k] = 0 if v is None else v.ctypes.data
        optrs[k] = o.ctypes.data
    oob = ctypes.c_longlong(0)
    lib.seg_fold_raw(
        kptrs, kinds, doms, offs, strides, ctypes.c_int(nk),
        ctypes.c_longlong(lo), ctypes.c_longlong(hi), ctypes.c_longlong(g),
        ctypes.c_int(n_out), ops, vts, ots, vptrs, optrs,
        ctypes.byref(oob), ctypes.c_int(seg_fold_threads()),
    )
    return int(oob.value)
