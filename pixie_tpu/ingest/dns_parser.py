"""DNS wire-format parser: raw UDP payloads -> dns_events records.

Reference parity: the socket tracer's DNS protocol parser
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/dns/parse.cc``): decode the 12-byte header + question/answer
sections (with name compression), pair queries to responses by
transaction id, and emit records whose header/body columns are the JSON
encodings the reference's dns_events table carries.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Optional

_HDR = struct.Struct(">HHHHHH")

_QTYPE = {1: "A", 2: "NS", 5: "CNAME", 6: "SOA", 12: "PTR", 15: "MX",
          16: "TXT", 28: "AAAA", 33: "SRV", 255: "ANY"}


class DNSParseError(ValueError):
    pass


def _read_name(buf: bytes, off: int, depth: int = 0) -> tuple[str, int]:
    """Decode a (possibly compressed) domain name; returns (name, next)."""
    if depth > 16:
        raise DNSParseError("compression loop")
    labels = []
    while True:
        if off >= len(buf):
            raise DNSParseError("truncated name")
        n = buf[off]
        if n == 0:
            return ".".join(labels), off + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(buf):
                raise DNSParseError("truncated pointer")
            ptr = ((n & 0x3F) << 8) | buf[off + 1]
            name, _ = _read_name(buf, ptr, depth + 1)
            labels.append(name)
            return ".".join(labels), off + 2
        off += 1
        labels.append(buf[off:off + n].decode("latin-1"))
        off += n


def parse_dns(payload: bytes) -> dict:
    """One UDP message -> {txid, is_response, rcode, queries, answers}."""
    if len(payload) < _HDR.size:
        raise DNSParseError("short header")
    txid, flags, qd, an, _ns, _ar = _HDR.unpack_from(payload, 0)
    off = _HDR.size
    queries = []
    for _ in range(qd):
        name, off = _read_name(payload, off)
        if off + 4 > len(payload):
            raise DNSParseError("truncated question")
        qtype, _qclass = struct.unpack_from(">HH", payload, off)
        off += 4
        queries.append({"name": name, "type": _QTYPE.get(qtype, str(qtype))})
    answers = []
    for _ in range(an):
        name, off = _read_name(payload, off)
        if off + 10 > len(payload):
            raise DNSParseError("truncated answer")
        rtype, _rc, _ttl, rdlen = struct.unpack_from(">HHIH", payload, off)
        off += 10
        rdata = payload[off:off + rdlen]
        off += rdlen
        ans = {"name": name, "type": _QTYPE.get(rtype, str(rtype))}
        if rtype == 1 and rdlen == 4:
            ans["addr"] = ".".join(str(b) for b in rdata)
        elif rtype == 28 and rdlen == 16:
            ans["addr"] = rdata.hex()
        answers.append(ans)
    return {
        "txid": txid,
        "is_response": bool(flags & 0x8000),
        "rcode": flags & 0x000F,
        "queries": queries,
        "answers": answers,
    }


class DNSStitcher:
    """Pairs queries with responses by transaction id; emits dns_events
    records (header/body JSON columns, the reference table's encoding)."""

    # Unanswered queries expire after this long (the reference socket
    # tracer similarly ages out connection-tracker state); the map is also
    # hard-capped so a txid flood can't grow it without bound.
    PENDING_TTL_NS = 30 * 1_000_000_000
    PENDING_MAX = 4096

    def __init__(self, pod: str = ""):
        self.pod = pod
        self._pending: dict[int, tuple[dict, int]] = {}
        self.records: list[dict] = []
        self.parse_errors = 0

    def _expire(self, now_ns: int) -> None:
        cutoff = now_ns - self.PENDING_TTL_NS
        if len(self._pending) > 64:
            self._pending = {
                txid: v for txid, v in self._pending.items() if v[1] >= cutoff
            }
        while len(self._pending) >= self.PENDING_MAX:
            self._pending.pop(next(iter(self._pending)))

    def feed(self, payload: bytes, ts_ns: Optional[int] = None) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        try:
            msg = parse_dns(payload)
        except DNSParseError:
            self.parse_errors += 1
            return 0
        if not msg["is_response"]:
            self._expire(ts)
            self._pending[msg["txid"]] = (msg, ts)
            return 0
        req = self._pending.pop(msg["txid"], None)
        if req is None:
            self.parse_errors += 1
            return 0
        req_msg, req_ts = req
        self.records.append({
            "time_": req_ts,
            "req_header": json.dumps({"txid": msg["txid"]}),
            "req_body": json.dumps({"queries": req_msg["queries"]}),
            "resp_header": json.dumps({"rcode": msg["rcode"]}),
            "resp_body": json.dumps({"answers": msg["answers"]}),
            "latency_ns": max(ts - req_ts, 0),
            "pod": self.pod,
        })
        return 1

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
