"""Connector framework: lifecycle, buffers, frequencies.

Reference parity: ``src/stirling/core`` — ``SourceConnector``
(``source_connector.h:43``: Init/TransferData/Stop, per-table schemas,
sampling+push periods), ``DataTable`` (``data_table.h:51``: accumulation
buffer with tablets and push thresholds), ``FrequencyManager``
(``frequency_manager.h:31``: expired/reset cycle accounting).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from ..types.relation import Relation


class FrequencyManager:
    """Cycle clock: fires when ``period_s`` has elapsed since last reset."""

    def __init__(self, period_s: float):
        self.period_s = period_s
        self._next = time.monotonic()
        self.count = 0

    def expired(self, now: Optional[float] = None) -> bool:
        return (now if now is not None else time.monotonic()) >= self._next

    def reset(self, now: Optional[float] = None) -> None:
        self._next = (now if now is not None else time.monotonic()) + self.period_s
        self.count += 1

    @property
    def next_deadline(self) -> float:
        return self._next


class DataTable:
    """Per-connector accumulation buffer for one output table.

    Reference: ``core/data_table.h:51`` — records accumulate between
    transfer cycles; the collector drains them to the push callback when
    the push period fires (or the buffer crosses its size threshold).
    """

    def __init__(
        self,
        name: str,
        relation: Relation,
        push_threshold_rows: int = 1 << 16,
        max_buffer_rows: int | None = None,
    ):
        self.name = name
        self.relation = relation
        self.push_threshold_rows = push_threshold_rows
        # Hard cap when no consumer drains us (e.g. collector started
        # before a push callback is wired): drop oldest, count the loss
        # (the reference DataTable expires oldest on occupancy too).
        self.max_buffer_rows = (
            max_buffer_rows if max_buffer_rows is not None
            else 4 * push_threshold_rows
        )
        self.rows_dropped = 0
        # append runs on the collector thread, drain on flush callers —
        # guard both (records landing mid-drain must not be lost).
        self._lock = threading.Lock()
        self._pending: list[dict] = []
        self._pending_rows = 0

    def append(self, records: dict) -> None:
        n = len(next(iter(records.values()))) if records else 0
        if n == 0:
            return
        with self._lock:
            self._pending.append(records)
            self._pending_rows += n
            while self._pending_rows > self.max_buffer_rows and len(self._pending) > 1:
                dropped = self._pending.pop(0)
                m = len(next(iter(dropped.values())))
                self._pending_rows -= m
                self.rows_dropped += m

    @property
    def pending_rows(self) -> int:
        with self._lock:
            return self._pending_rows

    def over_threshold(self) -> bool:
        return self.pending_rows >= self.push_threshold_rows

    def drain(self) -> Optional[dict]:
        """Concatenate and clear pending records."""
        with self._lock:
            pending, self._pending = self._pending, []
            self._pending_rows = 0
        if not pending:
            return None
        if len(pending) == 1:
            return pending[0]
        keys = pending[0].keys()
        return {
            k: np.concatenate([np.asarray(p[k]) for p in pending]) for k in keys
        }


class SourceConnector:
    """Base connector (``source_connector.h:43``).

    Subclasses declare ``tables`` = [(name, Relation)] and implement
    ``transfer_data(ctx, data_tables)`` to append newly-collected records.
    """

    name = "source"
    # [(table name, Relation)] — the InfoClassManager publication.
    tables: list = []
    default_sampling_period_s = 0.1
    default_push_period_s = 1.0

    def __init__(
        self,
        sampling_period_s: Optional[float] = None,
        push_period_s: Optional[float] = None,
    ):
        self.sampling_freq = FrequencyManager(
            sampling_period_s
            if sampling_period_s is not None
            else self.default_sampling_period_s
        )
        self.push_freq = FrequencyManager(
            push_period_s if push_period_s is not None else self.default_push_period_s
        )
        self.initialized = False

    # -- lifecycle -----------------------------------------------------------
    def init(self) -> None:
        """One-time setup (probe deployment in the reference)."""
        self.initialized = True

    def stop(self) -> None:
        self.initialized = False

    def transfer_data(self, ctx, data_tables: dict) -> None:
        """Collect and append records to ``data_tables[name]``."""
        raise NotImplementedError
