"""Replay loader: stream a recorded http_events trace into the engine.

The benchmark ingest path (SURVEY.md §6): the driver-defined north star
replays ~1B http_events rows through the query engine. This module
generates (or loads from .npz) the replay and streams it through the
push-callback surface in table-store-sized chunks, so the benchmark
exercises the same ingest path a live collector uses.
"""

from __future__ import annotations

import numpy as np

from ..types.dtypes import DataType
from ..types.relation import Relation

HTTP_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("remote_addr", DataType.STRING),
        ("req_method", DataType.STRING),
        ("req_path", DataType.STRING),
        ("resp_status", DataType.INT64),
        ("resp_body_size", DataType.INT64),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
        ("pod", DataType.STRING),
    ]
)


def gen_http_events(
    n: int,
    chunk: int = 1 << 20,
    seed: int = 7,
    n_services: int = 32,
    n_pods: int = 128,
    n_paths: int = 64,
    t0: int = 0,
):
    """Yield {col: np.ndarray} chunks of a synthetic http_events trace.

    Value distributions mirror the reference's protocol-loadtest shape:
    mostly-200 statuses, log-normal-ish latencies, service/pod/path drawn
    from small vocabularies (dictionary-encodable).
    """
    rng = np.random.default_rng(seed)
    methods = np.array(["GET", "GET", "GET", "POST", "PUT", "DELETE"])
    statuses = np.array([200] * 92 + [404] * 4 + [500] * 3 + [503])
    off = 0
    while off < n:
        m = min(chunk, n - off)
        svc_ids = rng.integers(0, n_services, m)
        yield {
            "time_": t0 + np.arange(off, off + m, dtype=np.int64) * 1000,
            "upid": np.stack(
                [
                    rng.integers(1, 1 << 30, m).astype(np.uint64),
                    rng.integers(1, 1 << 62, m).astype(np.uint64),
                ],
                axis=1,
            ),
            "remote_addr": [f"10.0.{i % 256}.{i % 251}" for i in svc_ids],
            "req_method": methods[rng.integers(0, len(methods), m)],
            "req_path": [f"/api/v1/ep{i}" for i in rng.integers(0, n_paths, m)],
            "resp_status": statuses[rng.integers(0, len(statuses), m)].astype(
                np.int64
            ),
            "resp_body_size": rng.integers(64, 1 << 20, m),
            "latency_ns": np.exp(rng.normal(15.0, 1.2, m)).astype(np.int64),
            "service": [f"svc-{i}" for i in svc_ids],
            "pod": [f"svc-{i}/pod-{j}" for i, j in zip(svc_ids, rng.integers(0, n_pods, m))],
        }
        off += m


def replay_into(target, n: int, chunk: int = 1 << 20, table: str = "http_events", **kw):
    """Stream a generated trace into an engine/agent via the push path.
    Returns total rows pushed."""
    total = 0
    for records in gen_http_events(n, chunk=chunk, **kw):
        target.append_data(table, records)
        total += len(records["resp_status"])
    return total


def save_npz(path: str, n: int, **kw) -> None:
    """Materialize a replay to disk for repeated benchmarking."""
    chunks = list(gen_http_events(n, **kw))
    keys = chunks[0].keys()
    np.savez_compressed(
        path,
        **{
            k: np.concatenate([np.asarray(c[k]) for c in chunks]) for k in keys
        },
    )


def load_npz(path: str, chunk: int = 1 << 20):
    """Yield chunks from a saved replay."""
    data = np.load(path, allow_pickle=False)
    n = len(data["resp_status"])
    for off in range(0, n, chunk):
        yield {k: data[k][off : off + chunk] for k in data.files}
