"""Capture-tap connector: recorded socket captures -> protocol tables.

Reference parity: the socket tracer's transfer pipeline
(``/root/reference/src/stirling/source_connectors/socket_tracer/
socket_trace_connector.cc`` TransferData: drain per-connection capture
buffers through protocol parsers/stitchers into the protocol tables).
The capture source here is a recorded tap — a JSONL file or an
in-memory feed of ``{"conn": id, "dir": "req"|"resp", "ts": ns,
"proto": <protocol>, "data_b64": ...}`` events (what a sidecar proxy or
pcap exporter produces) — pushed through the same incremental
per-protocol parsers/stitchers into the canonical event tables. All 11
reference protocols are covered: http (+http2 into the same
http_events), dns, mysql, pgsql, redis, kafka, cql, nats, mux, amqp.
"""

from __future__ import annotations

import base64
import json
from typing import Iterable, Optional

from ..types.dtypes import DataType
from .core import SourceConnector
from .amqp_parser import AMQPStitcher
from .cql_parser import CQLStitcher
from .dns_parser import DNSStitcher
from .http2_parser import HTTP2Stitcher
from .http_parser import HTTPStitcher
from .kafka_parser import KafkaStitcher
from .mux_parser import MuxStitcher
from .mysql_parser import MySQLStitcher
from .nats_parser import NATSStitcher
from .pgsql_parser import PgSQLStitcher
from .redis_parser import RedisStitcher
from .schemas import (
    AMQP_EVENTS_RELATION,
    CQL_EVENTS_RELATION,
    DNS_EVENTS_RELATION,
    HTTP_EVENTS_RELATION,
    KAFKA_EVENTS_RELATION,
    MUX_EVENTS_RELATION,
    MYSQL_EVENTS_RELATION,
    NATS_EVENTS_RELATION,
    PGSQL_EVENTS_RELATION,
    REDIS_EVENTS_RELATION,
)


class CaptureTapConnector(SourceConnector):
    """Feeds capture events through protocol stitchers into tables."""

    name = "capture_tap"
    tables = [
        ("http_events", HTTP_EVENTS_RELATION),
        ("dns_events", DNS_EVENTS_RELATION),
        ("mysql_events", MYSQL_EVENTS_RELATION),
        ("pgsql_events", PGSQL_EVENTS_RELATION),
        ("redis_events", REDIS_EVENTS_RELATION),
        ("kafka_events.beta", KAFKA_EVENTS_RELATION),
        ("cql_events", CQL_EVENTS_RELATION),
        ("nats_events.beta", NATS_EVENTS_RELATION),
        ("mux_events", MUX_EVENTS_RELATION),
        ("amqp_events", AMQP_EVENTS_RELATION),
    ]

    def __init__(self, feed: Optional[Iterable] = None, path: str = "",
                 service: str = "", pod: str = "", **kw):
        super().__init__(**kw)
        self._feed = iter(feed) if feed is not None else None
        self._path = path
        self._fh = None
        self.http = HTTPStitcher(service=service, pod=pod)
        self.http2 = HTTP2Stitcher(service=service, pod=pod)
        self.dns = DNSStitcher(pod=pod)
        self.mysql = MySQLStitcher(service=service, pod=pod)
        self.pgsql = PgSQLStitcher(service=service, pod=pod)
        self.redis = RedisStitcher(service=service, pod=pod)
        self.kafka = KafkaStitcher(service=service, pod=pod)
        self.cql = CQLStitcher(service=service, pod=pod)
        self.nats = NATSStitcher(service=service, pod=pod)
        self.mux = MuxStitcher(service=service, pod=pod)
        self.amqp = AMQPStitcher(service=service, pod=pod)
        self.upid_value = 0

    def init(self) -> None:
        super().init()
        if self._path:
            self._fh = open(self._path)

    def stop(self) -> None:
        super().stop()
        if self._fh:
            self._fh.close()
            self._fh = None

    def _events(self, budget: int):
        if self._fh is not None:
            for _ in range(budget):
                line = self._fh.readline()
                if not line:
                    return
                if line.strip():
                    yield json.loads(line)
            return
        if self._feed is not None:
            for _ in range(budget):
                try:
                    yield next(self._feed)
                except StopIteration:
                    return

    def transfer_data(self, ctx, data_tables, budget: int = 4096) -> None:
        for ev in self._events(budget):
            data = base64.b64decode(ev["data_b64"])
            proto = ev.get("proto", "http")
            if proto == "dns":
                self.dns.feed(data, ts_ns=ev.get("ts"))
            elif proto in ("mysql", "pgsql", "redis", "kafka", "cql",
                           "nats", "mux", "amqp", "http2"):
                stitcher = getattr(self, proto)
                stitcher.feed(
                    ev.get("conn", 0), data,
                    is_request=(ev.get("dir", "req") == "req"),
                    ts_ns=ev.get("ts"),
                )
            else:
                self.http.feed(
                    ev.get("conn", 0), data,
                    is_request=(ev.get("dir", "req") == "req"),
                    ts_ns=ev.get("ts"),
                )
        # HTTP/1 and HTTP/2 land in the same canonical table.
        http_recs = self.http.drain() + self.http2.drain()
        if http_recs:
            cols = {
                k: [r[k] for r in http_recs]
                for k in ("time_", "latency_ns", "resp_status", "req_path",
                          "service")
            }
            # Canonical http_events columns the stitcher does not carry.
            n = len(http_recs)
            full = {name: cols.get(name) for name, _ in
                    HTTP_EVENTS_RELATION.items() if name in cols}
            for name, _dt in HTTP_EVENTS_RELATION.items():
                if name in full and full[name] is not None:
                    continue
                full[name] = self._default_column(name, n, http_recs)
            data_tables["http_events"].append(full)
        for table, rel, recs in (
            ("dns_events", DNS_EVENTS_RELATION, self.dns.drain()),
            ("mysql_events", MYSQL_EVENTS_RELATION, self.mysql.drain()),
            ("pgsql_events", PGSQL_EVENTS_RELATION, self.pgsql.drain()),
            ("redis_events", REDIS_EVENTS_RELATION, self.redis.drain()),
            ("kafka_events.beta", KAFKA_EVENTS_RELATION, self.kafka.drain()),
            ("cql_events", CQL_EVENTS_RELATION, self.cql.drain()),
            ("nats_events.beta", NATS_EVENTS_RELATION, self.nats.drain()),
            ("mux_events", MUX_EVENTS_RELATION, self.mux.drain()),
            ("amqp_events", AMQP_EVENTS_RELATION, self.amqp.drain()),
        ):
            if not recs:
                continue
            n = len(recs)
            full = {}
            for name, dt in rel.items():
                if name == "upid":
                    full[name] = [self.upid_value] * n
                else:
                    dflt = "" if dt == DataType.STRING else 0
                    full[name] = [r.get(name, dflt) for r in recs]
            data_tables[table].append(full)

    def _default_column(self, name: str, n: int, recs):
        if name == "upid":
            return [self.upid_value] * n
        if name in ("req_method",):
            return [r.get("req_method", "") for r in recs]
        if name in ("req_body", "resp_body"):
            return [""] * n
        if name == "resp_body_size":
            return [r.get("resp_body_bytes", 0) for r in recs]
        if name in ("remote_addr", "pod"):
            return [r.get("pod", "") for r in recs]
        return [0] * n
