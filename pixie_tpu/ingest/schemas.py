"""Canonical output-table schemas shipped by the ingest edge.

Reference parity: Stirling's static table schemas —
``src/stirling/source_connectors/socket_tracer/http_table.h`` /
``conn_stats_table.h`` (kConnStatsElements),
``perf_profiler/stack_traces_table.h`` (kStackTraceTable),
``mysql_table.h``, ``source_connectors/process_stats``. These are the
relations a PEM creates at registration (``pem_manager.cc:86-104``
InitSchemas) and the contract the shipped PxL script library compiles
against (``src/e2e_test/vizier/planner/dump_schemas``).

The TPU build materializes the k8s-context columns (``service``/``pod``)
at ingest time — Stirling fills them from AgentMetadataState during
TransferData (SURVEY.md §3.2) — so group-bys hit dictionary ids directly.
"""

from __future__ import annotations

from ..types.dtypes import DataType
from ..types.relation import Relation
from .replay import HTTP_EVENTS_RELATION

# conn_stats_table.h kConnStatsElements (+ materialized k8s context).
CONN_STATS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("remote_addr", DataType.STRING),
        ("remote_port", DataType.INT64),
        ("trace_role", DataType.INT64),
        ("addr_family", DataType.INT64),
        ("protocol", DataType.INT64),
        ("ssl", DataType.BOOLEAN),
        ("conn_open", DataType.INT64),
        ("conn_close", DataType.INT64),
        ("conn_active", DataType.INT64),
        ("bytes_sent", DataType.INT64),
        ("bytes_recv", DataType.INT64),
        ("src_addr", DataType.STRING),
        ("src_pod", DataType.STRING),
    ]
)

# stack_traces_table.h kStackTraceTable ("stack_traces.beta").
STACK_TRACES_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("stack_trace_id", DataType.INT64),
        ("stack_trace", DataType.STRING),
        ("count", DataType.INT64),
        ("pod", DataType.STRING),
    ]
)

# mysql_table.h kMySQLTable (subset: the sql_stats script surface).
MYSQL_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_cmd", DataType.INT64),
        ("query_str", DataType.STRING),  # req_body in the reference
        ("resp_status", DataType.INT64),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# pgsql_table.h kPGSQLTable (subset; req_cmd is the protocol verb).
PGSQL_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_cmd", DataType.STRING),
        ("req", DataType.STRING),
        ("resp", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# process_stats connector (proc-fs metrics).
PROCESS_STATS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("major_faults", DataType.INT64),
        ("minor_faults", DataType.INT64),
        ("cpu_utime_ns", DataType.INT64),
        ("cpu_ktime_ns", DataType.INT64),
        ("rss_bytes", DataType.INT64),
        ("vsize_bytes", DataType.INT64),
        ("rchar_bytes", DataType.INT64),
        ("wchar_bytes", DataType.INT64),
        ("read_bytes", DataType.INT64),
        ("write_bytes", DataType.INT64),
        ("pod", DataType.STRING),
    ]
)

# network_stats connector (per-pod RX/TX).
NETWORK_STATS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("pod_id", DataType.STRING),
        ("rx_bytes", DataType.INT64),
        ("rx_packets", DataType.INT64),
        ("rx_errors", DataType.INT64),
        ("rx_drops", DataType.INT64),
        ("tx_bytes", DataType.INT64),
        ("tx_packets", DataType.INT64),
        ("tx_errors", DataType.INT64),
        ("tx_drops", DataType.INT64),
        ("pod", DataType.STRING),
    ]
)

# redis_table.h kRedisTable (subset; +service context).
REDIS_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_cmd", DataType.STRING),
        ("req_args", DataType.STRING),
        ("resp", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# kafka_table.h kKafkaTable ("kafka_events.beta" in the reference;
# req_cmd is the APIKey enum value).
KAFKA_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_cmd", DataType.INT64),
        ("client_id", DataType.STRING),
        ("req_body", DataType.STRING),
        ("resp", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# cass_table.h kCQLTable (subset; req_op/resp_op are protocol opcodes).
CQL_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_op", DataType.INT64),
        ("req_body", DataType.STRING),
        ("resp_op", DataType.INT64),
        ("resp_body", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# nats_table.h kNATSTable ("nats_events.beta": cmd/body/resp).
NATS_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("cmd", DataType.STRING),
        ("body", DataType.STRING),
        ("resp", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# mux_table.h kMuxTable (req_type enum + latency).
MUX_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_type", DataType.INT64),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# AMQP method events (reference protocols/amqp is WIP — this is the
# method-level shape its sibling tables share).
AMQP_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("channel", DataType.INT64),
        ("method", DataType.STRING),
        ("resp", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("service", DataType.STRING),
    ]
)

# proc_stat_connector.h kElements (system-wide CPU split, sampled by
# diffing the aggregate cpu jiffies line of /proc/stat).
PROC_STAT_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("system_percent", DataType.FLOAT64),
        ("user_percent", DataType.FLOAT64),
        ("idle_percent", DataType.FLOAT64),
    ]
)

# pid_runtime_connector.h kTable — the reference keeps the BPF-era name
# "bcc_pid_cpu_usage" for the table even though the gauge is generic.
PID_RUNTIME_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("pid", DataType.INT64),
        ("runtime_ns", DataType.INT64),
        ("cmd", DataType.STRING),
    ]
)

# proc_exit_events_table.h kProcExitEventsTable.
PROC_EXIT_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("exit_code", DataType.INT64),
        ("signal", DataType.INT64),
        ("comm", DataType.STRING),
    ]
)

# stirling_error_table.h kStirlingErrorElements (self-observability:
# connector install status + runtime collection errors).
STIRLING_ERROR_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("source_connector", DataType.STRING),
        ("status", DataType.INT64),
        ("error", DataType.STRING),
    ]
)

# -- self-observability tables (services/telemetry.py) -----------------------
# The engine's OWN telemetry as queryable tables: the TelemetryCollector
# folds finished query traces + resource records into these, so bundled
# PxL scripts (px/slow_queries, px/query_cost, px/agent_health) run over
# the system's history through the normal engine path. Reference analog:
# Stirling's stirling_error self-monitoring table, generalized to the
# whole query lifecycle.

# One row per finished query/fragment trace; time_ = trace end.
QUERIES_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("trace_id", DataType.STRING),
        ("qid", DataType.STRING),  # distributed query id ("" = local)
        # Admitting tenant (services/tenancy.py registered set; "" =
        # not a tenant-scoped query) — per-tenant cost/latency rollups
        # run over this column.
        ("tenant", DataType.STRING),
        ("agent_id", DataType.STRING),
        ("kind", DataType.STRING),  # query|stream|fragment|merge|distributed
        ("script_hash", DataType.STRING),
        ("script", DataType.STRING),  # first 200 chars
        ("status", DataType.STRING),
        ("duration_ms", DataType.FLOAT64),
        ("rows_in", DataType.INT64),
        ("rows_out", DataType.INT64),
        ("windows", DataType.INT64),
        ("bytes_staged", DataType.INT64),
        ("device_ms", DataType.FLOAT64),
        ("compile_ms", DataType.FLOAT64),
        ("stall_ms", DataType.FLOAT64),
        ("wire_bytes", DataType.INT64),
        ("retries", DataType.INT64),
        ("skipped_windows", DataType.INT64),
        # Device-tier additions: observed high-water device bytes while
        # the query ran (0 on stat-less backends), and the pxbound
        # PREDICTED cost stamped at plan time (0 = unknown/sketch-less)
        # — observed and predicted side by side is what lets
        # px/bound_accuracy compute the calibration ratio per script
        # hash, closing the arXiv:2102.02440 feedback loop.
        ("device_peak_bytes", DataType.INT64),
        ("predicted_bytes", DataType.INT64),
        ("predicted_rows", DataType.INT64),
        # Storage-tier staleness: query stop-time minus the max event-
        # time watermark of the scanned tables at execute time (worst
        # table; max across agents for distributed queries). 0 = fully
        # fresh OR no time-indexed scan — the exact validity predicate
        # a result cache keyed on (script hash, table watermark) checks.
        ("freshness_lag_ms", DataType.FLOAT64),
        # Result-cache disposition: hit|miss|stale|bypass|view ("" =
        # cache not in play — disabled, or a fragment/merge trace).
        # px/cache_stats rolls hit rates per script hash over this.
        ("cache", DataType.STRING),
    ]
)

# One row per trace span (bounded per trace); time_ = span start.
SPANS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("trace_id", DataType.STRING),
        ("span_id", DataType.STRING),
        ("parent_id", DataType.STRING),
        ("name", DataType.STRING),
        ("agent_id", DataType.STRING),
        ("duration_ms", DataType.FLOAT64),
    ]
)

# Cumulative-counter snapshots of the process program registry
# (exec/programs.py): one row per tracked XLA program whose state
# changed since the previous fold — the LATEST row per program_id is
# its current state (compiles/hits are monotonic). flops/bytes come
# from XLA cost_analysis(), the byte columns from memory_analysis();
# all 0 when the backend reports nothing (timing-only records).
PROGRAMS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("agent_id", DataType.STRING),
        ("program_id", DataType.STRING),
        ("kind", DataType.STRING),  # fragment_update|join_probe_sorted|...
        ("label", DataType.STRING),  # op chain / join strategy summary
        ("compiles", DataType.INT64),
        ("hits", DataType.INT64),
        ("compile_ms", DataType.FLOAT64),
        ("flops", DataType.FLOAT64),
        ("bytes_accessed", DataType.FLOAT64),
        ("argument_bytes", DataType.INT64),
        ("temp_bytes", DataType.INT64),
        ("peak_bytes", DataType.INT64),
    ]
)

# Storage-tier snapshots (services/telemetry.py TableStatsCollector):
# one row per (agent, table) whose stats CHANGED since the collector's
# previous fold — heartbeat cadence + every finished trace. The
# *_total columns are monotonic (latest row per (agent_id, table) is
# current state; cluster merges sum them across agents), `watermark`
# is the max event-time ns ever appended (never regresses; cluster
# merges take the max), live sizes (rows/bytes/...) are gauges.
# Reference analog: the table stats every agent heartbeat ships
# (``table_store.h`` GetTableStats -> agent heartbeat proto).
TABLES_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("agent_id", DataType.STRING),
        ("table", DataType.STRING),
        ("rows", DataType.INT64),  # live rows
        ("bytes", DataType.INT64),  # live bytes (hot + cold)
        ("hot_bytes", DataType.INT64),
        ("cold_bytes", DataType.INT64),  # encoded cold-store bytes
        ("hot_rows", DataType.INT64),  # pxtier split (0s untiered)
        ("cold_rows", DataType.INT64),
        ("cold_raw_bytes", DataType.INT64),  # pre-encoding widths
        ("cold_demotions_total", DataType.INT64),
        ("cold_evictions_total", DataType.INT64),
        ("device_bytes", DataType.INT64),  # HBM-resident staged windows
        ("rows_total", DataType.INT64),  # rows ever appended
        ("bytes_total", DataType.INT64),
        ("expired_rows_total", DataType.INT64),
        ("expired_bytes_total", DataType.INT64),
        ("watermark", DataType.INT64),  # max event-time ns (-1 = none)
        ("min_time", DataType.INT64),  # oldest live event-time ns
        ("last_append", DataType.INT64),  # unix ns of latest append
        ("ingest_rows_per_s", DataType.FLOAT64),  # per-append EWMA
    ]
)

# Profiling tier (ingest/profiler.py): one row per (folded stack,
# attribution) key drained each push period. ``stack_trace`` is the
# flamegraph-folded ``outermost;...;innermost`` string; ``count`` is
# samples at the profiler's period (100Hz default — CPU-seconds =
# count * period). qid/script_hash/tenant come from the thread
# attribution registry (exec/threadmap.py) at sample time ("" =
# unattributed — idle daemons, bus plumbing); ``phase`` splits
# host vs device_dispatch vs stall vs stage so flame roots show
# where the wall time actually went.
STACKS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("agent_id", DataType.STRING),
        ("stack_trace_id", DataType.INT64),
        ("stack_trace", DataType.STRING),
        ("count", DataType.INT64),
        ("qid", DataType.STRING),
        ("script_hash", DataType.STRING),
        ("tenant", DataType.STRING),
        ("phase", DataType.STRING),
    ]
)

# One row per finished trace: the folding agent's running totals (the
# latest row per agent_id is its current health snapshot).
AGENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("agent_id", DataType.STRING),
        ("kind", DataType.STRING),  # pem|kelvin|engine|broker
        ("queries_total", DataType.INT64),
        ("errors_total", DataType.INT64),
        ("bytes_staged_total", DataType.INT64),
        ("device_ms_total", DataType.FLOAT64),
        ("wire_bytes_total", DataType.INT64),
    ]
)

# Transport tier (services/busstats.py): one cumulative-counter row
# per changed (kind, topic_class/peer, direction) key each heartbeat
# fold. ``kind`` is bus (in-process fan-out; topic_class label),
# net (wire frames; the key column carries the peer), or rpc
# (request/reply; key = peer, lag quantiles = RTT). Counters are
# monotonic — ``px.max`` per key recovers the latest fold (the
# px/bus_health / px/rpc_latency idiom).
BUS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("agent_id", DataType.STRING),
        ("kind", DataType.STRING),  # bus|net|rpc
        ("topic_class", DataType.STRING),  # peer for net/rpc rows
        ("direction", DataType.STRING),  # pub|deliver|send|recv|conn|request
        ("msgs", DataType.INT64),
        ("bytes", DataType.INT64),
        ("errors", DataType.INT64),
        ("lag_p50_ms", DataType.FLOAT64),
        ("lag_p99_ms", DataType.FLOAT64),
        ("service_p50_ms", DataType.FLOAT64),
        ("service_p99_ms", DataType.FLOAT64),
        ("queue_high_water", DataType.INT64),
    ]
)

#: {table: Relation} for the self-telemetry tables.
TELEMETRY_SCHEMAS: dict[str, "Relation"] = {
    "__queries__": QUERIES_RELATION,
    "__spans__": SPANS_RELATION,
    "__agents__": AGENTS_RELATION,
    "__programs__": PROGRAMS_RELATION,
    "__tables__": TABLES_RELATION,
    "__stacks__": STACKS_RELATION,
    "__bus__": BUS_RELATION,
}

# dns_table.h kDNSTable (subset).
DNS_EVENTS_RELATION = Relation(
    [
        ("time_", DataType.TIME64NS),
        ("upid", DataType.UINT128),
        ("req_header", DataType.STRING),
        ("req_body", DataType.STRING),
        ("resp_header", DataType.STRING),
        ("resp_body", DataType.STRING),
        ("latency_ns", DataType.INT64),
        ("pod", DataType.STRING),
    ]
)

#: Every schema a PEM ships (InitSchemas analog): table name -> Relation.
CANONICAL_SCHEMAS: dict[str, Relation] = {
    "http_events": HTTP_EVENTS_RELATION,
    "conn_stats": CONN_STATS_RELATION,
    "stack_traces.beta": STACK_TRACES_RELATION,
    "mysql_events": MYSQL_EVENTS_RELATION,
    "pgsql_events": PGSQL_EVENTS_RELATION,
    "redis_events": REDIS_EVENTS_RELATION,
    "kafka_events.beta": KAFKA_EVENTS_RELATION,
    "cql_events": CQL_EVENTS_RELATION,
    "nats_events.beta": NATS_EVENTS_RELATION,
    "mux_events": MUX_EVENTS_RELATION,
    "amqp_events": AMQP_EVENTS_RELATION,
    "process_stats": PROCESS_STATS_RELATION,
    "network_stats": NETWORK_STATS_RELATION,
    "dns_events": DNS_EVENTS_RELATION,
    "proc_stat": PROC_STAT_RELATION,
    "bcc_pid_cpu_usage": PID_RUNTIME_RELATION,
    "proc_exit_events": PROC_EXIT_EVENTS_RELATION,
    "stirling_error": STIRLING_ERROR_RELATION,
    # Self-telemetry tables ship with every agent (the collector also
    # lazily creates them, but advertising the schema up front lets the
    # bundled self-monitoring scripts compile before the first query).
    **TELEMETRY_SCHEMAS,
}


def table_budgets(memory_limit_mb: int | None = None) -> dict:
    """{table: max_bytes} budget map (+ ``"*"`` default for non-canonical
    tables) — the ``pem_manager.cc:86-104`` split as data: http_events
    takes its percent, the rest divide the remainder evenly. Installed
    on a TableStore (``table_budgets``) it bounds lazily-created tables
    without pinning schemas."""
    from ..config import get_flag

    limit_mb = (
        memory_limit_mb if memory_limit_mb is not None
        else get_flag("table_store_data_limit_mb")
    )
    if limit_mb <= 0:
        return {}
    memory_limit = limit_mb * 1024 * 1024
    # Clamp: >= 100 would zero (or, negative, UNBOUND) every other table.
    http_pct = min(max(get_flag("table_store_http_events_percent"), 0), 95)
    http_bytes = http_pct * memory_limit // 100
    other = (memory_limit - http_bytes) // max(len(CANONICAL_SCHEMAS) - 1, 1)
    out = {name: other for name in CANONICAL_SCHEMAS}
    out["http_events"] = http_bytes
    out["*"] = other
    return out


def init_schemas(target, memory_limit_mb: int | None = None) -> None:
    """Create every canonical table on an engine/table-store-like target
    with the reference's byte-budget split (``pem_manager.cc:86-104``
    InitSchemas): the ``table_store_data_limit_mb`` budget bounds ALL
    tables, http_events takes ``table_store_http_events_percent`` of it
    and the rest divide the remainder evenly. Each table's ring expires
    its own oldest rows at its budget, so one chatty protocol can never
    evict another's history — the backpressure is per-table by
    construction."""
    budgets = table_budgets(memory_limit_mb)
    if not budgets:
        for name, rel in CANONICAL_SCHEMAS.items():
            target.create_table(name, rel)
        return
    for name, rel in CANONICAL_SCHEMAS.items():
        target.create_table(name, rel, max_bytes=budgets[name])
