"""Ingest edge: source connectors feeding the table store.

Reference parity: ``src/stirling`` core (SURVEY.md §2.3). The eBPF
collectors themselves stay out of scope (they are the kernel-facing
edge); what this package rebuilds is everything Stirling exposes to the
rest of the system: the ``SourceConnector`` lifecycle, per-source
``DataTable`` buffers, the sampling/push ``FrequencyManager`` poll loop,
``RegisterDataPushCallback`` semantics, the synthetic ``seq_gen`` source
the test strategy leans on, procfs-based process stats, and the
benchmark replay loader. A native collector pushes through the same
C ABI the table store exposes (``pixie_tpu/native/table_ring.cc``).
"""

from .core import DataTable, FrequencyManager, SourceConnector
from .collector import Collector
from .connectors import (
    NetworkStatsConnector,
    PIDRuntimeConnector,
    ProcExitConnector,
    ProcStatConnector,
    ProcessStatsConnector,
    SeqGenConnector,
    StirlingErrorConnector,
)
from .replay import gen_http_events, replay_into

__all__ = [
    "Collector",
    "DataTable",
    "FrequencyManager",
    "NetworkStatsConnector",
    "PIDRuntimeConnector",
    "ProcExitConnector",
    "ProcStatConnector",
    "ProcessStatsConnector",
    "SeqGenConnector",
    "StirlingErrorConnector",
    "gen_http_events",
    "replay_into",
]
