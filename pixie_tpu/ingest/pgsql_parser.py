"""PostgreSQL wire-protocol parser + stitcher: captured bytes ->
pgsql_events.

Reference parity: the socket tracer's pgsql protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/pgsql/parse.cc`` — message framing — and ``stitcher.cc`` —
pairing query/extended-protocol exchanges). Capture arrives as byte
chunks from any tap; partial messages survive across ``feed`` calls.

Protocol essentials (PostgreSQL frontend/backend protocol v3, public
spec):
- After startup, every message is a 1-byte type tag + u32 big-endian
  length (length counts itself, not the tag).
- The startup packet and SSLRequest have NO tag (just length+payload);
  the server answers SSLRequest with a bare 'S'/'N' byte.
- Frontend: 'Q' simple query (SQL text), 'P' Parse (stmt\\0 sql\\0...),
  'B' Bind, 'E' Execute, 'S' Sync, 'X' Terminate.
- Backend: 'T' RowDescription, 'D' DataRow, 'C' CommandComplete (tag
  text like "SELECT 3"), 'E' ErrorResponse (\\0-separated fields, each
  1-byte code + text; 'M' = human message), 'Z' ReadyForQuery.

Stitching granularity is the sync point (stitcher.cc handles the same
grouping): a request unit is one 'Q', or an extended-protocol run
P/B/E/... closed by 'S'; the response unit is everything up to the next
'Z' (ReadyForQuery), summarized as the CommandComplete tags (plus
row count) or the error message.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from .conn_table import ConnectionTable


class _Framer:
    """Incremental tagged-message framing for one direction."""

    MAX_BUF = 1 << 20

    def __init__(self, frontend: bool):
        self._buf = b""
        self.frontend = frontend
        self._startup_done = not frontend
        self._skip = 0  # bytes of an oversized message still to discard
        self.oversized = 0

    def feed(self, data: bytes):
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                continue
            if self.frontend and not self._startup_done:
                # Startup / SSLRequest / CancelRequest: length-prefixed,
                # no tag. An SSLRequest (code 80877103) is typically
                # followed by the real StartupMessage (protocol v3) on
                # plaintext connections — stay in startup mode until the
                # StartupMessage itself has been consumed.
                if len(self._buf) < 8:
                    break
                ln = int.from_bytes(self._buf[:4], "big")
                code = int.from_bytes(self._buf[4:8], "big")
                if ln < 8 or ln > self.MAX_BUF:
                    self._startup_done = True  # already tagged traffic
                    continue
                if len(self._buf) < ln:
                    break
                self._buf = self._buf[ln:]
                if code >> 16 == 3:  # StartupMessage (major version 3)
                    self._startup_done = True
                continue
            if not self._buf:
                break
            tag = self._buf[0:1]
            if not self.frontend and tag in (b"N", b"S") and len(self._buf) >= 5:
                # Could be an SSLRequest answer (bare byte) — but 'S' is
                # no backend message start and 'N' (NoticeResponse) has a
                # length; disambiguate by checking the would-be length.
                ln = int.from_bytes(self._buf[1:5], "big")
                if ln < 4 or ln > self.MAX_BUF:
                    self._buf = self._buf[1:]
                    continue
            if len(self._buf) < 5:
                break
            ln = int.from_bytes(self._buf[1:5], "big")
            if ln < 4:
                self._buf = self._buf[1:]  # resync: skip a garbage byte
                continue
            if ln > self.MAX_BUF:
                # Oversized message (e.g. a giant COPY payload): discard
                # its remaining bytes incrementally — truncating the
                # buffer mid-message would desync framing forever.
                self.oversized += 1
                drop = min(1 + ln, len(self._buf))
                self._skip = 1 + ln - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                continue
            if len(self._buf) < 1 + ln:
                break
            out.append((tag.decode("latin-1"), self._buf[5:1 + ln]))
            self._buf = self._buf[1 + ln:]
        return out


def _cstr(b: bytes, off: int = 0) -> str:
    end = b.find(b"\0", off)
    return b[off:end if end >= 0 else len(b)].decode("utf-8", "replace")


def _error_message(body: bytes) -> str:
    """ErrorResponse fields: code byte + cstring, repeated, \\0 end."""
    msg, sev = "", ""
    i = 0
    while i < len(body) and body[i] != 0:
        code = chr(body[i])
        end = body.find(b"\0", i + 1)
        if end < 0:
            break
        text = body[i + 1:end].decode("utf-8", "replace")
        if code == "M":
            msg = text
        elif code == "S":
            sev = text
        i = end + 1
    return f"{sev}: {msg}" if sev else msg


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer(frontend=True)
        self.resp = _Framer(frontend=False)
        self.pending: deque = deque()  # (req_cmd, sql, ts)
        self.open_unit = None  # extended-protocol run being assembled
        self.resp_parts: list = []
        self.resp_rows = 0
        self.resp_err = ""


class PgSQLStitcher:
    """Pairs sync-point exchanges; emits pgsql_events records."""

    PENDING_PER_CONN = 256

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for tag, body in c.req.feed(data):
                emitted += self._frontend(conn_id, c, tag, body, ts)
            return emitted
        for tag, body in c.resp.feed(data):
            emitted += self._backend(c, tag, body, ts)
        return emitted

    def _push_pending(self, conn_id, c: _Conn, unit) -> bool:
        if len(c.pending) >= self.PENDING_PER_CONN:
            self.parse_errors += len(c.pending) + 1
            self._conns.kill(conn_id)
            return False
        c.pending.append(unit)
        return True

    def _frontend(self, conn_id, c: _Conn, tag, body, ts) -> int:
        if tag == "Q":
            self._push_pending(conn_id, c, ("QUERY", _cstr(body), ts))
            return 0
        if tag == "P":
            # Parse: statement name \0 query \0 n_params...
            name_end = body.find(b"\0")
            sql = _cstr(body, name_end + 1) if name_end >= 0 else ""
            c.open_unit = ["EXECUTE", sql, ts]
            return 0
        if tag in ("B", "D", "E", "H", "F"):
            if c.open_unit is None:
                c.open_unit = ["EXECUTE", "", ts]
            return 0
        if tag == "S":
            unit = c.open_unit or ["SYNC", "", ts]
            c.open_unit = None
            self._push_pending(conn_id, c, tuple(unit))
            return 0
        if tag == "X":
            return 0  # Terminate: nothing to pair
        return 0

    def _backend(self, c: _Conn, tag, body, ts) -> int:
        if tag == "C":
            c.resp_parts.append(_cstr(body))
            return 0
        if tag == "D":
            c.resp_rows += 1
            return 0
        if tag == "E":
            c.resp_err = _error_message(body)
            return 0
        if tag == "Z":
            return self._finish(c, ts)
        return 0  # T/1/2/3/N/A/K/R/S...: shape-only messages

    def _finish(self, c: _Conn, ts: int) -> int:
        parts, rows, err = c.resp_parts, c.resp_rows, c.resp_err
        c.resp_parts, c.resp_rows, c.resp_err = [], 0, ""
        if not c.pending:
            return 0  # ReadyForQuery after connection startup
        req_cmd, sql, req_ts = c.pending.popleft()
        if err:
            resp = err
        elif parts:
            resp = "; ".join(parts)
        else:
            resp = f"rows={rows}" if rows else ""
        self.records.append({
            "time_": req_ts,
            "req_cmd": req_cmd,
            "req": sql,
            "resp": resp,
            "latency_ns": max(ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })
        return 1

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
