"""AMQP 0-9-1 wire-protocol parser: captured bytes -> amqp_events.

Reference parity: the socket tracer's amqp protocol scaffolding
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/amqp/types.h`` — frame types kMethod/kHeader/kBody/
kHeartbeat). The reference's table is still WIP; this emits
method-level events with synchronous-method latency pairing, the shape
its other protocol tables share.

Protocol essentials (AMQP 0-9-1, public spec):
- Connection opens with the literal ``AMQP\\x00\\x00\\x09\\x01``.
- Every frame: type (1: method, 2: header, 3: body, 8: heartbeat),
  channel (u16 BE), payload size (u32 BE), payload, 0xCE frame-end.
- A method payload starts class-id (u16) + method-id (u16). Synchronous
  methods (queue.declare, basic.get, ...) are answered on the SAME
  channel by their ``*-ok`` counterpart; basic.publish/deliver are
  asynchronous (no reply).
"""

from __future__ import annotations

import time
from typing import Optional

from .conn_table import ConnectionTable

_PREAMBLE = b"AMQP\x00\x00\x09\x01"
FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8

#: (class_id, method_id) -> name (spec §1.1 class/method tables).
METHODS = {
    (10, 10): "connection.start", (10, 11): "connection.start-ok",
    (10, 30): "connection.tune", (10, 31): "connection.tune-ok",
    (10, 40): "connection.open", (10, 41): "connection.open-ok",
    (10, 50): "connection.close", (10, 51): "connection.close-ok",
    (20, 10): "channel.open", (20, 11): "channel.open-ok",
    (20, 20): "channel.flow", (20, 21): "channel.flow-ok",
    (20, 40): "channel.close", (20, 41): "channel.close-ok",
    (40, 10): "exchange.declare", (40, 11): "exchange.declare-ok",
    (40, 20): "exchange.delete", (40, 21): "exchange.delete-ok",
    (50, 10): "queue.declare", (50, 11): "queue.declare-ok",
    (50, 20): "queue.bind", (50, 21): "queue.bind-ok",
    (50, 30): "queue.purge", (50, 31): "queue.purge-ok",
    (50, 40): "queue.delete", (50, 41): "queue.delete-ok",
    (50, 50): "queue.unbind", (50, 51): "queue.unbind-ok",
    (60, 10): "basic.qos", (60, 11): "basic.qos-ok",
    (60, 20): "basic.consume", (60, 21): "basic.consume-ok",
    (60, 30): "basic.cancel", (60, 31): "basic.cancel-ok",
    (60, 40): "basic.publish", (60, 50): "basic.return",
    (60, 60): "basic.deliver",
    (60, 70): "basic.get", (60, 71): "basic.get-ok",
    (60, 72): "basic.get-empty",
    (60, 80): "basic.ack", (60, 90): "basic.reject",
    (60, 110): "basic.recover", (60, 111): "basic.recover-ok",
    (85, 10): "confirm.select", (85, 11): "confirm.select-ok",
    (90, 10): "tx.select", (90, 11): "tx.select-ok",
    (90, 20): "tx.commit", (90, 21): "tx.commit-ok",
    (90, 30): "tx.rollback", (90, 31): "tx.rollback-ok",
}
#: Async methods never awaited (publish/deliver/ack...).
_ASYNC = {(60, 40), (60, 50), (60, 60), (60, 80), (60, 90)}


class _Framer:
    MAX_BODY = 4 << 20

    def __init__(self):
        self._buf = b""
        self._preamble_done = False
        self._skip = 0
        self.oversized = 0

    def feed(self, data: bytes):
        """Yield (frame_type, channel, class_id, method_id) — method ids
        are (0, 0) for non-method frames."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                continue
            if not self._preamble_done:
                if _PREAMBLE.startswith(self._buf[:len(_PREAMBLE)]):
                    # Buffer is a (possibly partial) preamble prefix:
                    # wait for the rest before deciding.
                    if len(self._buf) < len(_PREAMBLE):
                        break
                    self._buf = self._buf[len(_PREAMBLE):]
                self._preamble_done = True
                continue
            if len(self._buf) < 7:
                break
            ftype = self._buf[0]
            channel = int.from_bytes(self._buf[1:3], "big")
            size = int.from_bytes(self._buf[3:7], "big")
            if ftype not in (FRAME_METHOD, FRAME_HEADER, FRAME_BODY,
                             FRAME_HEARTBEAT):
                self._buf = self._buf[1:]  # garbage: resync byte-wise
                continue
            if size > self.MAX_BODY:
                # Oversized body frame: header info is enough to emit.
                self.oversized += 1
                out.append((ftype, channel, 0, 0))
                drop = min(7 + size + 1, len(self._buf))
                self._skip = 7 + size + 1 - drop
                self._buf = self._buf[drop:]
                continue
            if len(self._buf) < 7 + size + 1:
                break
            payload = self._buf[7:7 + size]
            self._buf = self._buf[7 + size + 1:]  # +1: 0xCE frame end
            if ftype == FRAME_METHOD and len(payload) >= 4:
                cid = int.from_bytes(payload[0:2], "big")
                mid = int.from_bytes(payload[2:4], "big")
                out.append((ftype, channel, cid, mid))
            else:
                out.append((ftype, channel, 0, 0))
        return out


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        # channel -> (class_id, method_id, ts) awaiting its *-ok.
        self.pending: dict = {}


class AMQPStitcher:
    """Emits method events; synchronous methods pair with their -ok
    reply on the same channel for latency."""

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(self, conn_id, data: bytes, is_request: bool,
             ts_ns: Optional[int] = None) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        framer = c.req if is_request else c.resp
        emitted = 0
        for ftype, channel, cid, mid in framer.feed(data):
            if ftype != FRAME_METHOD:
                continue  # header/body/heartbeat frames carry no event
            name = METHODS.get((cid, mid), f"class{cid}.method{mid}")
            if is_request:
                if (cid, mid) in _ASYNC or (cid, mid) not in METHODS:
                    self._emit(channel, name, ts, 0)
                    emitted += 1
                else:
                    prev = c.pending.pop(channel, None)
                    if prev is not None:
                        # Unanswered sync method (lost capture): emit it.
                        self._emit(channel, prev[2], prev[3], 0)
                        emitted += 1
                        self.parse_errors += 1
                    c.pending[channel] = (cid, mid, name, ts)
            else:
                req = c.pending.get(channel)
                if req is not None and mid in (req[1] + 1, req[1] + 2):
                    # *-ok (and basic.get-empty = get + 2) answers it.
                    del c.pending[channel]
                    self._emit(channel, req[2], req[3],
                               max(ts - req[3], 0), resp=name)
                    emitted += 1
                else:
                    # Server-initiated method (deliver, close, start...).
                    self._emit(channel, name, ts, 0)
                    emitted += 1
        return emitted

    def _emit(self, channel, method, ts, latency, resp: str = ""):
        self.records.append({
            "time_": ts,
            "channel": int(channel),
            "method": method,
            "resp": resp,
            "latency_ns": int(latency),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
