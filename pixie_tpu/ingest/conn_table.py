"""Shared per-connection tracker table for protocol stitchers.

Reference parity: the socket tracer's ConnTracker lifecycle
(``socket_trace_connector.cc`` expires idle trackers and disables ones
it can no longer trust). Every stitcher (HTTP/MySQL/PgSQL) keeps
per-connection parser state; this table owns the eviction policy so it
exists in exactly one place: idle connections expire after a TTL sweep,
and at the hard cap the least-recently-used tracker is dropped.

Connection state objects must expose a mutable ``last_ts`` attribute.
"""

from __future__ import annotations


class ConnectionTable:
    IDLE_TTL_NS = 300 * 1_000_000_000
    MAX_CONNS = 4096
    SWEEP_MIN = 64  # skip the TTL sweep below this population

    def __init__(self, factory):
        self._factory = factory
        self._conns: dict = {}

    def __len__(self) -> int:
        return len(self._conns)

    def get(self, conn_id, now_ns: int):
        """The connection's state, created on first sight; touches its
        last-activity timestamp."""
        c = self._conns.get(conn_id)
        if c is None:
            self._evict(now_ns)
            c = self._factory()
            c.last_ts = now_ns
            self._conns[conn_id] = c
        c.last_ts = now_ns
        return c

    def kill(self, conn_id) -> None:
        """Drop a tracker whose stream can no longer be trusted."""
        self._conns.pop(conn_id, None)

    def values(self):
        return self._conns.values()

    def _evict(self, now_ns: int) -> None:
        cutoff = now_ns - self.IDLE_TTL_NS
        if len(self._conns) > self.SWEEP_MIN:
            self._conns = {
                cid: c for cid, c in self._conns.items()
                if c.last_ts >= cutoff
            }
        while len(self._conns) >= self.MAX_CONNS:
            lru = min(self._conns, key=lambda cid: self._conns[cid].last_ts)
            self._conns.pop(lru)
