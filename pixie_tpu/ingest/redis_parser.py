"""Redis (RESP) wire-protocol parser + stitcher: captured bytes ->
redis_events.

Reference parity: the socket tracer's redis protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/redis/parse.cc`` — RESP value parsing — and ``stitcher``/
``cmd_args.cc`` — command classification + arg formatting). Capture
arrives as byte chunks from any tap and flows through an incremental
per-connection state machine; partial values survive across ``feed``.

Protocol essentials (RESP2/RESP3, public spec):
- Every value starts with a type byte: '+' simple string, '-' error,
  ':' integer, '$' bulk string (length then payload + CRLF; -1 = null),
  '*' array (element count then nested values; -1 = null). RESP3 adds
  '_' null, '#' bool, ',' double, '(' big number, '=' verbatim string,
  '%' map, '~' set, '>' push.
- A client request is an array of bulk strings (or an inline text
  line); the first element is the command, optionally two-word
  (CONFIG GET, XINFO STREAM, ...).
- Responses pair with requests positionally (pipelining preserves
  order). '>' push frames (and pub/sub 'message' arrays) arrive
  without a request and are emitted as standalone PUSH records — the
  reference handles published messages the same way.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from .conn_table import ConnectionTable

#: Commands whose first argument completes the command name
#: (redis command table's container commands).
_TWO_WORD = frozenset({
    "ACL", "CLIENT", "CLUSTER", "COMMAND", "CONFIG", "DEBUG", "FUNCTION",
    "LATENCY", "MEMORY", "OBJECT", "PUBSUB", "SCRIPT", "SLOWLOG", "XGROUP",
    "XINFO",
})

_MAX_BULK = 1 << 20      # payloads past this are skipped, not buffered
_MAX_VALUE_BYTES = 256   # per-value cap in formatted output


class _Incomplete(Exception):
    pass


class _RESPParser:
    """Incremental RESP value parser for one direction."""

    MAX_BUF = 4 << 20

    def __init__(self):
        self._buf = b""
        self._skip = 0  # bytes of an oversized bulk still to discard
        self.oversized = 0
        self.resync = 0

    def feed(self, data: bytes):
        """Consume bytes; return a list of complete top-level values.

        An oversized bulk string parses as the '<oversized>' sentinel
        (its payload is discarded incrementally so framing never
        desyncs)."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                continue
            if not self._buf:
                break
            try:
                val, pos = self._value(0, top=True)
            except _Incomplete:
                if len(self._buf) > self.MAX_BUF:
                    # Unparseable giant buffer: drop it rather than grow
                    # without bound (a lost capture byte can do this).
                    self._buf = b""
                    self.resync += 1
                break
            out.append(val)
            self._buf = self._buf[pos:]
        return out

    # -- single-value parse (raises _Incomplete to wait for more bytes) ------
    def _line(self, pos: int):
        end = self._buf.find(b"\r\n", pos)
        if end < 0:
            raise _Incomplete  # feed()'s MAX_BUF guard bounds the wait
        return self._buf[pos:end], end + 2

    def _value(self, pos: int, top: bool = False):
        if pos >= len(self._buf):
            raise _Incomplete
        t = self._buf[pos:pos + 1]
        if t in (b"+", b"-", b":", b"_", b"#", b",", b"("):
            line, pos2 = self._line(pos + 1)
            text = line.decode("utf-8", "replace")
            if t == b"+":
                return text, pos2
            if t == b"-":
                return ("err", text), pos2
            if t == b":":
                return _int_or(text), pos2
            if t == b"_":
                return None, pos2
            if t == b"#":
                return text == "t", pos2
            return text, pos2  # double / big number as text
        if t in (b"$", b"="):
            line, pos2 = self._line(pos + 1)
            n = _int_or(line.decode("latin-1"), None)
            if n is None:
                raise _Incomplete
            if n < 0:
                return None, pos2
            if len(self._buf) >= pos2 + n + 2:
                if n > _MAX_BULK:
                    self.oversized += 1
                    return "<oversized>", pos2 + n + 2
                payload = self._buf[pos2:pos2 + n]
                return payload.decode("utf-8", "replace"), pos2 + n + 2
            if n > _MAX_BULK and top:
                # Top-level giant bulk (GET of a multi-MB key): complete
                # it as a sentinel NOW and discard its payload
                # incrementally, so the buffer never holds the body.
                # Nested giant bulks (inside an array) can't skip without
                # corrupting the outer parse — they either arrive fully
                # (branch above) or hit the MAX_BUF resync drop.
                self.oversized += 1
                self._skip = pos2 + n + 2 - len(self._buf)
                self._buf = b""
                return "<oversized>", 0
            raise _Incomplete
        if t in (b"*", b"%", b"~", b">"):
            line, pos2 = self._line(pos + 1)
            n = _int_or(line.decode("latin-1"), None)
            if n is None:
                raise _Incomplete
            if n < 0:
                return None, pos2
            if t == b"%":
                n *= 2  # maps carry n key-value pairs
            if n > 1 << 20:
                raise _Incomplete  # absurd count: wait, then resync-drop
            items = []
            for _ in range(n):
                v, pos2 = self._value(pos2)
                items.append(v)
            if t == b">":
                return ("push", items), pos2
            return items, pos2
        # Inline command (plain text line) — the spec's legacy form.
        line, pos2 = self._line(pos)
        return [w.decode("utf-8", "replace") for w in line.split()], pos2


def _int_or(s, default=0):
    try:
        return int(s)
    except ValueError:
        return default


def _fmt(val, depth: int = 0) -> str:
    """Human-readable response rendering (cmd_args.cc FormatToJSON
    analog, without the JSON escape machinery)."""
    if val is None:
        return "<null>"
    if isinstance(val, bool):
        return "true" if val else "false"
    if isinstance(val, tuple) and len(val) == 2 and val[0] == "err":
        return f"-{val[1]}"
    if isinstance(val, tuple) and len(val) == 2 and val[0] == "push":
        return "[" + ", ".join(_fmt(v, depth + 1) for v in val[1][:16]) + "]"
    if isinstance(val, list):
        if depth >= 3:
            return f"[{len(val)} items]"
        body = ", ".join(_fmt(v, depth + 1) for v in val[:16])
        more = f", +{len(val) - 16}" if len(val) > 16 else ""
        return f"[{body}{more}]"
    s = str(val)
    return s if len(s) <= _MAX_VALUE_BYTES else s[:_MAX_VALUE_BYTES] + "..."


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _RESPParser()
        self.resp = _RESPParser()
        self.pending: deque = deque()  # (cmd, args, ts)


class RedisStitcher:
    """Pairs RESP requests with positional responses; emits redis_events
    records."""

    PENDING_PER_CONN = 512  # pipelining runs deep on redis

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for val in c.req.feed(data):
                if not isinstance(val, list) or not val:
                    self.parse_errors += 1
                    continue
                words = [str(w) for w in val]
                cmd = words[0].upper()
                rest = words[1:]
                if cmd in _TWO_WORD and rest:
                    cmd = f"{cmd} {rest[0].upper()}"
                    rest = rest[1:]
                args = " ".join(
                    w if len(w) <= 64 else w[:64] + "..." for w in rest[:16]
                )
                if len(c.pending) >= self.PENDING_PER_CONN:
                    self.parse_errors += len(c.pending) + 1
                    self._conns.kill(conn_id)
                    return emitted
                c.pending.append((cmd, args, ts))
            return emitted
        for val in c.resp.feed(data):
            if isinstance(val, tuple) and len(val) == 2 and val[0] == "push":
                # RESP3 push / pub-sub delivery: no request to pair.
                self._emit("PUSH", "", ts, ts, _fmt(val))
                emitted += 1
                continue
            if not c.pending:
                # Pub/sub 'message' arrays on RESP2 subscribers also
                # arrive unrequested.
                if isinstance(val, list) and val and str(val[0]).lower() in (
                    "message", "pmessage", "smessage"
                ):
                    self._emit("PUSH", "", ts, ts, _fmt(val))
                    emitted += 1
                else:
                    self.parse_errors += 1
                continue
            cmd, args, req_ts = c.pending.popleft()
            self._emit(cmd, args, req_ts, ts, _fmt(val))
            emitted += 1
        return emitted

    def _emit(self, cmd, args, req_ts, resp_ts, resp):
        self.records.append({
            "time_": req_ts,
            "req_cmd": cmd,
            "req_args": args,
            "resp": resp,
            "latency_ns": max(resp_ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
