"""HTTP/2 wire-protocol parser: captured bytes -> http_events records.

Reference parity: the socket tracer's http2 protocol
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/http2/`` — which does NOT parse wire HPACK at all: it attaches
uprobes inside Go/gRPC runtimes and reads the ALREADY-DECODED header
fields, because its kernel capture cannot see through TLS). This parser
handles the plaintext/h2c + decrypted-tap case the capture-tap feeds:
real frame framing and real HPACK header decoding (static + dynamic
tables, integer/string literals). Huffman-coded string literals decode
to the ``<huffman>`` placeholder — a documented limitation, one step
past the reference's no-wire-parsing baseline.

Protocol essentials (RFC 7540/7541, public spec):
- Client connection preface: ``PRI * HTTP/2.0\\r\\n\\r\\nSM\\r\\n\\r\\n``.
- Every frame: length (u24 BE), type (u8), flags (u8), R + stream id
  (u31 BE), payload.
- HEADERS (+ CONTINUATION until END_HEADERS) carry an HPACK block;
  requests use :method/:path pseudo-headers, responses :status.
  Requests pair with responses BY STREAM ID.
"""

from __future__ import annotations

import time
from typing import Optional

from .conn_table import ConnectionTable

_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

F_DATA, F_HEADERS, F_PRIORITY, F_RST, F_SETTINGS = 0, 1, 2, 3, 4
F_PUSH, F_PING, F_GOAWAY, F_WINDOW, F_CONT = 5, 6, 7, 8, 9
FLAG_END_STREAM, FLAG_END_HEADERS, FLAG_PADDED, FLAG_PRIORITY = 1, 4, 8, 0x20

#: RFC 7541 Appendix A static table (1-based).
_STATIC = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin", ""),
    ("age", ""), ("allow", ""), ("authorization", ""), ("cache-control", ""),
    ("content-disposition", ""), ("content-encoding", ""),
    ("content-language", ""), ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""), ("date", ""),
    ("etag", ""), ("expect", ""), ("expires", ""), ("from", ""), ("host", ""),
    ("if-match", ""), ("if-modified-since", ""), ("if-none-match", ""),
    ("if-range", ""), ("if-unmodified-since", ""), ("last-modified", ""),
    ("link", ""), ("location", ""), ("max-forwards", ""),
    ("proxy-authenticate", ""), ("proxy-authorization", ""), ("range", ""),
    ("referer", ""), ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""), ("via", ""),
    ("www-authenticate", ""),
]


class HPACKDecoder:
    """Per-direction HPACK decoding context (RFC 7541)."""

    def __init__(self, max_size: int = 4096):
        self.dynamic: list[tuple[str, str]] = []
        self.max_size = max_size
        self.size = 0

    def _entry(self, idx: int):
        if 1 <= idx <= len(_STATIC):
            return _STATIC[idx - 1]
        d = idx - len(_STATIC) - 1
        if 0 <= d < len(self.dynamic):
            return self.dynamic[d]
        raise ValueError(f"HPACK index {idx} out of range")

    def _add(self, name: str, value: str):
        self.dynamic.insert(0, (name, value))
        self.size += len(name) + len(value) + 32
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= len(n) + len(v) + 32

    @staticmethod
    def _int(data: bytes, pos: int, prefix: int):
        mask = (1 << prefix) - 1
        v = data[pos] & mask
        pos += 1
        if v < mask:
            return v, pos
        shift = 0
        while pos < len(data):
            b = data[pos]
            pos += 1
            v += (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        return v, pos

    def _string(self, data: bytes, pos: int):
        huffman = bool(data[pos] & 0x80)
        n, pos = self._int(data, pos, 7)
        raw = data[pos:pos + n]
        pos += n
        if huffman:
            # Huffman decoding needs the RFC 7541 Appendix B code table;
            # keep framing/table state exact and surface a placeholder.
            return "<huffman>", pos
        return raw.decode("utf-8", "replace"), pos

    def decode(self, block: bytes) -> list[tuple[str, str]]:
        out = []
        pos = 0
        while pos < len(block):
            b = block[pos]
            if b & 0x80:  # indexed
                idx, pos = self._int(block, pos, 7)
                out.append(self._entry(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = self._int(block, pos, 6)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, pos = self._string(block, pos)
                value, pos = self._string(block, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                self.max_size, pos = self._int(block, pos, 5)
                while self.size > self.max_size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self.size -= len(n) + len(v) + 32
            else:  # literal without indexing / never indexed
                idx, pos = self._int(block, pos, 4)
                name = self._entry(idx)[0] if idx else None
                if name is None:
                    name, pos = self._string(block, pos)
                value, pos = self._string(block, pos)
                out.append((name, value))
        return out


class _Framer:
    MAX_BODY = 4 << 20

    def __init__(self, client_side: bool):
        self._buf = b""
        self._preface_done = not client_side
        self._skip = 0
        self._skip_hdr = None
        self.oversized = 0

    def feed(self, data: bytes):
        """Yield (type, flags, stream, payload|None) frames."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                out.append((*self._skip_hdr, None))
                continue
            if not self._preface_done:
                if _PREFACE.startswith(self._buf[:len(_PREFACE)]):
                    # Partial preface prefix: wait for the rest before
                    # deciding (a 2-byte first chunk must not misparse).
                    if len(self._buf) < len(_PREFACE):
                        break
                    self._buf = self._buf[len(_PREFACE):]
                self._preface_done = True
                continue
            if len(self._buf) < 9:
                break
            ln = int.from_bytes(self._buf[:3], "big")
            ftype = self._buf[3]
            flags = self._buf[4]
            stream = int.from_bytes(self._buf[5:9], "big") & 0x7FFFFFFF
            if ftype > F_CONT:
                self._buf = self._buf[1:]  # garbage: resync byte-wise
                continue
            if ln > self.MAX_BODY:
                self.oversized += 1
                self._skip_hdr = (ftype, flags, stream)
                drop = min(9 + ln, len(self._buf))
                self._skip = 9 + ln - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                out.append((*self._skip_hdr, None))
                continue
            if len(self._buf) < 9 + ln:
                break
            out.append((ftype, flags, stream, self._buf[9:9 + ln]))
            self._buf = self._buf[9 + ln:]
        return out


def _strip_headers_payload(flags: int, payload: bytes) -> bytes:
    """Remove padding/priority sections from a HEADERS payload."""
    pos = 0
    pad = 0
    if flags & FLAG_PADDED and len(payload) > 0:
        pad = payload[0]
        pos = 1
    if flags & FLAG_PRIORITY:
        pos += 5
    end = len(payload) - pad
    return payload[pos:max(pos, end)]


class _Stream:
    __slots__ = ("method", "path", "req_ts", "status", "body_bytes")

    def __init__(self):
        self.method = ""
        self.path = ""
        self.req_ts = 0
        self.status = 0
        self.body_bytes = 0


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer(client_side=True)
        self.resp = _Framer(client_side=False)
        self.req_hpack = HPACKDecoder()
        self.resp_hpack = HPACKDecoder()
        self.streams: dict[int, _Stream] = {}
        # CONTINUATION accumulation per direction: (stream, flags, block)
        self.frag: dict[bool, tuple] = {}


class HTTP2Stitcher:
    """Pairs request/response HEADERS by stream id; emits http_events
    records (the HTTPStitcher record shape, so the tap merges both)."""

    MAX_STREAMS = 1024

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(self, conn_id, data: bytes, is_request: bool,
             ts_ns: Optional[int] = None) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        framer = c.req if is_request else c.resp
        emitted = 0
        for ftype, flags, stream, payload in framer.feed(data):
            if payload is None:
                self.parse_errors += 1
                continue
            if ftype == F_RST:
                # Cancelled stream (gRPC deadline-exceeded etc.): drop
                # its state so it can't linger to the MAX_STREAMS cap.
                c.streams.pop(stream, None)
                continue
            if ftype == F_DATA and not is_request:
                st = c.streams.get(stream)
                if st is not None:
                    st.body_bytes += len(payload)
                    if flags & FLAG_END_STREAM:
                        emitted += self._finish(c, stream, ts)
                continue
            if ftype not in (F_HEADERS, F_CONT):
                continue
            if ftype == F_HEADERS:
                block = _strip_headers_payload(flags, payload)
            else:
                prev = c.frag.pop(is_request, None)
                if prev is None or prev[0] != stream:
                    self.parse_errors += 1
                    continue
                block = prev[2] + payload
                flags |= prev[1] & FLAG_END_STREAM
            if not flags & FLAG_END_HEADERS:
                c.frag[is_request] = (stream, flags, block)
                continue
            dec = c.req_hpack if is_request else c.resp_hpack
            try:
                headers = dict(dec.decode(block))
            except (ValueError, IndexError):
                self.parse_errors += 1
                continue
            if is_request:
                if len(c.streams) >= self.MAX_STREAMS:
                    c.streams.pop(next(iter(c.streams)))
                    self.parse_errors += 1
                st = c.streams.setdefault(stream, _Stream())
                st.method = headers.get(":method", "")
                st.path = headers.get(":path", "")
                st.req_ts = ts
            else:
                st = c.streams.get(stream)
                if st is None:
                    self.parse_errors += 1
                    continue
                try:
                    st.status = int(headers.get(":status", "0"))
                except ValueError:
                    st.status = 0
                if flags & FLAG_END_STREAM:
                    emitted += self._finish(c, stream, ts)
        return emitted

    def _finish(self, c: _Conn, stream: int, ts: int) -> int:
        st = c.streams.pop(stream, None)
        if st is None:
            return 0
        self.records.append({
            "time_": st.req_ts or ts,
            "req_method": st.method,
            "req_path": st.path,
            "resp_status": st.status,
            "resp_body_bytes": st.body_bytes,
            "latency_ns": max(ts - st.req_ts, 0) if st.req_ts else 0,
            "service": self.service,
            "pod": self.pod,
        })
        return 1

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
