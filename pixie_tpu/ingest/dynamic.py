"""Dynamic tracer: deploy tracepoints as runtime-registered connectors.

Reference parity: ``src/stirling/source_connectors/dynamic_tracer/
dynamic_tracer.h:48`` ``CompileProgram`` — a TracepointDeployment
compiles through dwarvifier (argument layout) + code_gen (BCC C) and
attaches kernel uprobes that stream records into a brand-new table.

TPU-native analog: the instrumentation surface is **in-process Python
callables** (this runtime's "symbols"). A ``TraceTargetRegistry`` maps
symbol names to patchable attributes; attaching wraps the callable so
every call records (time, upid, captured args/ret/latency) into a
lock-guarded ring that the connector's ``transfer_data`` drains into the
deployment's table — the same connector lifecycle every other source
uses (``ingest/core.py``), so the collector loop, push thresholds, and
schema publication all apply unchanged.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..trace.spec import TracepointDeployment
from ..types.dtypes import DataType
from ..utils.upid import UPID
from .core import SourceConnector


class TraceError(Exception):
    pass


def native_probe_plan(binary_path: str, function: str) -> dict:
    """Capture plan for probing a native function: the dwarvifier step
    (reference ``dynamic_tracer/.../dwarvifier.h`` — resolve a probed
    function's argument names/types/sizes/frame offsets from DWARF so a
    tracepoint knows what to read where). Raises TraceError when the
    binary has no debug info or the function is unknown.

    Returns ``{"function", "address", "args": {name: {"type", "size",
    "frame_offset"}}}`` — what an instrumentation backend (or an
    operator inspecting a probe target) needs.
    """
    from ..utils.dwarf import DwarfError, DwarfReader

    try:
        reader = DwarfReader(binary_path)
    except DwarfError as e:
        raise TraceError(str(e)) from None
    except (OSError, ValueError, struct.error, IndexError) as e:
        # Missing file / truncated or corrupt ELF: same contract.
        raise TraceError(f"{binary_path}: {e}") from None
    fn = reader.functions.get(function)
    if fn is None:
        raise TraceError(
            f"no DWARF subprogram {function!r} in {binary_path} "
            f"(known: {sorted(reader.functions)[:12]})"
        )
    return {
        "function": fn.name,
        "address": fn.low_pc,
        "args": {
            a.name: {
                "type": a.type_name,
                "size": a.byte_size,
                "frame_offset": a.frame_offset,
            }
            for a in fn.args
        },
    }


@dataclass
class _Target:
    owner: object
    attr: str

    @property
    def fn(self):
        return getattr(self.owner, self.attr)


class TraceTargetRegistry:
    """symbol -> patchable callable (the ELF/DWARF symbol table analog)."""

    def __init__(self):
        self._targets: dict[str, _Target] = {}

    def register(self, symbol: str, owner, attr: str) -> None:
        if not callable(getattr(owner, attr, None)):
            raise TraceError(f"{symbol!r}: {attr!r} is not callable")
        self._targets[symbol] = _Target(owner, attr)

    def resolve(self, symbol: str) -> _Target:
        if symbol not in self._targets:
            raise TraceError(
                f"no traceable target registered for symbol {symbol!r}"
            )
        return self._targets[symbol]

    def symbols(self) -> list[str]:
        return sorted(self._targets)


def _cast(value, dtype: DataType):
    try:
        if dtype == DataType.STRING:
            return str(value)
        if dtype == DataType.FLOAT64:
            return float(value)
        if dtype == DataType.BOOLEAN:
            return bool(value)
        return int(value)  # INT64 / TIME64NS
    except (TypeError, ValueError):
        return "" if dtype == DataType.STRING else 0


class DynamicTraceConnector(SourceConnector):
    """A deployed tracepoint: wraps the target callable, buffers records.

    ``init()`` attaches (patches the registered attribute), ``stop()``
    detaches and restores the original callable.
    """

    default_sampling_period_s = 0.05

    def __init__(self, deployment: TracepointDeployment,
                 registry: TraceTargetRegistry, asid: int = 0, **kw):
        super().__init__(**kw)
        self.deployment = deployment
        self.name = f"dynamic:{deployment.name}"
        self.relation = deployment.relation()
        self.tables = [(deployment.table_name, self.relation)]
        self._registry = registry
        self._asid = asid
        self._upid = UPID(asid=asid, pid=os.getpid() & 0xFFFFFFFF,
                          start_ts=int(time.monotonic_ns() & (2**63 - 1)))
        self._lock = threading.Lock()
        self._ring: list[tuple] = []
        self._max_ring = 1 << 16
        self._target = None
        self._orig = None
        self._wrapped = None

    # -- attach / detach ----------------------------------------------------
    def init(self) -> None:
        self._target = self._registry.resolve(self.deployment.probe.target)
        self._orig = self._target.fn
        outputs = self.deployment.probe.outputs
        orig = self._orig
        upid = self._upid
        ring, lock, max_ring = self._ring, self._lock, self._max_ring
        # Argument layout resolution (the dwarvifier analog): bind call
        # args against the target's signature so named captures see
        # applied defaults.
        import inspect

        try:
            sig = inspect.signature(orig)
        except (TypeError, ValueError):
            sig = None

        def pick(expr, args, kwargs):
            if expr.startswith("arg") and expr[3:].isdigit():
                i = int(expr[3:])
                return args[i] if i < len(args) else 0
            if sig is not None:
                try:
                    ba = sig.bind(*args, **kwargs)
                    ba.apply_defaults()
                    if expr in ba.arguments:
                        return ba.arguments[expr]
                except TypeError:
                    pass
            return kwargs.get(expr, 0)

        # The inner callable lives in a mutable cell so a tracepoint can
        # be spliced out of a wrapper CHAIN (two tracepoints on one
        # symbol) without un-wrapping the others.
        holder = [orig]

        def wrapped(*args, **kwargs):
            t0 = time.perf_counter_ns()
            ret = holder[0](*args, **kwargs)
            t1 = time.perf_counter_ns()
            row = [time.time_ns(), upid.hi, upid.lo]
            for _col, te in outputs:
                if te.kind == "latency":
                    row.append(_cast(t1 - t0, te.dtype))
                elif te.kind == "ret":
                    row.append(_cast(ret, te.dtype))
                else:  # arg
                    row.append(_cast(pick(te.expr, args, kwargs), te.dtype))
            with lock:
                ring.append(tuple(row))
                if len(ring) > max_ring:
                    del ring[: len(ring) - max_ring]
            return ret

        wrapped._pxt_holder = holder
        self._wrapped = wrapped
        setattr(self._target.owner, self._target.attr, wrapped)
        super().init()

    def stop(self) -> None:
        if self._target is not None and self._wrapped is not None:
            cur = getattr(self._target.owner, self._target.attr)
            if cur is self._wrapped:
                setattr(
                    self._target.owner, self._target.attr,
                    self._wrapped._pxt_holder[0],
                )
            else:
                # We are somewhere inside a wrapper chain: splice our
                # layer out by pointing the enclosing wrapper's cell at
                # our inner callable.
                w = cur
                while getattr(w, "_pxt_holder", None) is not None:
                    if w._pxt_holder[0] is self._wrapped:
                        w._pxt_holder[0] = self._wrapped._pxt_holder[0]
                        break
                    w = w._pxt_holder[0]
            self._target = None
            self._orig = None
            self._wrapped = None
        super().stop()

    # -- collection ---------------------------------------------------------
    def transfer_data(self, ctx, data_tables: dict) -> None:
        with self._lock:
            rows, self._ring[:] = list(self._ring), []
        if not rows:
            return
        cols = list(zip(*rows))
        records = {
            "time_": np.asarray(cols[0], dtype=np.int64),
            "upid": np.stack(
                [
                    np.asarray(cols[1], dtype=np.uint64),
                    np.asarray(cols[2], dtype=np.uint64),
                ],
                axis=1,
            ),
        }
        for i, (col, te) in enumerate(self.deployment.probe.outputs):
            vals = cols[3 + i]
            if te.dtype == DataType.STRING:
                records[col] = np.asarray(vals, dtype=object)
            elif te.dtype == DataType.FLOAT64:
                records[col] = np.asarray(vals, dtype=np.float64)
            elif te.dtype == DataType.BOOLEAN:
                records[col] = np.asarray(vals, dtype=bool)
            else:
                records[col] = np.asarray(vals, dtype=np.int64)
        data_tables[self.deployment.table_name].append(records)


def compile_program(deployment: TracepointDeployment,
                    registry: TraceTargetRegistry,
                    asid: int = 0) -> DynamicTraceConnector:
    """dynamic_tracer.h:48 CompileProgram analog: validate the target
    resolves and produce the attachable connector."""
    registry.resolve(deployment.probe.target)  # fail fast (FAILED state)
    return DynamicTraceConnector(deployment, registry, asid=asid)
