"""NATS wire-protocol parser: captured bytes -> nats_events.beta.

Reference parity: the socket tracer's nats protocol
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/nats/`` and ``nats_table.h`` kNATSElements: cmd / body /
resp). Capture arrives as byte chunks from any tap; partial commands
survive across ``feed`` calls.

Protocol essentials (NATS client protocol, public spec):
- Text commands terminated by CRLF: CONNECT {json}, INFO {json},
  SUB <subject> [queue] <sid>, UNSUB <sid> [max], PING, PONG,
  +OK, -ERR 'message'.
- PUB <subject> [reply-to] <#bytes>\\r\\n<payload>\\r\\n and the server's
  MSG <subject> <sid> [reply-to] <#bytes>\\r\\n<payload>\\r\\n carry a
  length-prefixed binary payload after the command line (HPUB/HMSG add
  a headers section; the total-size field still bounds the skip).
- Responses (+OK/-ERR) appear only in verbose mode and apply to the
  PREVIOUS client command; the reference emits one event per command
  with the response attached when present.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Optional

from .conn_table import ConnectionTable

_PAYLOAD_CMDS = {"PUB", "MSG", "HPUB", "HMSG"}
_MAX_SHOWN_PAYLOAD = 128
_MAX_BUF = 1 << 20


class _Framer:
    """Incremental NATS command framing for one direction."""

    def __init__(self):
        self._buf = b""
        self._skip = 0  # payload bytes of an oversized message to drop
        self._skip_cmd = None
        self.oversized = 0

    def feed(self, data: bytes):
        """Yield (cmd, args_line, payload|None) tuples."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                out.append((self._skip_cmd[0], self._skip_cmd[1], None))
                continue
            end = self._buf.find(b"\r\n")
            if end < 0:
                if len(self._buf) > _MAX_BUF:
                    self._buf = b""  # unparseable garbage: resync-drop
                break
            line = self._buf[:end]
            head, _, rest = line.partition(b" ")
            cmd = head.decode("latin-1").upper()
            if cmd in _PAYLOAD_CMDS:
                parts = rest.split()
                try:
                    nbytes = int(parts[-1])
                except (ValueError, IndexError):
                    self._buf = self._buf[end + 2:]
                    continue
                total = end + 2 + nbytes + 2
                if nbytes > _MAX_BUF:
                    self.oversized += 1
                    self._skip_cmd = (cmd, rest.decode("utf-8", "replace"))
                    drop = min(total, len(self._buf))
                    self._skip = total - drop
                    self._buf = self._buf[drop:]
                    if self._skip:
                        break
                    out.append((cmd, self._skip_cmd[1], None))
                    continue
                if len(self._buf) < total:
                    break
                payload = self._buf[end + 2:end + 2 + nbytes]
                self._buf = self._buf[total:]
                out.append((cmd, rest.decode("utf-8", "replace"), payload))
                continue
            self._buf = self._buf[end + 2:]
            out.append((cmd, rest.decode("utf-8", "replace"), b""))
        return out


def _body(cmd: str, args: str, payload) -> str:
    """JSON body the reference's nats events carry (options + payload)."""
    fields: dict = {}
    parts = args.split()
    # Size-field count per command: PUB/MSG end with <#bytes>; the
    # headers variants end with <#header-bytes> <#total-bytes> — the
    # reply-to presence test must skip the right number of trailers.
    n_sizes = 2 if cmd in ("HPUB", "HMSG") else 1
    if cmd in ("PUB", "HPUB") and parts:
        fields["subject"] = parts[0]
        if len(parts) > 1 + n_sizes:
            fields["reply_to"] = parts[1]
    elif cmd in ("MSG", "HMSG") and len(parts) >= 2:
        fields["subject"] = parts[0]
        fields["sid"] = parts[1]
        if len(parts) > 2 + n_sizes:
            fields["reply_to"] = parts[2]
    elif cmd == "SUB" and parts:
        fields["subject"] = parts[0]
        fields["sid"] = parts[-1]
        if len(parts) == 3:
            fields["queue_group"] = parts[1]
    elif cmd == "UNSUB" and parts:
        fields["sid"] = parts[0]
    elif cmd in ("CONNECT", "INFO"):
        try:
            fields = json.loads(args)
        except ValueError:
            fields = {"raw": args[:256]}
    if payload is None:
        fields["payload"] = "<oversized>"
    elif payload:
        fields["payload"] = payload[:_MAX_SHOWN_PAYLOAD].decode(
            "utf-8", "replace"
        )
    return json.dumps(fields, sort_keys=True)


class NATSStitcher:
    """Emits one nats event per command; verbose-mode +OK/-ERR attach to
    the preceding client command (nats stitcher semantics)."""

    PENDING_PER_CONN = 64
    #: A held command older than this is assumed unanswered (non-verbose
    #: server) and emitted with no response — pending survives drain()
    #: so a +OK arriving in the NEXT capture batch still pairs.
    PENDING_TTL_NS = 1_000_000_000

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(self, conn_id, data: bytes, is_request: bool,
             ts_ns: Optional[int] = None) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        framer = c.req if is_request else c.resp
        emitted = 0
        # Age out held commands whose verbose-mode reply never came.
        while c.pending and ts - c.pending[0]["time_"] > self.PENDING_TTL_NS:
            self.records.append(c.pending.popleft())
            emitted += 1
        for cmd, args, payload in framer.feed(data):
            if cmd in ("+OK", "-ERR") and not is_request:
                # Attach to the oldest unanswered client command.
                if c.pending:
                    rec = c.pending.popleft()
                    rec["resp"] = "OK" if cmd == "+OK" else f"ERR {args}"
                    rec["latency_ns"] = max(ts - rec["time_"], 0)
                    self.records.append(rec)
                    emitted += 1
                else:
                    self.parse_errors += 1
                continue
            if not cmd or (cmd[0] not in "+-" and not cmd.isalpha()):
                self.parse_errors += 1
                continue
            rec = {
                "time_": ts,
                "cmd": cmd,
                "body": _body(cmd, args, payload),
                "resp": "",
                "latency_ns": 0,
                "service": self.service,
                "pod": self.pod,
            }
            if is_request and cmd == "CONNECT":
                # The CONNECT options say whether the server will answer
                # commands at all (verbose mode); non-verbose connections
                # never hold.
                try:
                    c.verbose = bool(json.loads(args).get("verbose", True))
                except ValueError:
                    pass
                if c.verbose is False:
                    while c.pending:
                        self.records.append(c.pending.popleft())
                        emitted += 1
            if (
                is_request
                and c.verbose is not False
                and cmd in ("CONNECT", "PUB", "HPUB", "SUB", "UNSUB")
            ):
                # May receive a verbose-mode +OK/-ERR; hold briefly.
                if len(c.pending) >= self.PENDING_PER_CONN:
                    self.records.append(c.pending.popleft())
                    emitted += 1
                c.pending.append(rec)
            else:
                self.records.append(rec)
                emitted += 1
        return emitted

    def drain(self) -> list[dict]:
        """Completed records only: in-flight held commands stay pending
        (the tap drains every transfer cycle — a +OK in the next batch
        must still pair; the feed-time TTL bounds how long they wait)."""
        out, self.records = self.records, []
        return out


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        self.pending: deque = deque()
        self.verbose = None  # unknown until CONNECT (None = hold)
