"""Builtin source connectors.

Reference parity:
- ``SeqGenConnector`` (``source_connectors/seq_gen``): deterministic
  synthetic sequences — the reference test strategy's stand-in for real
  eBPF sources (SURVEY.md §4).
- ``ProcessStatsConnector`` (``source_connectors/process_stats``):
  per-process CPU/memory counters scraped from procfs.
- ``NetworkStatsConnector`` (``source_connectors/network_stats``):
  per-interface rx/tx counters from /proc/net/dev.
- ``ProcStatConnector`` (``source_connectors/proc_stat``): system-wide
  CPU utilization split sampled from /proc/stat.
- ``PIDRuntimeConnector`` (``source_connectors/pid_runtime``):
  per-process cumulative CPU runtime gauge.
- ``ProcExitConnector`` (``source_connectors/proc_exit``): process-exit
  events detected by procfs diffing.
- ``StirlingErrorConnector`` (``source_connectors/stirling_error``):
  connector install status + runtime collection errors.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..types.dtypes import DataType
from ..types.relation import Relation
from ..utils.upid import UPID
from .core import SourceConnector
from .schemas import (
    NETWORK_STATS_RELATION,
    PID_RUNTIME_RELATION,
    PROC_EXIT_EVENTS_RELATION,
    PROC_STAT_RELATION,
    STIRLING_ERROR_RELATION,
)

I, F, S, T = DataType.INT64, DataType.FLOAT64, DataType.STRING, DataType.TIME64NS


class SeqGenConnector(SourceConnector):
    """Deterministic sequence generator, one table of counters.

    Reference: ``seq_gen_connector.h`` — linear/modulo/square sequences
    keyed off a monotone counter, used to validate the push path without
    kernel probes.
    """

    name = "seq_gen"
    tables = [
        (
            "sequences",
            Relation(
                [
                    ("time_", T),
                    ("x", I),
                    ("linear", I),
                    ("modulo10", I),
                    ("square", I),
                    ("fibonacci", I),
                ]
            ),
        )
    ]

    def __init__(self, rows_per_transfer: int = 64, **kw):
        super().__init__(**kw)
        self.rows_per_transfer = rows_per_transfer
        self._x = 0
        self._fib = (0, 1)

    def transfer_data(self, ctx, data_tables) -> None:
        n = self.rows_per_transfer
        xs = np.arange(self._x, self._x + n, dtype=np.int64)
        fibs = np.empty(n, dtype=np.int64)
        a, b = self._fib
        for i in range(n):
            fibs[i] = a
            a, b = b, (a + b) % (1 << 62)
        self._fib = (a, b)
        self._x += n
        now = time.time_ns()
        data_tables["sequences"].append(
            {
                "time_": np.full(n, now, dtype=np.int64),
                "x": xs,
                "linear": 2 * xs + 1,
                "modulo10": xs % 10,
                "square": xs * xs,
                "fibonacci": fibs,
            }
        )


class ProcessStatsConnector(SourceConnector):
    """Per-process CPU/memory from /proc (``process_stats`` parity)."""

    name = "process_stats"
    tables = [
        (
            "process_stats",
            Relation(
                [
                    ("time_", T),
                    ("pid", I),
                    ("cmd", S),
                    ("utime_ticks", I),
                    ("stime_ticks", I),
                    ("vsize_bytes", I),
                    ("rss_bytes", I),
                ]
            ),
        )
    ]

    def __init__(self, max_procs: int = 256, **kw):
        super().__init__(**kw)
        self.max_procs = max_procs
        self._page = os.sysconf("SC_PAGE_SIZE")

    def transfer_data(self, ctx, data_tables) -> None:
        rows = {k: [] for k, _ in self.tables[0][1].items()}
        now = time.time_ns()
        count = 0
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            if count >= self.max_procs:
                break
            parsed = _read_pid_stat(pid_s)
            if parsed is None:
                continue  # process exited mid-scan (or truncated read)
            comm, fields = parsed
            rows["time_"].append(now)
            rows["pid"].append(int(pid_s))
            rows["cmd"].append(comm)
            rows["utime_ticks"].append(int(fields[11]))
            rows["stime_ticks"].append(int(fields[12]))
            rows["vsize_bytes"].append(int(fields[20]))
            rows["rss_bytes"].append(int(fields[21]) * self._page)
            count += 1
        data_tables["process_stats"].append(rows)


class NetworkStatsConnector(SourceConnector):
    """Per-interface network counters from /proc/net/dev.

    Reference parity: the network_stats source
    (``src/stirling/source_connectors/network_stats/
    network_stats_connector.h`` — per-pod rx/tx byte/packet/error/drop
    counters from the netns). Without k8s netns access, interfaces stand
    in for pods; the schema is the canonical ``network_stats`` table.
    """

    name = "network_stats"
    tables = [("network_stats", NETWORK_STATS_RELATION)]

    def __init__(self, pod: str = "default/self", **kw):
        super().__init__(**kw)
        self.pod = pod

    def transfer_data(self, ctx, data_tables) -> None:
        try:
            with open("/proc/net/dev") as f:
                lines = f.readlines()[2:]  # skip the two header lines
        except OSError:
            return
        rows = {k: [] for k, _ in self.tables[0][1].items()}
        now = time.time_ns()
        for line in lines:
            if ":" not in line:
                continue
            iface, rest = line.split(":", 1)
            fields = rest.split()
            if len(fields) < 12:
                continue
            rows["time_"].append(now)
            rows["pod_id"].append(iface.strip())
            rows["rx_bytes"].append(int(fields[0]))
            rows["rx_packets"].append(int(fields[1]))
            rows["rx_errors"].append(int(fields[2]))
            rows["rx_drops"].append(int(fields[3]))
            rows["tx_bytes"].append(int(fields[8]))
            rows["tx_packets"].append(int(fields[9]))
            rows["tx_errors"].append(int(fields[10]))
            rows["tx_drops"].append(int(fields[11]))
            rows["pod"].append(self.pod)
        data_tables["network_stats"].append(rows)


def _read_pid_stat(pid_s: str):
    """(comm, post-comm fields) from /proc/<pid>/stat, or None if the
    process exited mid-read. comm may contain spaces/parens, so split
    around the LAST ')'."""
    try:
        with open(f"/proc/{pid_s}/stat") as f:
            stat = f.read()
    except OSError:
        return None
    lpar, rpar = stat.find("("), stat.rfind(")")
    if lpar < 0 or rpar < 0:
        return None
    return stat[lpar + 1 : rpar], stat[rpar + 2 :].split()


class ProcStatConnector(SourceConnector):
    """System-wide CPU utilization from /proc/stat.

    Reference parity: ``proc_stat/proc_stat_connector.h`` kElements —
    {time_, system_percent, user_percent, idle_percent} gauges computed
    by diffing the aggregate ``cpu`` jiffies line between samples (the
    reference's GetProcStat does the same two-sample delta).
    """

    name = "proc_stat"
    tables = [("proc_stat", PROC_STAT_RELATION)]

    def __init__(self, **kw):
        super().__init__(**kw)
        self._prev = None

    @staticmethod
    def _cpu_jiffies():
        with open("/proc/stat") as f:
            parts = f.readline().split()
        if not parts or parts[0] != "cpu" or len(parts) < 5:
            return None
        vals = [int(x) for x in parts[1:]]
        user = vals[0] + vals[1]  # user + nice
        system = vals[2]
        idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
        # guest/guest_nice (fields 9-10) are already folded into
        # user/nice by the kernel — summing them would double-count.
        return user, system, idle, sum(vals[:8])

    def transfer_data(self, ctx, data_tables) -> None:
        try:
            cur = self._cpu_jiffies()
        except OSError:
            return
        if cur is None:
            return
        prev, self._prev = self._prev, cur
        if prev is None:
            return  # percentages need a two-sample delta
        total = cur[3] - prev[3]
        if total <= 0:
            return
        data_tables["proc_stat"].append(
            {
                "time_": np.array([time.time_ns()], dtype=np.int64),
                "system_percent": np.array([100.0 * (cur[1] - prev[1]) / total]),
                "user_percent": np.array([100.0 * (cur[0] - prev[0]) / total]),
                "idle_percent": np.array([100.0 * (cur[2] - prev[2]) / total]),
            }
        )


class PIDRuntimeConnector(SourceConnector):
    """Per-process cumulative CPU runtime gauge.

    Reference parity: ``pid_runtime/pid_runtime_connector.h`` kTable
    ("bcc_pid_cpu_usage": {time_, pid, runtime_ns, cmd}). The reference
    sums sched-switch deltas in a BPF map; without kernel probes the
    same cumulative gauge comes from /proc/<pid>/stat utime+stime
    (ticks -> ns).
    """

    name = "pid_runtime"
    tables = [("bcc_pid_cpu_usage", PID_RUNTIME_RELATION)]

    def __init__(self, max_procs: int = 256, **kw):
        super().__init__(**kw)
        self.max_procs = max_procs
        self._ns_per_tick = 1_000_000_000 // os.sysconf("SC_CLK_TCK")

    def transfer_data(self, ctx, data_tables) -> None:
        rows = {k: [] for k, _ in PID_RUNTIME_RELATION.items()}
        now = time.time_ns()
        count = 0
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            if count >= self.max_procs:
                break
            parsed = _read_pid_stat(pid_s)
            if parsed is None:
                continue
            comm, fields = parsed
            rows["time_"].append(now)
            rows["pid"].append(int(pid_s))
            # utime+stime are post-comm fields 11/12 (overall 14/15).
            rows["runtime_ns"].append(
                (int(fields[11]) + int(fields[12])) * self._ns_per_tick
            )
            rows["cmd"].append(comm)
            count += 1
        data_tables["bcc_pid_cpu_usage"].append(rows)


class ProcExitConnector(SourceConnector):
    """Process-exit events, procfs edition.

    Reference parity: ``proc_exit/proc_exit_events_table.h``
    kProcExitEventsTable ({time_, upid, exit_code, signal, comm}). The
    reference hooks the sched_process_exit tracepoint; without kernel
    probes (SCOPING.md) an exit is a (pid, start_ticks) incarnation that
    vanishes between two /proc scans. exit_code/signal are tracepoint-
    only — procfs does not expose another process's exit status — so
    both report -1 (unknown).
    """

    name = "proc_exit"
    tables = [("proc_exit_events", PROC_EXIT_EVENTS_RELATION)]

    def __init__(self, asid: int = 1, **kw):
        super().__init__(**kw)
        self.asid = asid
        self._seen: dict = {}  # pid -> (start_ticks, comm)

    def _scan(self) -> dict:
        out = {}
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            parsed = _read_pid_stat(pid_s)
            if parsed is None:
                continue
            comm, fields = parsed
            # starttime is post-comm field 19 (overall field 22).
            out[int(pid_s)] = (int(fields[19]), comm)
        return out

    def transfer_data(self, ctx, data_tables) -> None:
        cur = self._scan()
        prev, self._seen = self._seen, cur
        if not prev:
            return  # first scan only establishes the baseline
        now = time.time_ns()
        hi, lo, rows = [], [], {"time_": [], "exit_code": [], "signal": [], "comm": []}
        for pid, (start, comm) in prev.items():
            if cur.get(pid, (None, None))[0] == start:
                continue  # same incarnation still running
            u = UPID(self.asid, pid, start)
            hi.append(u.hi)
            lo.append(u.lo)
            rows["time_"].append(now)
            rows["exit_code"].append(-1)
            rows["signal"].append(-1)
            rows["comm"].append(comm)
        if not rows["time_"]:
            return
        rows["upid"] = np.stack(
            [np.array(hi, np.uint64), np.array(lo, np.uint64)], axis=1
        )
        data_tables["proc_exit_events"].append(rows)


#: stirling_error status codes (reference px::statuspb::Code subset).
ERROR_STATUS_OK = 0
ERROR_STATUS_FAILED = 2  # UNKNOWN: generic runtime collection failure


class StirlingErrorConnector(SourceConnector):
    """Self-observability: connector install status + runtime errors.

    Reference parity: ``stirling_error/stirling_error_table.h``
    kStirlingErrorElements ({time_, upid, source_connector, status,
    error}). ``ctx`` is the Collector: each registered connector gets
    one status row when first observed (0 = OK), and every entry
    appended to ``Collector.errors`` since the previous transfer
    becomes a status-2 row carrying the message.
    """

    name = "stirling_error"
    tables = [("stirling_error", STIRLING_ERROR_RELATION)]

    def __init__(self, asid: int = 1, **kw):
        super().__init__(**kw)
        self.asid = asid
        self._reported: set = set()
        self._err_cursor = 0

    def transfer_data(self, ctx, data_tables) -> None:
        rows = {"time_": [], "source_connector": [], "status": [], "error": []}
        now = time.time_ns()
        for c in list(getattr(ctx, "_connectors", [])):
            if c.name in self._reported:
                continue
            self._reported.add(c.name)
            rows["time_"].append(now)
            rows["source_connector"].append(c.name)
            rows["status"].append(ERROR_STATUS_OK)
            rows["error"].append("")
        errors = getattr(ctx, "errors", [])
        fresh, self._err_cursor = errors[self._err_cursor :], len(errors)
        for src, msg in fresh:
            rows["time_"].append(now)
            rows["source_connector"].append(src)
            rows["status"].append(ERROR_STATUS_FAILED)
            rows["error"].append(msg)
        n = len(rows["time_"])
        if n == 0:
            return
        u = UPID(self.asid, os.getpid(), 0)
        rows["upid"] = np.stack(
            [np.full(n, u.hi, np.uint64), np.full(n, u.lo, np.uint64)], axis=1
        )
        data_tables["stirling_error"].append(rows)
