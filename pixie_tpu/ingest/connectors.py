"""Builtin source connectors.

Reference parity:
- ``SeqGenConnector`` (``source_connectors/seq_gen``): deterministic
  synthetic sequences — the reference test strategy's stand-in for real
  eBPF sources (SURVEY.md §4).
- ``ProcessStatsConnector`` (``source_connectors/process_stats``):
  per-process CPU/memory counters scraped from procfs.
- ``NetworkStatsConnector`` (``source_connectors/network_stats``):
  per-interface rx/tx counters from /proc/net/dev.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..types.dtypes import DataType
from ..types.relation import Relation
from .core import SourceConnector
from .schemas import NETWORK_STATS_RELATION

I, F, S, T = DataType.INT64, DataType.FLOAT64, DataType.STRING, DataType.TIME64NS


class SeqGenConnector(SourceConnector):
    """Deterministic sequence generator, one table of counters.

    Reference: ``seq_gen_connector.h`` — linear/modulo/square sequences
    keyed off a monotone counter, used to validate the push path without
    kernel probes.
    """

    name = "seq_gen"
    tables = [
        (
            "sequences",
            Relation(
                [
                    ("time_", T),
                    ("x", I),
                    ("linear", I),
                    ("modulo10", I),
                    ("square", I),
                    ("fibonacci", I),
                ]
            ),
        )
    ]

    def __init__(self, rows_per_transfer: int = 64, **kw):
        super().__init__(**kw)
        self.rows_per_transfer = rows_per_transfer
        self._x = 0
        self._fib = (0, 1)

    def transfer_data(self, ctx, data_tables) -> None:
        n = self.rows_per_transfer
        xs = np.arange(self._x, self._x + n, dtype=np.int64)
        fibs = np.empty(n, dtype=np.int64)
        a, b = self._fib
        for i in range(n):
            fibs[i] = a
            a, b = b, (a + b) % (1 << 62)
        self._fib = (a, b)
        self._x += n
        now = time.time_ns()
        data_tables["sequences"].append(
            {
                "time_": np.full(n, now, dtype=np.int64),
                "x": xs,
                "linear": 2 * xs + 1,
                "modulo10": xs % 10,
                "square": xs * xs,
                "fibonacci": fibs,
            }
        )


class ProcessStatsConnector(SourceConnector):
    """Per-process CPU/memory from /proc (``process_stats`` parity)."""

    name = "process_stats"
    tables = [
        (
            "process_stats",
            Relation(
                [
                    ("time_", T),
                    ("pid", I),
                    ("cmd", S),
                    ("utime_ticks", I),
                    ("stime_ticks", I),
                    ("vsize_bytes", I),
                    ("rss_bytes", I),
                ]
            ),
        )
    ]

    def __init__(self, max_procs: int = 256, **kw):
        super().__init__(**kw)
        self.max_procs = max_procs
        self._page = os.sysconf("SC_PAGE_SIZE")

    def transfer_data(self, ctx, data_tables) -> None:
        rows = {k: [] for k, _ in self.tables[0][1].items()}
        now = time.time_ns()
        count = 0
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            if count >= self.max_procs:
                break
            try:
                with open(f"/proc/{pid_s}/stat") as f:
                    stat = f.read()
            except OSError:
                continue  # process exited mid-scan
            # comm may contain spaces/parens: split around the last ')'.
            lpar, rpar = stat.find("("), stat.rfind(")")
            comm = stat[lpar + 1 : rpar]
            fields = stat[rpar + 2 :].split()
            rows["time_"].append(now)
            rows["pid"].append(int(pid_s))
            rows["cmd"].append(comm)
            rows["utime_ticks"].append(int(fields[11]))
            rows["stime_ticks"].append(int(fields[12]))
            rows["vsize_bytes"].append(int(fields[20]))
            rows["rss_bytes"].append(int(fields[21]) * self._page)
            count += 1
        data_tables["process_stats"].append(rows)


class NetworkStatsConnector(SourceConnector):
    """Per-interface network counters from /proc/net/dev.

    Reference parity: the network_stats source
    (``src/stirling/source_connectors/network_stats/
    network_stats_connector.h`` — per-pod rx/tx byte/packet/error/drop
    counters from the netns). Without k8s netns access, interfaces stand
    in for pods; the schema is the canonical ``network_stats`` table.
    """

    name = "network_stats"
    tables = [("network_stats", NETWORK_STATS_RELATION)]

    def __init__(self, pod: str = "default/self", **kw):
        super().__init__(**kw)
        self.pod = pod

    def transfer_data(self, ctx, data_tables) -> None:
        try:
            with open("/proc/net/dev") as f:
                lines = f.readlines()[2:]  # skip the two header lines
        except OSError:
            return
        rows = {k: [] for k, _ in self.tables[0][1].items()}
        now = time.time_ns()
        for line in lines:
            if ":" not in line:
                continue
            iface, rest = line.split(":", 1)
            fields = rest.split()
            if len(fields) < 12:
                continue
            rows["time_"].append(now)
            rows["pod_id"].append(iface.strip())
            rows["rx_bytes"].append(int(fields[0]))
            rows["rx_packets"].append(int(fields[1]))
            rows["rx_errors"].append(int(fields[2]))
            rows["rx_drops"].append(int(fields[3]))
            rows["tx_bytes"].append(int(fields[8]))
            rows["tx_packets"].append(int(fields[9]))
            rows["tx_errors"].append(int(fields[10]))
            rows["tx_drops"].append(int(fields[11]))
            rows["pod"].append(self.pod)
        data_tables["network_stats"].append(rows)
