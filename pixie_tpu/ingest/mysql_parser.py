"""MySQL wire-protocol parser + stitcher: captured bytes -> mysql_events.

Reference parity: the socket tracer's MySQL protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/mysql/parse.cc`` — packet framing + command classification —
and ``stitcher.cc`` — request/response pairing with resultset
consumption). Like the HTTP parser here, capture arrives as byte chunks
from any tap (proxy, pcap export, fixtures) and flows through an
incremental per-connection state machine; partial packets survive
across ``feed`` calls.

Protocol essentials (MySQL client/server protocol, public spec):
- Every packet: 3-byte little-endian payload length + 1-byte sequence
  id, then the payload.
- A client COMMAND packet has sequence id 0; its first payload byte is
  the command code (COM_QUERY=0x03 carries SQL text). Client packets
  with seq > 0 belong to the login/auth handshake and are skipped.
- A response begins with an OK (0x00), ERR (0xff: error code u16 +
  '#' + 5-byte sqlstate + message) or EOF (0xfe, payload < 9 bytes)
  packet, or a column-count packet opening a resultset; a resultset
  runs column definitions then rows, each section closed by EOF (or a
  terminating OK with the DEPRECATE_EOF capability).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

from .conn_table import ConnectionTable

# Command codes (protocol constants; mysql/types.h Command enum).
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
MAX_COMMAND = 0x1F

#: Commands whose body is a single human-readable string.
_STRING_BODY = {COM_QUERY, COM_STMT_PREPARE, COM_INIT_DB, COM_FIELD_LIST}
#: Commands the server never answers (stitcher.cc kNoResponse set).
_NO_RESPONSE = {COM_QUIT, COM_STMT_SEND_LONG_DATA, COM_STMT_CLOSE}

# RespStatus enum values (mysql/types.h RespStatus ordering).
RESP_UNKNOWN = 0
RESP_NONE = 1
RESP_OK = 2
RESP_ERR = 3


class _Framer:
    """Incremental MySQL packet framing for one direction."""

    MAX_BUF = 1 << 20

    def __init__(self):
        self._buf = b""
        self._skip = 0  # bytes of an oversized packet still to discard
        self._skip_head = None  # its first payload byte, when seen
        self.oversized = 0

    def feed(self, data: bytes):
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                # The oversized packet's place in the stream is marked so
                # the stitcher keeps request/response pairing aligned.
                out.append((None, self._skip_head))
                continue
            if len(self._buf) < 4:
                break
            plen = int.from_bytes(self._buf[:3], "little")
            if 4 + plen > self.MAX_BUF:
                # Protocol allows 16MB packets; discard incrementally —
                # truncating the buffer mid-packet desyncs framing
                # forever. The marker keeps pairing aligned and carries
                # the first payload byte (the command/response head).
                self.oversized += 1
                self._skip_head = self._buf[4] if len(self._buf) > 4 else None
                drop = min(4 + plen, len(self._buf))
                self._skip = 4 + plen - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                out.append((None, self._skip_head))
                continue
            if len(self._buf) < 4 + plen:
                break
            out.append((self._buf[3], self._buf[4:4 + plen]))
            self._buf = self._buf[4 + plen:]
        return out


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        self.pending: deque = deque()  # (cmd, body, ts)
        # Resultset consumption state: None = expecting a response head;
        # otherwise {"eofs", "rows", "cols", "defs_seen", "mode"}.
        self.rs = None
        # Prepare-OK definition packets still to consume (None = not in
        # a prepare followup; 0 = defs done, trailing EOF may remain).
        self.prep_skip = None


class MySQLStitcher:
    """Pairs command packets with their responses; emits mysql_events
    records (``stitcher.cc`` ProcessMySQLPackets)."""

    PENDING_PER_CONN = 256

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for seq, payload in c.req.feed(data):
                if seq is None:
                    # Oversized command packet (e.g. a multi-MB INSERT):
                    # body lost, but the slot must pair with its response.
                    self.parse_errors += 1
                    head = payload
                    cmd = head if head is not None and head <= MAX_COMMAND else COM_QUERY
                    if cmd not in _NO_RESPONSE:
                        c.pending.append((cmd, "<oversized>", ts))
                    continue
                if seq != 0 or not payload:
                    continue  # login/auth handshake continuation
                cmd = payload[0]
                if cmd > MAX_COMMAND:
                    self.parse_errors += 1
                    continue
                body = (
                    payload[1:].decode("utf-8", "replace")
                    if cmd in _STRING_BODY
                    else ""
                )
                if cmd in _NO_RESPONSE:
                    self._emit(cmd, body, ts, ts, RESP_NONE, "")
                    emitted += 1
                    continue
                if len(c.pending) >= self.PENDING_PER_CONN:
                    # Positional pairing: overflow kills the tracker (the
                    # same policy as the HTTP stitcher).
                    self.parse_errors += len(c.pending) + 1
                    self._conns.kill(conn_id)
                    return emitted
                c.pending.append((cmd, body, ts))
            return emitted
        for seq, payload in c.resp.feed(data):
            if seq is None:
                # Oversized response packet: the framer's marker carries
                # the head byte (or None) as ``payload`` — an int, which
                # the state machine must never see. Normalize here: an
                # oversized ERR at head position keeps its classification
                # (huge error messages exist); everything else flows
                # through the payload-None sentinel the handlers treat as
                # "one packet of unknown body" (a row inside a resultset,
                # a definition inside a prepare followup, unknown at head).
                self.parse_errors += 1
                if (
                    c.rs is None and c.prep_skip is None and c.pending
                    and payload == 0xFF
                ):
                    emitted += self._finish(c, ts, RESP_ERR, "<oversized>")
                else:
                    emitted += self._response_packet(c, None, ts)
                continue
            emitted += self._response_packet(c, payload, ts)
        return emitted

    # -- response state machine ----------------------------------------------
    def _response_packet(self, c: _Conn, payload, ts: int) -> int:
        if not c.pending and c.prep_skip is None:
            return 0  # server greeting / unsolicited: connection setup
        if c.prep_skip is not None:
            return self._prepare_followup(c, payload, ts)
        if c.rs is not None:
            return self._resultset_packet(c, payload, ts)
        if payload is None:  # oversized packet where a head was expected
            return self._finish(c, ts, RESP_UNKNOWN, "<oversized>")
        head = payload[0] if payload else -1
        cmd, _body, _rts = c.pending[0]
        if head == 0xFF:
            code = int.from_bytes(payload[1:3], "little") if len(payload) >= 3 else 0
            msg = payload[9:].decode("utf-8", "replace") if len(payload) > 9 else ""
            return self._finish(c, ts, RESP_ERR, f"({code}) {msg}".strip())
        if head == 0x00:
            if cmd == COM_STMT_PREPARE and len(payload) >= 9:
                # Prepare-OK carries num_columns/num_params (u16 each);
                # their definition packets follow and must be consumed or
                # they would be misread as the NEXT command's response.
                n_cols = int.from_bytes(payload[5:7], "little")
                n_params = int.from_bytes(payload[7:9], "little")
                n = self._finish(c, ts, RESP_OK, "")
                if n_cols or n_params:
                    c.prep_skip = n_cols + n_params
                return n
            return self._finish(c, ts, RESP_OK, "")
        if head == 0xFE and len(payload) < 9:
            return self._finish(c, ts, RESP_OK, "")
        if cmd == COM_STMT_PREPARE:
            # Anything else is a protocol surprise — classify unknown.
            return self._finish(c, ts, RESP_UNKNOWN, "")
        # Column-count packet: a resultset begins. The framing mode
        # (classic EOFs vs DEPRECATE_EOF) reveals itself after the
        # definitions: classic sends an EOF there.
        ncols = payload[0] if payload else 0
        c.rs = {"cols": int(ncols), "defs_seen": 0, "eofs": 0, "rows": 0,
                "mode": None}
        return 0

    def _prepare_followup(self, c: _Conn, payload, ts: int) -> int:
        """Consume a Prepare-OK's parameter/column definition packets
        (EOF separators included, in classic framing)."""
        if payload is not None and payload[:1] == b"\xfe" and len(payload) < 9:
            if c.prep_skip <= 0:
                c.prep_skip = None  # trailing EOF closed the last section
            return 0
        if c.prep_skip is not None and c.prep_skip > 0:
            c.prep_skip -= 1
            if c.prep_skip == 0:
                # Definitions done; a trailing EOF may still follow (and
                # is absorbed above); anything else re-enters normally.
                c.prep_skip = 0
            return 0
        # prep_skip exhausted and a non-EOF packet arrived: this packet
        # belongs to the next response — reprocess it.
        c.prep_skip = None
        return self._response_packet(c, payload, ts)

    def _resultset_packet(self, c: _Conn, payload, ts: int) -> int:
        rs = c.rs
        if payload is None:  # oversized packet: count as one row/def
            if rs["defs_seen"] < rs["cols"]:
                rs["defs_seen"] += 1
            else:
                rs["rows"] += 1
            return 0
        head = payload[0] if payload else -1
        if head == 0xFF:
            code = int.from_bytes(payload[1:3], "little") if len(payload) >= 3 else 0
            msg = payload[9:].decode("utf-8", "replace") if len(payload) > 9 else ""
            return self._finish(c, ts, RESP_ERR, f"({code}) {msg}".strip())
        in_defs = rs["defs_seen"] < rs["cols"]
        if head == 0xFE and len(payload) < 9:
            # An EOF right after the definitions marks classic framing
            # (defs EOF + rows EOF); the second one ends the resultset.
            if rs["mode"] is None:
                rs["mode"] = "classic"
            rs["eofs"] += 1
            if rs["eofs"] >= 2 or rs["mode"] == "deprecate_eof":
                return self._finish(
                    c, ts, RESP_OK, f"Resultset rows={rs['rows']}"
                )
            return 0
        if (
            head == 0xFE and not in_defs and len(payload) < 32
            and rs["mode"] != "classic"
        ):
            # DEPRECATE_EOF (MySQL >= 5.7.5 default): rows end with an
            # OK packet whose header byte is 0xFE. Distinguished from a
            # data row by its short length (heuristic — the capability
            # flags live in the handshake, which taps often miss);
            # classic mode never takes this branch (its explicit final
            # EOF is authoritative).
            return self._finish(
                c, ts, RESP_OK, f"Resultset rows={rs['rows']}"
            )
        if in_defs:
            rs["defs_seen"] += 1
            if rs["defs_seen"] == rs["cols"]:
                # Next packet decides the framing mode: EOF = classic,
                # a row = DEPRECATE_EOF.
                pass
        else:
            if rs["mode"] is None:
                rs["mode"] = "deprecate_eof"
            rs["rows"] += 1
        return 0

    def _finish(self, c: _Conn, ts: int, status: int, resp_body: str) -> int:
        c.rs = None
        if not c.pending:
            return 0
        cmd, body, req_ts = c.pending.popleft()
        self._emit(cmd, body, req_ts, ts, status, resp_body)
        return 1

    def _emit(self, cmd, body, req_ts, resp_ts, status, resp_body):
        self.records.append({
            "time_": req_ts,
            "req_cmd": int(cmd),
            "query_str": body,
            "resp_status": int(status),
            "resp_body": resp_body,
            "latency_ns": max(resp_ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
