"""MySQL wire-protocol parser + stitcher: captured bytes -> mysql_events.

Reference parity: the socket tracer's MySQL protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/mysql/parse.cc`` — packet framing + command classification —
and ``stitcher.cc`` — request/response pairing with resultset
consumption). Like the HTTP parser here, capture arrives as byte chunks
from any tap (proxy, pcap export, fixtures) and flows through an
incremental per-connection state machine; partial packets survive
across ``feed`` calls.

Protocol essentials (MySQL client/server protocol, public spec):
- Every packet: 3-byte little-endian payload length + 1-byte sequence
  id, then the payload.
- A client COMMAND packet has sequence id 0; its first payload byte is
  the command code (COM_QUERY=0x03 carries SQL text). Client packets
  with seq > 0 belong to the login/auth handshake and are skipped.
- A response begins with an OK (0x00), ERR (0xff: error code u16 +
  '#' + 5-byte sqlstate + message) or EOF (0xfe, payload < 9 bytes)
  packet, or a column-count packet opening a resultset; a resultset
  runs column definitions then rows, each section closed by EOF (or a
  terminating OK with the DEPRECATE_EOF capability).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

# Command codes (protocol constants; mysql/types.h Command enum).
COM_QUIT = 0x01
COM_INIT_DB = 0x02
COM_QUERY = 0x03
COM_FIELD_LIST = 0x04
COM_PING = 0x0E
COM_STMT_PREPARE = 0x16
COM_STMT_EXECUTE = 0x17
COM_STMT_SEND_LONG_DATA = 0x18
COM_STMT_CLOSE = 0x19
MAX_COMMAND = 0x1F

#: Commands whose body is a single human-readable string.
_STRING_BODY = {COM_QUERY, COM_STMT_PREPARE, COM_INIT_DB, COM_FIELD_LIST}
#: Commands the server never answers (stitcher.cc kNoResponse set).
_NO_RESPONSE = {COM_QUIT, COM_STMT_SEND_LONG_DATA, COM_STMT_CLOSE}

# RespStatus enum values (mysql/types.h RespStatus ordering).
RESP_UNKNOWN = 0
RESP_NONE = 1
RESP_OK = 2
RESP_ERR = 3


class _Framer:
    """Incremental MySQL packet framing for one direction."""

    MAX_BUF = 1 << 20

    def __init__(self):
        self._buf = b""

    def feed(self, data: bytes):
        self._buf += data
        if len(self._buf) > self.MAX_BUF:
            self._buf = self._buf[-self.MAX_BUF:]
        out = []
        while len(self._buf) >= 4:
            plen = int.from_bytes(self._buf[:3], "little")
            if len(self._buf) < 4 + plen:
                break
            out.append((self._buf[3], self._buf[4:4 + plen]))
            self._buf = self._buf[4 + plen:]
        return out


class _Conn:
    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        self.pending: deque = deque()  # (cmd, body, ts)
        # Resultset consumption state: None = expecting a response head;
        # otherwise {"eofs": n, "rows": n, "cols": n, "defs_seen": n}.
        self.rs = None
        self.last_ts = 0


class MySQLStitcher:
    """Pairs command packets with their responses; emits mysql_events
    records (``stitcher.cc`` ProcessMySQLPackets)."""

    CONN_IDLE_TTL_NS = 300 * 1_000_000_000
    CONN_MAX = 4096
    PENDING_PER_CONN = 256

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns: dict = {}
        self.records: list[dict] = []
        self.parse_errors = 0

    def _expire(self, now_ns: int) -> None:
        cutoff = now_ns - self.CONN_IDLE_TTL_NS
        if len(self._conns) > 64:
            self._conns = {
                cid: c for cid, c in self._conns.items()
                if c.last_ts >= cutoff
            }
        while len(self._conns) >= self.CONN_MAX:
            lru = min(self._conns, key=lambda cid: self._conns[cid].last_ts)
            self._conns.pop(lru)

    def _conn(self, conn_id, now_ns: int) -> _Conn:
        c = self._conns.get(conn_id)
        if c is None:
            self._expire(now_ns)
            c = _Conn()
            self._conns[conn_id] = c
        c.last_ts = now_ns
        return c

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conn(conn_id, ts)
        emitted = 0
        if is_request:
            for seq, payload in c.req.feed(data):
                if seq != 0 or not payload:
                    continue  # login/auth handshake continuation
                cmd = payload[0]
                if cmd > MAX_COMMAND:
                    self.parse_errors += 1
                    continue
                body = (
                    payload[1:].decode("utf-8", "replace")
                    if cmd in _STRING_BODY
                    else ""
                )
                if cmd in _NO_RESPONSE:
                    self._emit(cmd, body, ts, ts, RESP_NONE, "")
                    emitted += 1
                    continue
                if len(c.pending) >= self.PENDING_PER_CONN:
                    # Positional pairing: overflow kills the tracker (the
                    # same policy as the HTTP stitcher).
                    self.parse_errors += len(c.pending) + 1
                    self._conns.pop(conn_id, None)
                    return emitted
                c.pending.append((cmd, body, ts))
            return emitted
        for _seq, payload in c.resp.feed(data):
            emitted += self._response_packet(c, payload, ts)
        return emitted

    # -- response state machine ----------------------------------------------
    def _response_packet(self, c: _Conn, payload: bytes, ts: int) -> int:
        if not c.pending:
            return 0  # server greeting / unsolicited: connection setup
        if c.rs is not None:
            return self._resultset_packet(c, payload, ts)
        head = payload[0] if payload else -1
        cmd, _body, _rts = c.pending[0]
        if head == 0xFF:
            code = int.from_bytes(payload[1:3], "little") if len(payload) >= 3 else 0
            msg = payload[9:].decode("utf-8", "replace") if len(payload) > 9 else ""
            return self._finish(c, ts, RESP_ERR, f"({code}) {msg}".strip())
        if head == 0x00:
            return self._finish(c, ts, RESP_OK, "")
        if head == 0xFE and len(payload) < 9:
            return self._finish(c, ts, RESP_OK, "")
        if cmd == COM_STMT_PREPARE:
            # Prepare-OK: header 0x00 handled above; anything else is a
            # protocol surprise — classify unknown and move on.
            return self._finish(c, ts, RESP_UNKNOWN, "")
        # Column-count packet: a resultset begins.
        ncols = payload[0] if payload else 0
        c.rs = {"cols": int(ncols), "defs_seen": 0, "eofs": 0, "rows": 0}
        return 0

    def _resultset_packet(self, c: _Conn, payload: bytes, ts: int) -> int:
        head = payload[0] if payload else -1
        rs = c.rs
        if head == 0xFF:
            code = int.from_bytes(payload[1:3], "little") if len(payload) >= 3 else 0
            msg = payload[9:].decode("utf-8", "replace") if len(payload) > 9 else ""
            return self._finish(c, ts, RESP_ERR, f"({code}) {msg}".strip())
        if head == 0xFE and len(payload) < 9:
            # Classic framing: one EOF closes the column definitions, a
            # second closes the rows. (DEPRECATE_EOF's OK terminator is
            # indistinguishable from a row starting 0x00 without the
            # handshake's capability flags; classic framing is what taps
            # record.)
            rs["eofs"] += 1
            if rs["eofs"] >= 2:
                return self._finish(
                    c, ts, RESP_OK, f"Resultset rows={rs['rows']}"
                )
            return 0
        if rs["defs_seen"] < rs["cols"]:
            rs["defs_seen"] += 1
        else:
            rs["rows"] += 1
        return 0

    def _finish(self, c: _Conn, ts: int, status: int, resp_body: str) -> int:
        c.rs = None
        if not c.pending:
            return 0
        cmd, body, req_ts = c.pending.popleft()
        self._emit(cmd, body, req_ts, ts, status, resp_body)
        return 1

    def _emit(self, cmd, body, req_ts, resp_ts, status, resp_body):
        self.records.append({
            "time_": req_ts,
            "req_cmd": int(cmd),
            "query_str": body,
            "resp_status": int(status),
            "resp_body": resp_body,
            "latency_ns": max(resp_ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
