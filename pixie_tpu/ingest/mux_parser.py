"""Mux (Twitter Finagle) wire-protocol parser: captured bytes ->
mux_events.

Reference parity: the socket tracer's mux protocol
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/mux/`` and ``mux_table.h`` kMuxElements: req_type + latency).

Protocol essentials (Mux protocol, public Finagle spec):
- Every message: u32 big-endian length, then a 1-byte SIGNED type and a
  3-byte tag; the remaining (length - 4) bytes are the body.
- Transmit types are positive (Tdispatch=2, Treq=1, Tping=65,
  Tdiscarded=66, Tlease=67, Tinit=68, ...); the matching reply is the
  NEGATED type (Rdispatch=-2, Rping=-65, ...). Requests pair with
  replies BY TAG (concurrent dispatches multiplex one connection).
- Tag 0 is reserved; Tlease/Tdiscarded are one-way.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

from .conn_table import ConnectionTable

#: type value -> name (mux spec; mux/types.h Type enum).
TYPES = {
    1: "Treq", 2: "Tdispatch", 64: "Tdrain", 65: "Tping", 66: "Tdiscarded",
    67: "Tlease", 68: "Tinit", 127: "Rerr",
}
#: Special encodings outside the +T/-R pairing: old-style Rerr (127),
#: modern Rerr (-128), and old-style Tdiscarded (-62 — a TRANSMIT type
#: despite the sign).
_SPECIAL = {127, -128, -62}
_ONE_WAY = {66, 67}  # Tdiscarded / Tlease have no reply


class _Framer:
    MAX_BODY = 4 << 20

    def __init__(self):
        self._buf = b""
        self._skip = 0
        self._skip_hdr = None
        self.oversized = 0

    def feed(self, data: bytes):
        """Yield (type, tag) headers (bodies are not needed for the
        event table; oversized bodies skip incrementally)."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                out.append(self._skip_hdr)
                continue
            if len(self._buf) < 8:
                break
            ln = int.from_bytes(self._buf[:4], "big")
            if ln < 4:
                self._buf = self._buf[1:]  # garbage: resync byte-wise
                continue
            typ = int.from_bytes(self._buf[4:5], "big", signed=True)
            tag = int.from_bytes(self._buf[5:8], "big")
            if abs(typ) not in TYPES and typ not in _SPECIAL:
                self._buf = self._buf[1:]
                continue
            if ln > self.MAX_BODY:
                self.oversized += 1
                self._skip_hdr = (typ, tag)
                drop = min(4 + ln, len(self._buf))
                self._skip = 4 + ln - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                out.append(self._skip_hdr)
                continue
            if len(self._buf) < 4 + ln:
                break
            out.append((typ, tag))
            self._buf = self._buf[4 + ln:]
        return out


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        self.pending: OrderedDict = OrderedDict()  # tag -> (type, ts)


class MuxStitcher:
    """Pairs Tmsg/Rmsg by tag; emits mux_events records."""

    PENDING_PER_CONN = 512

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(self, conn_id, data: bytes, is_request: bool,
             ts_ns: Optional[int] = None) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for typ, tag in c.req.feed(data):
                if typ == -62:
                    typ = 66  # old-style Tdiscarded: one-way transmit
                if typ <= 0:
                    self.parse_errors += 1
                    continue
                if typ in _ONE_WAY:
                    self._emit(typ, ts, ts)
                    emitted += 1
                    continue
                while len(c.pending) >= self.PENDING_PER_CONN:
                    c.pending.popitem(last=False)
                    self.parse_errors += 1
                c.pending[tag] = (typ, ts)
            return emitted
        for typ, tag in c.resp.feed(data):
            # Replies are negated transmit types; Rerr arrives as -128
            # (modern) or 127 (old-style) and still answers its tag.
            if typ >= 0 and typ != 127:
                self.parse_errors += 1
                continue
            req = c.pending.pop(tag, None)
            if req is None:
                self.parse_errors += 1
                continue
            req_type, req_ts = req
            self._emit(req_type, req_ts, ts)
            emitted += 1
        return emitted

    def _emit(self, req_type, req_ts, resp_ts):
        self.records.append({
            "time_": req_ts,
            "req_type": int(req_type),
            "latency_ns": max(resp_ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
