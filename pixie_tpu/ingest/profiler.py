"""Self-sampling perf profiler connector with query/tenant attribution.

Reference parity: the continuous profiler
(``/root/reference/src/stirling/source_connectors/perf_profiler/
perf_profiler_connector.h`` — eBPF stack sampling folded into the
``stack_traces.beta`` table). Without eBPF in scope (SURVEY.md §7 stage
7), the TPU-side analog samples THIS process's Python threads via
``sys._current_frames`` at the sampling period and folds identical
stacks into (stack_trace, count) rows — the same ``;``-joined
flamegraph-folded encoding the reference emits, queryable by the shipped
``px/perf_flamegraph`` script.

Profiling tier (PR 17): each sample also reads the thread attribution
registry (``exec/threadmap.py``) so folded stacks land in the
``__stacks__`` telemetry ring WITH {qid, script_hash, tenant, phase}
columns — queryable via ``px/query_cpu`` / ``px/tenant_cpu`` — and
per-tenant CPU burn is counted in ``pixie_cpu_samples_total{tenant}``.
Active connectors register in a module-level set so the owning agent
can ship cumulative folded-stack summaries in heartbeats
(:func:`profile_summary`), which ``AgentTracker`` merges cluster-wide
for ``/debug/pprof`` and ``/debug/flamez``.

The sample path is a pxlint hot region: NO locks on the per-thread
read (threadmap entries are immutable dicts read GIL-atomically), no
device syncs — a 100Hz sampler that blocks is a profiler-shaped
outage.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
import time

from ..exec import threadmap
from ..utils.upid import UPID
from .core import SourceConnector
from .schemas import STACK_TRACES_RELATION, STACKS_RELATION

#: Root marker frame appended when a stack exceeded the fold depth.
#: Without it, a 70-deep stack truncated to 64 frames folds to the SAME
#: key as a genuinely-64-deep stack with that prefix — two different
#: code paths aliased into one flame box.
TRUNCATED_MARKER = "...[truncated]"

#: Active connectors (registered in init(), removed in stop()) — the
#: per-process roster :func:`profile_summary` merges for heartbeats.
_ACTIVE: list["PerfProfilerConnector"] = []
_ACTIVE_LOCK = threading.Lock()


def _fold_stack(frame, max_depth: int = 64) -> str:
    """Flamegraph-folded stack string: outermost;...;innermost."""
    parts: list[str] = []
    while frame is not None and len(parts) < max_depth:
        code = frame.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    if frame is not None:
        # Deeper than max_depth: mark the truncation at the ROOT (this
        # list is innermost-first; reversal puts the marker first).
        parts.append(TRUNCATED_MARKER)
    return ";".join(reversed(parts))


def stack_id(folded: str) -> int:
    """Stable 63-bit content hash of a folded stack: bounded memory on
    long-lived PEMs (no per-stack id table), stable across agents and
    restarts."""
    return int.from_bytes(
        hashlib.blake2b(folded.encode(), digest_size=8).digest(), "big"
    ) >> 1


class PerfProfilerConnector(SourceConnector):
    """Sample all Python threads; publish attributed folded stacks."""

    name = "perf_profiler"
    tables = [
        ("stack_traces.beta", STACK_TRACES_RELATION),
        ("__stacks__", STACKS_RELATION),
    ]
    default_sampling_period_s = 0.01  # 100Hz, the reference's default rate
    default_push_period_s = 1.0

    def __init__(
        self,
        pod: str = "default/self",
        asid: int = 0,
        agent_id: str | None = None,
        **kw,
    ):
        super().__init__(**kw)
        self.pod = pod
        #: Stamped into __stacks__ rows and used to filter
        #: profile_summary() when several agents share one process
        #: (tests, single-node deploys) — without it their samples
        #: would double-count in every heartbeat.
        self.agent_id = agent_id if agent_id is not None else pod
        self.upid = UPID(asid=asid, pid=os.getpid(), start_ts=0)
        # (folded, qid, script_hash, tenant, phase) -> sample count.
        self._counts: dict[tuple, int] = {}
        # Cumulative since start (drained counts fold in here), bounded
        # by the profile_summary_stacks flag — the heartbeat export.
        self._summary: dict[tuple, int] = {}
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def init(self) -> None:
        super().init()
        with _ACTIVE_LOCK:
            if self not in _ACTIVE:
                _ACTIVE.append(self)

    def stop(self) -> None:
        with _ACTIVE_LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        super().stop()

    # -- sampling ------------------------------------------------------------
    def sample(self) -> None:
        """One sampling tick: fold every live thread's current stack.
        Stacks accumulate in a sweep-local dict and merge under ONE
        lock acquisition — at 100Hz on a many-thread agent, a lock
        round trip per stack was measurable churn against the drain
        in ``transfer_data``. Attribution reads are lock-free (one
        GIL-atomic dict get per thread)."""
        me = threading.get_ident()
        sweep: dict[tuple, int] = {}
        tenant_sweep: dict[str, int] = {}
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the collector thread observing itself is noise
            folded = _fold_stack(frame)
            if not folded:
                continue
            attr = threadmap.attribution(threadmap.lookup(tid))
            key = (folded, *attr)
            sweep[key] = sweep.get(key, 0) + 1
            tenant_sweep[attr[2]] = tenant_sweep.get(attr[2], 0) + 1
        if not sweep:
            return
        with self._lock:
            for key, n in sweep.items():
                self._counts[key] = self._counts.get(key, 0) + n
        self._count_tenants(tenant_sweep)

    def _count_tenants(self, tenant_sweep: dict[str, int]) -> None:
        # Raw attribution strings fold through the registered-tenant
        # resolver before labeling (bounded series cardinality; the
        # metrics-naming lint contract). count_unknown=False: an
        # unattributed sample is not an unknown-tenant *query*.
        from ..services.observability import default_counter
        from ..services.tenancy import resolve_tenant

        counter = default_counter(
            "pixie_cpu_samples_total",
            "Profiler stack samples attributed to each tenant "
            "(samples * sampling period = CPU-seconds)",
        )
        for raw, n in tenant_sweep.items():
            tenant = resolve_tenant(raw or None, count_unknown=False)
            counter.labels(tenant=tenant).inc(n)

    # -- drain ---------------------------------------------------------------
    def transfer_data(self, ctx, data_tables) -> None:
        # The collector calls transfer_data on the sampling cadence; fold
        # a sample each call and drain the accumulated counts every call —
        # the DataTable buffers until the push period fires (the BPF map
        # drain analog).
        self.sample()
        from ..config import get_flag

        cap = max(int(get_flag("profile_summary_stacks")), 16)
        with self._lock:
            if not self._counts:
                return
            items = list(self._counts.items())
            self._counts.clear()
            for key, n in items:
                self._summary[key] = self._summary.get(key, 0) + n
            if len(self._summary) > cap:
                # Keep the hottest stacks; cold tails age out. Counts
                # stay monotonic for survivors (diff-safe).
                keep = sorted(
                    self._summary.items(), key=lambda kv: -kv[1]
                )[:cap]
                self._summary = dict(keep)
        now = time.time_ns()
        # Attributed rows -> the __stacks__ telemetry ring.
        n = len(items)
        data_tables["__stacks__"].append({
            "time_": [now] * n,
            "agent_id": [self.agent_id] * n,
            "stack_trace_id": [stack_id(k[0]) for k, _ in items],
            "stack_trace": [k[0] for k, _ in items],
            "count": [c for _, c in items],
            "qid": [k[1] for k, _ in items],
            "script_hash": [k[2] for k, _ in items],
            "tenant": [k[3] for k, _ in items],
            "phase": [k[4] for k, _ in items],
        })
        # Legacy anonymous aggregate (px/perf_flamegraph compatibility):
        # collapse the attribution dimensions back out.
        agg: dict[str, int] = {}
        for key, c in items:
            agg[key[0]] = agg.get(key[0], 0) + c
        stacks = list(agg)
        m = len(stacks)
        data_tables["stack_traces.beta"].append({
            "time_": [now] * m,
            "upid": [self.upid.value()] * m,
            "stack_trace_id": [stack_id(s) for s in stacks],
            "stack_trace": stacks,
            "count": [agg[s] for s in stacks],
            "pod": [self.pod] * m,
        })

    # -- export --------------------------------------------------------------
    def summary_items(self) -> list[tuple[tuple, int]]:
        """Cumulative (key, count) pairs: drained summary + pending
        counts, so callers see samples taken since the last push too."""
        with self._lock:
            merged = dict(self._summary)
            for key, n in self._counts.items():
                merged[key] = merged.get(key, 0) + n
        return list(merged.items())


def profile_summary(
    agent_id: str | None = None, top: int = 64
) -> list[dict]:
    """Merged cumulative folded-stack summary across this process's
    active profilers (filtered to one agent when ``agent_id`` is given)
    — the payload agents ship in heartbeats. Rows:
    ``{stack, count, qid, script_hash, tenant, phase}``, hottest first,
    bounded to ``top`` (0 = unbounded)."""
    with _ACTIVE_LOCK:
        conns = list(_ACTIVE)
    merged: dict[tuple, int] = {}
    for c in conns:
        if agent_id is not None and c.agent_id != agent_id:
            continue
        for key, n in c.summary_items():
            merged[key] = merged.get(key, 0) + n
    rows = [
        {
            "stack": k[0],
            "count": n,
            "qid": k[1],
            "script_hash": k[2],
            "tenant": k[3],
            "phase": k[4],
        }
        for k, n in merged.items()
    ]
    rows.sort(key=lambda r: (-r["count"], r["stack"]))
    return rows[:top] if top else rows
