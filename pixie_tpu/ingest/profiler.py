"""Self-sampling perf profiler connector.

Reference parity: the continuous profiler
(``/root/reference/src/stirling/source_connectors/perf_profiler/
perf_profiler_connector.h`` — eBPF stack sampling folded into the
``stack_traces.beta`` table). Without eBPF in scope (SURVEY.md §7 stage
7), the TPU-side analog samples THIS process's Python threads via
``sys._current_frames`` at the sampling period and folds identical
stacks into (stack_trace, count) rows — the same ``;``-joined
flamegraph-folded encoding the reference emits, queryable by the shipped
``px/perf_flamegraph`` script.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
import time

from ..utils.upid import UPID
from .core import SourceConnector
from .schemas import STACK_TRACES_RELATION


def _fold_stack(frame, max_depth: int = 64) -> str:
    """Flamegraph-folded stack string: outermost;...;innermost."""
    parts: list[str] = []
    while frame is not None and len(parts) < max_depth:
        code = frame.f_code
        parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    return ";".join(reversed(parts))


class PerfProfilerConnector(SourceConnector):
    """Sample all Python threads; publish folded stacks with counts."""

    name = "perf_profiler"
    tables = [("stack_traces.beta", STACK_TRACES_RELATION)]
    default_sampling_period_s = 0.01  # 100Hz, the reference's default rate
    default_push_period_s = 1.0

    def __init__(self, pod: str = "default/self", asid: int = 0, **kw):
        super().__init__(**kw)
        self.pod = pod
        self.upid = UPID(asid=asid, pid=os.getpid(), start_ts=0)
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def sample(self) -> None:
        """One sampling tick: fold every live thread's current stack.
        Stacks accumulate in a sweep-local dict and merge under ONE
        lock acquisition — at 100Hz on a many-thread agent, a lock
        round trip per stack was measurable churn against the drain
        in ``transfer_data``."""
        me = threading.get_ident()
        sweep: dict[str, int] = {}
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the collector thread observing itself is noise
            folded = _fold_stack(frame)
            if not folded:
                continue
            sweep[folded] = sweep.get(folded, 0) + 1
        if not sweep:
            return
        with self._lock:
            for folded, n in sweep.items():
                self._counts[folded] = self._counts.get(folded, 0) + n

    def transfer_data(self, ctx, data_tables) -> None:
        # The collector calls transfer_data on the sampling cadence; fold
        # a sample each call and drain the accumulated counts every call —
        # the DataTable buffers until the push period fires (the BPF map
        # drain analog).
        self.sample()
        with self._lock:
            if not self._counts:
                return
            stacks = list(self._counts)
            counts = [self._counts[s] for s in stacks]
            self._counts.clear()
        # Stable 63-bit content hash: bounded memory on long-lived PEMs
        # (no per-stack id table), stable across agents and restarts.
        ids = [
            int.from_bytes(
                hashlib.blake2b(s.encode(), digest_size=8).digest(), "big"
            ) >> 1
            for s in stacks
        ]
        now = time.time_ns()
        n = len(stacks)
        data_tables["stack_traces.beta"].append({
            "time_": [now] * n,
            "upid": [self.upid.value()] * n,
            "stack_trace_id": ids,
            "stack_trace": stacks,
            "count": counts,
            "pod": [self.pod] * n,
        })
