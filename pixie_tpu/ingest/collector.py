"""Collector core loop: poll connectors, push full tables downstream.

Reference parity: ``src/stirling/stirling.{h,cc}`` —
``Stirling::Create`` + ``RegisterDataPushCallback`` + ``RunAsThread``
(``stirling.h:90-190``); the core loop wakes at the earliest
sampling/push deadline across connectors (``stirling.cc:732,770-815``),
calls ``TransferData`` on expired samplers, and drains tables whose push
period fired (or whose buffers crossed their threshold) into the
registered push callback — ``TableStore.append_data`` when wired to an
engine/agent (``pem_manager.cc:48``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .core import DataTable, SourceConnector


class Collector:
    def __init__(self):
        self._connectors: list[SourceConnector] = []
        self._data_tables: dict[str, DataTable] = {}
        self._push_cb: Optional[Callable] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.stats = {"transfer_calls": 0, "pushes": 0, "rows_pushed": 0}
        # Connector failures are recorded, not fatal (the stirling_error
        # self-observability pattern): one bad source must never stop the
        # others from collecting.
        self.errors: list[tuple[str, str]] = []

    # -- setup ---------------------------------------------------------------
    def register_source(self, connector: SourceConnector) -> None:
        connector.init()
        with self._lock:
            self._connectors.append(connector)
            for name, rel in connector.tables:
                existing = self._data_tables.get(name)
                if existing is not None and list(existing.relation.items()) == list(rel.items()):
                    continue  # same-schema redeploy: pending rows survive
                self._data_tables[name] = DataTable(name, rel)

    def remove_source(self, connector: SourceConnector) -> None:
        """Stop and detach a connector (dynamic tracepoint removal); its
        table buffer stays so already-collected rows still push."""
        connector.stop()
        with self._lock:
            if connector in self._connectors:
                self._connectors.remove(connector)

    def register_data_push_callback(self, cb: Callable) -> None:
        """cb(table_name, relation, records_dict) — the
        RegisterDataPushCallback surface (``stirling.h:115``)."""
        self._push_cb = cb

    def wire_to(self, engine_or_agent) -> None:
        """Convenience: push straight into an engine/agent table store
        (``pem_manager.cc:48`` binds the callback to AppendData)."""

        def cb(name, relation, records):
            engine_or_agent.append_data(name, records)

        self.register_data_push_callback(cb)

    def schemas(self) -> dict:
        """Published table schemas (InfoClassManager pub/sub analog)."""
        with self._lock:
            return {n: dt.relation for n, dt in self._data_tables.items()}

    # -- core loop -----------------------------------------------------------
    def run_core(self, once: bool = False) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                connectors = list(self._connectors)
            for c in connectors:
                if c.sampling_freq.expired(now):
                    try:
                        c.transfer_data(self, self._data_tables)
                        self.stats["transfer_calls"] += 1
                    except Exception as e:
                        self.errors.append((c.name, repr(e)))
                    c.sampling_freq.reset(now)
                push_due = c.push_freq.expired(now)
                if push_due:
                    c.push_freq.reset(now)
                for name, _rel in c.tables:
                    dt = self._data_tables[name]
                    if (push_due or dt.over_threshold()) and dt.pending_rows:
                        try:
                            self._push(dt)
                        except Exception as e:  # push must not kill the loop
                            self.errors.append((dt.name, repr(e)))
            if once:
                return
            # Sleep until the earliest upcoming deadline (stirling.cc:732).
            deadlines = [
                f.next_deadline
                for c in connectors
                for f in (c.sampling_freq, c.push_freq)
            ]
            wake = min(deadlines) if deadlines else now + 0.1
            self._stop.wait(timeout=max(0.0, wake - time.monotonic()))

    def _push(self, dt: DataTable) -> None:
        if self._push_cb is None:
            return  # keep buffering until a callback is wired
        records = dt.drain()
        if records is None:
            return
        n = len(next(iter(records.values())))
        self._push_cb(dt.name, dt.relation, records)
        self.stats["pushes"] += 1
        self.stats["rows_pushed"] += n

    def run_as_thread(self) -> threading.Thread:
        """Stirling::RunAsThread (``stirling.h:132``)."""
        self._thread = threading.Thread(target=self.run_core, daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for c in self._connectors:
            c.stop()

    def flush(self) -> None:
        """Drain every pending buffer immediately (test/shutdown path)."""
        with self._lock:
            tables = list(self._data_tables.values())
        for dt in tables:
            if dt.pending_rows:
                self._push(dt)
