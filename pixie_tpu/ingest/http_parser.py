"""HTTP/1.x stream parser: raw captured bytes -> http_events records.

Reference parity: the socket tracer's HTTP protocol parser
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/http/parse.cc`` + ``stitcher.cc``): incremental parsing of
captured request and response byte streams per connection, then
request/response stitching into trace records with latency. Without
eBPF capture in scope, the parser consumes byte chunks from any tap
(pcap export, proxy log, test fixtures) through the same incremental
state machine: partial messages survive across ``feed`` calls, pipelined
messages in one chunk all parse, and stitching pairs FIFO per
connection (HTTP/1.1 ordering guarantee).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from .conn_table import ConnectionTable
from typing import Optional

_MAX_BUF = 1 << 20  # per-direction cap; a stuck stream drops oldest bytes


@dataclass
class HTTPMessage:
    is_request: bool
    method: str = ""
    path: str = ""
    status: int = 0
    headers: dict = field(default_factory=dict)
    body: bytes = b""
    ts_ns: int = 0


class _StreamParser:
    """Incremental parser for one direction of one connection."""

    def __init__(self, is_request: bool):
        self.is_request = is_request
        self._buf = b""

    def feed(self, data: bytes, ts_ns: int) -> list[HTTPMessage]:
        self._buf += data
        if len(self._buf) > _MAX_BUF:
            self._buf = self._buf[-_MAX_BUF:]
        out = []
        while True:
            msg, consumed = self._parse_one(ts_ns)
            if msg is None:
                break
            self._buf = self._buf[consumed:]
            out.append(msg)
        return out

    def _parse_one(self, ts_ns: int):
        # Garbage-resync loop, not recursion: a chunk of binary data on a
        # tapped connection can hold thousands of CRLFCRLF-delimited
        # blocks (parse.cc's recovery on garbage bytes skips them all).
        while True:
            head_end = self._buf.find(b"\r\n\r\n")
            if head_end < 0:
                return None, 0
            head = self._buf[:head_end].decode("latin-1")
            lines = head.split("\r\n")
            start = lines[0].split(" ", 2)
            msg = HTTPMessage(is_request=self.is_request, ts_ns=ts_ns)
            try:
                if self.is_request:
                    if len(start) < 3 or not start[2].startswith("HTTP/"):
                        raise ValueError(start)
                    msg.method, msg.path = start[0], start[1]
                else:
                    if len(start) < 2 or not start[0].startswith("HTTP/"):
                        raise ValueError(start)
                    msg.status = int(start[1])
                break
            except ValueError:
                self._parse_errors = getattr(self, "_parse_errors", 0) + 1
                self._buf = self._buf[head_end + 4:]
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            msg.headers[k.strip().lower()] = v.strip()

        body_start = head_end + 4
        clen = msg.headers.get("content-length")
        if clen is not None and clen.isdigit():
            n = int(clen)
            if len(self._buf) < body_start + n:
                return None, 0  # body incomplete: wait for more bytes
            msg.body = self._buf[body_start:body_start + n]
            return msg, body_start + n
        if msg.headers.get("transfer-encoding", "").lower() == "chunked":
            end = self._buf.find(b"0\r\n\r\n", body_start)
            if end < 0:
                return None, 0
            msg.body = self._buf[body_start:end]
            return msg, end + 5
        return msg, body_start  # no body (the telemetry common case)


class _HTTPConn:
    last_ts = 0

    def __init__(self):
        self.req = _StreamParser(True)
        self.resp = _StreamParser(False)
        self.pending: deque = deque()


class HTTPStitcher:
    """Pairs requests with responses per connection; emits http_events
    records (``stitcher.cc`` ProcessMessages)."""

    # Per-connection pending requests are capped so a request flood
    # with no responses can't grow without bound; idle-connection expiry
    # lives in the shared ConnectionTable.
    PENDING_PER_CONN = 512

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_HTTPConn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        """Feed one captured chunk; returns records emitted."""
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        req_p, resp_p, pending = c.req, c.resp, c.pending
        emitted = 0
        if is_request:
            for m in req_p.feed(data, ts):
                if len(pending) >= self.PENDING_PER_CONN:
                    # Pairing is positional, so dropping any one entry
                    # would silently mispair every later response on this
                    # connection. Kill the connection instead (the
                    # reference disables a ConnTracker it can no longer
                    # trust): its state is discarded and the drops are
                    # counted; later chunks start a fresh tracker.
                    self.parse_errors += len(pending) + 1
                    self._conns.kill(conn_id)
                    return emitted
                pending.append(m)
        else:
            for m in resp_p.feed(data, ts):
                if not pending:
                    self.parse_errors += 1  # response with no request
                    continue
                req = pending.popleft()
                self.records.append({
                    "time_": req.ts_ns,
                    "latency_ns": max(m.ts_ns - req.ts_ns, 0),
                    "resp_status": m.status,
                    "req_method": req.method,
                    "req_path": req.path,
                    "resp_body_bytes": len(m.body),
                    "service": self.service,
                    "pod": self.pod,
                })
                emitted += 1
        return emitted

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
