"""Cassandra CQL wire-protocol parser + stitcher: captured bytes ->
cql_events.

Reference parity: the socket tracer's cass protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/cass/`` — frame decode + stream-id matching). Capture arrives
as byte chunks from any tap; partial frames survive across ``feed``.

Protocol essentials (CQL binary protocol v3/v4/v5, public spec):
- Every frame: version (1 byte; high bit set = response), flags
  (1 byte; 0x01 = compressed body), stream id (i16 big-endian),
  opcode (1 byte), body length (u32 big-endian), body.
- Requests and responses pair BY STREAM ID (clients multiplex many
  in-flight queries per connection). Server push EVENT frames use
  stream id -1 and have no request.
- QUERY/PREPARE bodies start with a "long string" (u32 length + utf8)
  holding the CQL text; EXECUTE starts with "short bytes" (u16 length)
  holding the prepared-statement id; RESULT bodies start with an i32
  kind (Void/Rows/SetKeyspace/Prepared/SchemaChange); ERROR bodies are
  i32 code + string message.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

from .conn_table import ConnectionTable

# Opcodes (protocol spec §2.4; cass/types.h ReqOp/RespOp).
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_REGISTER = 0x0B
OP_EVENT = 0x0C
OP_BATCH = 0x0D
OP_AUTH_CHALLENGE = 0x0E
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

OP_NAMES = {
    OP_ERROR: "ERROR", OP_STARTUP: "STARTUP", OP_READY: "READY",
    OP_AUTHENTICATE: "AUTHENTICATE", OP_OPTIONS: "OPTIONS",
    OP_SUPPORTED: "SUPPORTED", OP_QUERY: "QUERY", OP_RESULT: "RESULT",
    OP_PREPARE: "PREPARE", OP_EXECUTE: "EXECUTE", OP_REGISTER: "REGISTER",
    OP_EVENT: "EVENT", OP_BATCH: "BATCH",
    OP_AUTH_CHALLENGE: "AUTH_CHALLENGE", OP_AUTH_RESPONSE: "AUTH_RESPONSE",
    OP_AUTH_SUCCESS: "AUTH_SUCCESS",
}

_RESULT_KINDS = {1: "Void", 2: "Rows", 3: "SetKeyspace", 4: "Prepared",
                 5: "SchemaChange"}

_HDR = 9  # version + flags + stream + opcode + length


class _Framer:
    """Incremental CQL frame splitter for one direction."""

    MAX_BODY = 4 << 20

    def __init__(self):
        self._buf = b""
        self._skip = 0
        self._skip_hdr = None
        self.oversized = 0

    def feed(self, data: bytes):
        """Yield (version, flags, stream, opcode, body|None) frames —
        body None marks an oversized frame whose payload was dropped."""
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                out.append((*self._skip_hdr, None))
                continue
            if len(self._buf) < _HDR:
                break
            ver = self._buf[0]
            flags = self._buf[1]
            stream = int.from_bytes(self._buf[2:4], "big", signed=True)
            opcode = self._buf[4]
            ln = int.from_bytes(self._buf[5:9], "big")
            if (ver & 0x7F) not in (3, 4, 5) or opcode > 0x10:
                self._buf = self._buf[1:]  # garbage: resync byte-wise
                continue
            if ln > self.MAX_BODY:
                # Giant body (huge batch / result page): keep the header
                # for pairing, discard the payload incrementally.
                self.oversized += 1
                self._skip_hdr = (ver, flags, stream, opcode)
                drop = min(_HDR + ln, len(self._buf))
                self._skip = _HDR + ln - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                out.append((*self._skip_hdr, None))
                continue
            if len(self._buf) < _HDR + ln:
                break
            out.append(
                (ver, flags, stream, opcode, self._buf[_HDR:_HDR + ln])
            )
            self._buf = self._buf[_HDR + ln:]
        return out


def _long_string(body: bytes) -> str:
    if len(body) < 4:
        return ""
    n = int.from_bytes(body[:4], "big")
    return body[4:4 + min(n, len(body) - 4)].decode("utf-8", "replace")


_COMPRESSED = object()  # flags & 0x01: body is lz4/snappy, not parsed
# (oversized frames keep the framer's body=None convention)


def _req_summary(opcode: int, body) -> str:
    if body is None:
        return "<oversized>"
    if body is _COMPRESSED:
        return "<compressed>"
    if opcode in (OP_QUERY, OP_PREPARE):
        q = _long_string(body)
        return q if len(q) <= 1024 else q[:1024] + "..."
    if opcode == OP_EXECUTE:
        if len(body) >= 2:
            n = int.from_bytes(body[:2], "big")
            return "id=" + body[2:2 + min(n, 16)].hex()
        return ""
    if opcode == OP_BATCH:
        # batch type (1) + query count (u16).
        if len(body) >= 3:
            n = int.from_bytes(body[1:3], "big")
            return f"queries={n}"
        return ""
    return ""


def _resp_summary(opcode: int, body) -> str:
    if body is None:
        return "<oversized>"
    if body is _COMPRESSED:
        return "<compressed>"
    if opcode == OP_RESULT:
        if len(body) >= 4:
            kind = int.from_bytes(body[:4], "big")
            name = _RESULT_KINDS.get(kind, f"kind={kind}")
            if kind == 2 and len(body) >= 12:
                # Rows: i32 metadata flags then i32 column count.
                ncols = int.from_bytes(body[8:12], "big")
                return f"Rows cols={ncols}"
            return name
        return "Result"
    if opcode == OP_ERROR:
        if len(body) >= 6:
            code = int.from_bytes(body[:4], "big")
            n = int.from_bytes(body[4:6], "big")
            msg = body[6:6 + min(n, 256)].decode("utf-8", "replace")
            return f"({code:#06x}) {msg}"
        return "Error"
    return OP_NAMES.get(opcode, "")


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        # stream id -> (req_op, req_body, ts); insertion-ordered so
        # overflow evicts the oldest in-flight stream.
        self.pending: OrderedDict = OrderedDict()


class CQLStitcher:
    """Pairs CQL frames by stream id; emits cql_events records."""

    PENDING_PER_CONN = 512

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for ver, flags, stream, opcode, body in c.req.feed(data):
                if ver & 0x80:
                    self.parse_errors += 1  # response bits on req stream
                    continue
                if flags & 0x01 and body is not None:
                    body = _COMPRESSED  # summary-only, distinct sentinel
                while len(c.pending) >= self.PENDING_PER_CONN:
                    c.pending.popitem(last=False)
                    self.parse_errors += 1
                c.pending[stream] = (opcode, _req_summary(opcode, body), ts)
            return emitted
        for ver, flags, stream, opcode, body in c.resp.feed(data):
            if not ver & 0x80:
                self.parse_errors += 1
                continue
            if flags & 0x01 and body is not None:
                body = _COMPRESSED
            if opcode == OP_EVENT:
                # Server push (topology/status/schema change): no
                # request to pair; stream id is -1 by spec.
                self._emit(OP_EVENT, "", ts, ts, opcode,
                           _resp_summary(opcode, body))
                emitted += 1
                continue
            req = c.pending.pop(stream, None)
            if req is None:
                self.parse_errors += 1
                continue
            req_op, req_body, req_ts = req
            self._emit(req_op, req_body, req_ts, ts, opcode,
                       _resp_summary(opcode, body))
            emitted += 1
        return emitted

    def _emit(self, req_op, req_body, req_ts, resp_ts, resp_op, resp_body):
        self.records.append({
            "time_": req_ts,
            "req_op": int(req_op),
            "req_body": req_body,
            "resp_op": int(resp_op),
            "resp_body": resp_body,
            "latency_ns": max(resp_ts - req_ts, 0),
            "service": self.service,
            "pod": self.pod,
        })

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
