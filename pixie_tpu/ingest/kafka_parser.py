"""Kafka wire-protocol parser + stitcher: captured bytes ->
kafka_events.

Reference parity: the socket tracer's kafka protocol pair
(``/root/reference/src/stirling/source_connectors/socket_tracer/
protocols/kafka/`` — length-prefixed frame decode + correlation-id
matching in its stitcher). Capture arrives as byte chunks from any tap;
partial frames survive across ``feed`` calls.

Protocol essentials (Kafka protocol, public spec):
- Every request/response is a 4-byte big-endian length prefix + body.
- Request body header: api_key (i16), api_version (i16),
  correlation_id (i32), client_id (nullable string: i16 length, -1 =
  null). Flexible versions append tagged fields — ignored here (the
  summary needs only the fixed header).
- Response body header: correlation_id (i32). Responses pair with
  requests BY CORRELATION ID, not position (brokers may interleave
  fetch long-polls with pipelined produces).
"""

from __future__ import annotations

import time
from collections import OrderedDict

from typing import Optional

from .conn_table import ConnectionTable

#: api_key -> name (protocol spec's ApiKeys table; kafka/types.h APIKey).
API_KEYS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata",
    4: "LeaderAndIsr", 5: "StopReplica", 6: "UpdateMetadata",
    7: "ControlledShutdown", 8: "OffsetCommit", 9: "OffsetFetch",
    10: "FindCoordinator", 11: "JoinGroup", 12: "Heartbeat",
    13: "LeaveGroup", 14: "SyncGroup", 15: "DescribeGroups",
    16: "ListGroups", 17: "SaslHandshake", 18: "ApiVersions",
    19: "CreateTopics", 20: "DeleteTopics", 21: "DeleteRecords",
    22: "InitProducerId", 23: "OffsetForLeaderEpoch", 24: "AddPartitionsToTxn",
    25: "AddOffsetsToTxn", 26: "EndTxn", 27: "WriteTxnMarkers",
    28: "TxnOffsetCommit", 29: "DescribeAcls", 30: "CreateAcls",
    31: "DeleteAcls", 32: "DescribeConfigs", 33: "AlterConfigs",
    34: "AlterReplicaLogDirs", 35: "DescribeLogDirs", 36: "SaslAuthenticate",
    37: "CreatePartitions", 38: "CreateDelegationToken",
    39: "RenewDelegationToken", 40: "ExpireDelegationToken",
    41: "DescribeDelegationToken", 42: "DeleteGroups", 43: "ElectLeaders",
    44: "IncrementalAlterConfigs", 45: "AlterPartitionReassignments",
    46: "ListPartitionReassignments", 47: "OffsetDelete",
    48: "DescribeClientQuotas", 49: "AlterClientQuotas",
    50: "DescribeUserScramCredentials", 51: "AlterUserScramCredentials",
    56: "AlterPartition", 57: "UpdateFeatures", 60: "DescribeCluster",
    61: "DescribeProducers", 65: "DescribeTransactions",
    66: "ListTransactions", 67: "AllocateProducerIds",
}


class _Framer:
    """Incremental 4-byte-length frame splitter for one direction."""

    MAX_FRAME = 8 << 20  # broker default message.max.bytes is ~1MB

    def __init__(self):
        self._buf = b""
        self._skip = 0
        self._skip_head = b""  # first bytes of an oversized frame
        self.oversized = 0

    def feed(self, data: bytes):
        self._buf += data
        out = []
        while True:
            if self._skip:
                drop = min(self._skip, len(self._buf))
                self._buf = self._buf[drop:]
                self._skip -= drop
                if self._skip:
                    break
                out.append((True, self._skip_head))  # (truncated, head)
                continue
            if len(self._buf) < 4:
                break
            ln = int.from_bytes(self._buf[:4], "big", signed=True)
            if ln < 0:
                self._buf = self._buf[1:]  # garbage: resync byte-wise
                continue
            if ln > self.MAX_FRAME:
                # Keep the header bytes (they carry api key/correlation
                # id) and discard the rest incrementally — pairing must
                # survive giant produce batches. Wait for the header to
                # be fully buffered first: entering skip mode off a chunk
                # boundary inside the first 8 body bytes would lose the
                # correlation id.
                head_n = min(ln, 64)
                if len(self._buf) < 4 + head_n:
                    break
                self.oversized += 1
                self._skip_head = self._buf[4:4 + head_n]
                drop = min(4 + ln, len(self._buf))
                self._skip = 4 + ln - drop
                self._buf = self._buf[drop:]
                if self._skip:
                    break
                out.append((True, self._skip_head))
                continue
            if len(self._buf) < 4 + ln:
                break
            out.append((False, self._buf[4:4 + ln]))
            self._buf = self._buf[4 + ln:]
        return out


class _Conn:
    last_ts = 0

    def __init__(self):
        self.req = _Framer()
        self.resp = _Framer()
        # correlation_id -> (api_name, api_version, client_id, ts);
        # insertion-ordered so overflow evicts the oldest.
        self.pending: OrderedDict = OrderedDict()


class KafkaStitcher:
    """Pairs request/response frames by correlation id; emits
    kafka_events records."""

    PENDING_PER_CONN = 512

    def __init__(self, service: str = "", pod: str = ""):
        self.service = service
        self.pod = pod
        self._conns = ConnectionTable(_Conn)
        self.records: list[dict] = []
        self.parse_errors = 0

    def feed(
        self, conn_id, data: bytes, is_request: bool,
        ts_ns: Optional[int] = None,
    ) -> int:
        ts = ts_ns if ts_ns is not None else time.time_ns()
        c = self._conns.get(conn_id, ts)
        emitted = 0
        if is_request:
            for truncated, body in c.req.feed(data):
                if len(body) < 8:
                    self.parse_errors += 1
                    continue
                api_key = int.from_bytes(body[0:2], "big", signed=True)
                api_ver = int.from_bytes(body[2:4], "big", signed=True)
                cid = int.from_bytes(body[4:8], "big", signed=True)
                client_id = ""
                if len(body) >= 10:
                    cl = int.from_bytes(body[8:10], "big", signed=True)
                    if 0 <= cl <= len(body) - 10:
                        client_id = body[10:10 + cl].decode("utf-8", "replace")
                if api_key not in API_KEYS:
                    self.parse_errors += 1
                    continue  # not kafka / corrupt: don't poison pending
                while len(c.pending) >= self.PENDING_PER_CONN:
                    # Oldest request never got a response (lost capture):
                    # evict rather than kill — correlation ids keep later
                    # pairs valid, unlike positional protocols.
                    c.pending.popitem(last=False)
                    self.parse_errors += 1
                body_note = "<truncated>" if truncated else ""
                c.pending[cid] = (api_key, api_ver, client_id, ts, body_note)
            return emitted
        for truncated, body in c.resp.feed(data):
            if len(body) < 4:
                self.parse_errors += 1
                continue
            cid = int.from_bytes(body[0:4], "big", signed=True)
            req = c.pending.pop(cid, None)
            if req is None:
                self.parse_errors += 1
                continue
            api_key, api_ver, client_id, req_ts, body_note = req
            resp = "<truncated>" if truncated else f"bytes={len(body)}"
            self.records.append({
                "time_": req_ts,
                "req_cmd": api_key,
                "client_id": client_id,
                "req_body": f"{API_KEYS[api_key]} v{api_ver}"
                            + (f" {body_note}" if body_note else ""),
                "resp": resp,
                "latency_ns": max(ts - req_ts, 0),
                "service": self.service,
                "pod": self.pod,
            })
            emitted += 1
        return emitted

    def drain(self) -> list[dict]:
        out, self.records = self.records, []
        return out
