"""Always-on query-lifecycle tracing: spans, ring buffer, OTLP export.

Reference parity: Carnot ships per-operator ``OperatorExecutionStats``
with every query result (``src/carnot/carnot.cc:389-423``) and the
services expose statusz/metrics — but that telemetry is per-request and
the engine's own ``analyze`` mode forces device sync (killing the PR-1
pipeline overlap). This module is the cheap, always-on third way: every
query gets a **trace** — a tree of spans stamped at existing host-side
boundaries, never ``block_until_ready`` — kept in a bounded ring buffer
and optionally pushed over the engine's own OTLP path (dogfooding
``exec/otel.py``'s span dicts through ``OTLPHttpExporter``).

Span hierarchy (one trace per ``Engine.execute_plan`` /
``StreamingQuery`` lifetime):

- ``query``               root; status/script-hash/row-count attributes
- ``compile``             parse + PxL compile + plan (execute_query path)
- ``fragment``            one per compiled fragment actually executed
  (Map/Filter/Agg chain, join driver, rebucket attempt); attributes
  carry windows, rows in/out and the per-stage second totals
- ``window.<stage>``      sampled per-window stage/compute/stall
  intervals (every ``trace_window_sample``-th interval per stage),
  children of their fragment span

The stats spine is shared with ``analyze`` (``analyze.py``): a trace
owns a ``QueryStats`` whose fragments the engine fills exactly as
before; ``analyze=True`` just flips ``sync=True`` on that object, so
analyze is a *detail level* of the same trace, not a separate path.

Because compute stamps are taken without fencing the device, a window's
``compute`` interval measures **dispatch** time (host-side cost of
enqueueing the program) and ``stall`` measures where the query thread
actually waited — which is exactly the signal sketch/telemetry-driven
optimization wants (arXiv:2102.02440, arXiv:2506.20010): where does
wall-clock go, without perturbing it.

Consumers:

- ``Tracer.recent()`` / ``in_flight()`` — served by
  ``ObservabilityServer`` as ``/debug/queryz``
- Prometheus histograms on the shared ``MetricsRegistry``
  (``pixie_query_duration_seconds``, ``pixie_window_stage_seconds``,
  ``pixie_pipeline_stall_seconds``) — ``/metrics``
- the slow-query log (``slow_query_threshold_ms`` flag): offending
  queries dump their full trace to the ``pixie_tpu.slow_query`` logger
- OTLP/HTTP push of finished traces when ``trace_export_url`` is set
  (in-memory otherwise); export failures count in
  ``pixie_trace_export_errors_total`` and never fail the query
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field

from ..config import get_flag
from . import tracectx
from .analyze import FragmentStats, QueryStats, StageStat

logger = logging.getLogger("pixie_tpu.slow_query")

#: Hard cap on spans kept per trace (sampling bounds the rate, this
#: bounds the worst case — a million-window query must not hold a
#: million span dicts).
MAX_SPANS_PER_TRACE = 512

#: Sub-second buckets for per-window stage timings (a window stage is
#: typically 0.1ms..1s; the prometheus defaults top out too coarse).
STAGE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

#: Byte-volume buckets (staged / wire bytes per query): one window is
#: KBs..MBs, a 16M-row scan is GBs.
BYTES_BUCKETS = (
    1 << 10, 1 << 14, 1 << 18, 1 << 22, 1 << 26, 1 << 30, 1 << 34,
)

#: Millisecond buckets (per-query device dispatch time).
MS_BUCKETS = (0.1, 1.0, 10.0, 100.0, 1_000.0, 10_000.0, 100_000.0)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class QueryResourceUsage:
    """What one query actually COST, accumulated at existing host
    boundaries (never a device sync): the observed counterpart of the
    sketch-guided planner's predictions (arXiv:2102.02440 feedback loop)
    and the load signal multi-tenant admission control schedules on.

    - ``bytes_staged``  host->device transfer bytes during execution
      (0 for device-cache-resident windows — those were staged at
      append time; the gap between rows_in and bytes_staged IS the
      cache-hit signal)
    - ``device_ms``     host-side dispatch time of device programs
      (compute + finalize stage seconds; dispatch, not fenced runtime)
    - ``compile_ms``    the compile span (parse + PxL + plan + verify)
    - ``stall_ms``      query-thread time blocked on the prefetch pipe
    - ``wire_bytes``    bridge payload bytes this query SHIPPED
      (BridgeSinkOp egress — data-agent attribution; the merge's
      ingress is the sum over its producers)
    - ``retries``       dispatch retries (broker) + join-capacity
      overflow retries (engine)
    - ``skipped_windows`` probe/scan windows never staged (zone maps)
    - ``device_peak_bytes`` high-water device ``bytes_in_use`` observed
      while the query ran (``exec/programs.py`` DeviceMemoryMonitor;
      TPU-real, 0 on backends whose ``memory_stats()`` is None).
      Merges by MAX across agents — it is a watermark, not a volume.
    - ``freshness_lag_ms`` result staleness: query stop-time minus the
      max event-time watermark of each scanned table at execute time,
      worst table kept (0 = fresh or no time-indexed scan). Merges by
      MAX across agents — the answer is only as fresh as the most
      behind shard. The validity predicate a result cache keyed on
      (script hash, table watermark) would check.
    """

    rows_in: int = 0
    rows_out: int = 0
    windows: int = 0
    bytes_staged: int = 0
    device_ms: float = 0.0
    compile_ms: float = 0.0
    stall_ms: float = 0.0
    wire_bytes: int = 0
    retries: int = 0
    skipped_windows: int = 0
    device_peak_bytes: int = 0
    freshness_lag_ms: float = 0.0
    # Cold-tier decode wall time charged to this query (decoding runs on
    # the prefetch producer thread — decode-on-stage — so this overlaps
    # device compute rather than adding to it; compare against stall_ms
    # to see whether decode ever became the bottleneck).
    decode_ms: float = 0.0

    def to_dict(self) -> dict:
        d = asdict(self)
        for k in ("device_ms", "compile_ms", "stall_ms",
                  "freshness_lag_ms", "decode_ms"):
            d[k] = round(d[k], 3)
        return d

    def merge(self, other: "QueryResourceUsage | dict") -> None:
        """Fold another usage record in (broker-side per-agent
        aggregation; accepts the dict form that crossed the bus)."""
        d = other if isinstance(other, dict) else asdict(other)
        for k in (
            "rows_in", "rows_out", "windows", "bytes_staged", "wire_bytes",
            "retries", "skipped_windows",
        ):
            setattr(self, k, getattr(self, k) + int(d.get(k, 0)))
        for k in ("device_ms", "compile_ms", "stall_ms", "decode_ms"):
            setattr(self, k, getattr(self, k) + float(d.get(k, 0.0)))
        # A watermark, not a volume: agents sharing a device would
        # double-count under addition.
        self.device_peak_bytes = max(
            self.device_peak_bytes, int(d.get("device_peak_bytes", 0))
        )
        # Staleness too: the merged answer is only as fresh as the most
        # behind agent's shard.
        self.freshness_lag_ms = max(
            self.freshness_lag_ms, float(d.get("freshness_lag_ms", 0.0))
        )


@dataclass
class Span:
    """One timed interval. ``to_otlp`` emits the OTLP-JSON span shape
    ``exec/otel.py`` ships (plus trace/span ids, which the batch path
    leaves to the collector)."""

    name: str
    trace_id: str
    span_id: str = field(default_factory=lambda: _new_id(8))
    parent_id: str = ""
    start_unix_nano: int = 0
    end_unix_nano: int = 0
    attributes: dict = field(default_factory=dict)

    def to_otlp(self) -> dict:
        from .otel import _attr_kvs

        d = {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "startTimeUnixNano": int(self.start_unix_nano),
            "endTimeUnixNano": int(self.end_unix_nano),
            "attributes": _attr_kvs(sorted(self.attributes.items())),
        }
        if self.parent_id:
            d["parentSpanId"] = self.parent_id
        return d


class _SpanCtx:
    """Context manager stamping a span's start/end around a block."""

    def __init__(self, trace: "QueryTrace", name: str, parent: Span | None):
        self.span = trace._new_span(name, parent)

    def __enter__(self) -> Span:
        self.span.start_unix_nano = time.time_ns()
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self.span.end_unix_nano = time.time_ns()
        if exc is not None:
            self.span.attributes["error"] = f"{type(exc).__name__}: {exc}"


class TracedFragment(FragmentStats):
    """FragmentStats that additionally owns a ``fragment`` span and
    records sampled per-window stage-interval spans + stage histograms.
    ``add`` runs on both the query thread (compute/stall) and the
    prefetch thread (stage) — the inherited lock covers both."""

    def __init__(self, ops: tuple, trace: "QueryTrace", sync: bool):
        super().__init__(ops=ops, sync=sync)
        self.trace = trace
        self.span = trace._new_span("fragment", trace.root)
        self.span.start_unix_nano = time.time_ns()
        self.span.attributes["ops"] = ",".join(ops) or "(join)"
        self.last_activity_ns = self.span.start_unix_nano

    def add(self, stage: str, seconds: float, rows: int = 0,
            nbytes: int = 0) -> None:
        now_ns = time.time_ns()
        with self._lock:
            s = self.stages.setdefault(stage, StageStat())
            s.seconds += seconds
            s.rows += int(rows)
            s.count += 1
            s.nbytes += int(nbytes)
            count = s.count
            self.last_activity_ns = now_ns
        tracer = self.trace.tracer
        if tracer is not None:
            tracer._observe_stage(stage, seconds)
        k = self.trace.window_sample
        if k and (count - 1) % k == 0:
            attrs = {"interval": count - 1}
            if rows:
                attrs["rows"] = int(rows)
            self.trace._add_span(Span(
                name=f"window.{stage}",
                trace_id=self.trace.trace_id,
                parent_id=self.span.span_id,
                start_unix_nano=now_ns - int(seconds * 1e9),
                end_unix_nano=now_ns,
                attributes=attrs,
            ))

    def finish(self, end_ns: int) -> None:
        """Seal the fragment span (trace end): end timestamp = last
        host-side activity, attributes = the final counters."""
        with self._lock:
            self.span.end_unix_nano = min(
                max(self.last_activity_ns, self.span.start_unix_nano), end_ns
            ) or end_ns
            self.span.attributes.update({
                "windows": self.windows,
                "rows_in": self.rows_in,
                "rows_out": self.rows_out,
            })
            for k, v in self.stages.items():
                self.span.attributes[f"{k}_seconds"] = round(v.seconds, 6)


class TraceStats(QueryStats):
    """The trace's stats spine — what the engine sees as
    ``_query_stats``. ``sync`` False = always-on tracing (no device
    fence); True = analyze detail level."""

    def __init__(self, trace: "QueryTrace", sync: bool = False):
        super().__init__(sync=sync)
        self.trace = trace

    def new_fragment(self, ops) -> TracedFragment:
        fs = TracedFragment(
            tuple(type(o).__name__ for o in ops), self.trace, self.sync
        )
        self.fragments.append(fs)
        return fs


class QueryTrace:
    """One query's lifecycle: ids, status, span tree, stats spine."""

    def __init__(self, tracer: "Tracer | None", script: str = "",
                 analyze: bool = False, kind: str = "query",
                 parent_ctx: dict | None = None):
        self.tracer = tracer
        # A valid parent context (a broker dispatch span, carried in the
        # bus envelope — see tracectx.py) makes this trace PART of the
        # distributed trace: same trace id, root parented under the
        # dispatch span. Otherwise this query is its own trace root.
        self.parent_ctx = (
            dict(parent_ctx) if tracectx.valid(parent_ctx) else None
        )
        self.trace_id = (
            self.parent_ctx["trace_id"] if self.parent_ctx else _new_id(16)
        )
        self.script = script or ""
        self.script_hash = hashlib.sha256(
            self.script.encode()
        ).hexdigest()[:12]
        self.kind = kind  # "query" | "stream" | "fragment" | "merge" | ...
        self.qid = ""  # distributed query id (agents/broker stamp it)
        self.agent_id = ""  # executing agent (agents stamp it)
        # Tenant the query was admitted under (services/tenancy.py):
        # the broker stamps its resolved tenant, agents copy it from
        # the dispatch envelope so per-agent __queries__ rows carry the
        # same attribution. "" = not a tenant-scoped query (bare local
        # engines).
        self.tenant = ""
        # Result-cache disposition (exec/result_cache.py): "hit" /
        # "miss" / "stale" / "bypass" / "view"; "" = cache not in play
        # (disabled, or a path the cache never sees). Flows to
        # __queries__ and `px debug queries`.
        self.cache = ""
        self.status = "running"
        self.error = ""
        self.start_unix_nano = time.time_ns()
        self.end_unix_nano = 0
        self._t0 = time.perf_counter()
        self.duration_s = 0.0
        self.window_sample = int(get_flag("trace_window_sample"))
        self.pipeline: dict | None = None  # engine.last_pipeline snapshot
        self.usage = QueryResourceUsage()
        self.agent_usage: dict = {}  # broker: {agent_id: usage dict}
        # pxbound predicted_cost (analysis/bounds.py): what the query
        # was PREDICTED to stage/ship at plan time. The broker stamps
        # it; `px debug queries` renders predicted vs observed.
        self.predicted: dict | None = None
        # Per-scanned-table staleness detail ({table: lag_ms} at scan
        # setup; usage.freshness_lag_ms keeps the worst) — queryz rows.
        self.freshness: dict = {}
        self.exported = False  # OTLP push succeeded (ring-drop counting)
        self.dropped_spans = 0
        self._lock = threading.Lock()
        self.root = Span(
            "query", self.trace_id, start_unix_nano=self.start_unix_nano,
            parent_id=self.parent_ctx["span_id"] if self.parent_ctx else "",
        )
        self.spans: list[Span] = [self.root]
        self.stats = TraceStats(self, sync=analyze)

    def ctx(self, span: "Span | None" = None) -> dict:
        """The propagation envelope for children of ``span`` (default:
        the root) — what the broker stamps onto dispatch messages."""
        return tracectx.make(
            self.trace_id, (span or self.root).span_id
        )

    def add_wire_bytes(self, n: int) -> None:
        """Account bridge egress bytes (BridgeSinkOp payloads)."""
        with self._lock:
            self.usage.wire_bytes += int(n)

    def note_freshness_lag(self, table: str, lag_ms: float) -> None:
        """Record one scanned table's staleness (query stop-time minus
        its max event-time watermark at scan setup): the usage field
        keeps the WORST table/round, ``self.freshness`` the per-table
        detail (/debug/queryz). Bounded: one key per scanned table."""
        lag_ms = max(0.0, float(lag_ms))
        with self._lock:
            self.usage.freshness_lag_ms = max(
                self.usage.freshness_lag_ms, lag_ms
            )
            self.freshness[table] = max(
                self.freshness.get(table, 0.0), lag_ms
            )

    # -- span plumbing -------------------------------------------------------
    def _new_span(self, name: str, parent: Span | None) -> Span:
        s = Span(
            name, self.trace_id,
            parent_id=parent.span_id if parent is not None else "",
        )
        self._add_span(s)
        return s

    def _add_span(self, span: Span) -> None:
        with self._lock:
            if len(self.spans) >= MAX_SPANS_PER_TRACE:
                self.dropped_spans += 1
                return
            self.spans.append(span)

    def span(self, name: str, parent: Span | None = None) -> _SpanCtx:
        """``with trace.span("compile"): ...`` — stamps start/end."""
        return _SpanCtx(self, name, parent if parent is not None else self.root)

    # -- derived views -------------------------------------------------------
    @property
    def rows_in(self) -> int:
        return sum(f.rows_in for f in self.stats.fragments)

    @property
    def rows_out(self) -> int:
        return sum(f.rows_out for f in self.stats.fragments)

    @property
    def windows(self) -> int:
        return sum(f.windows for f in self.stats.fragments)

    def _finalize(self, status: str, error: str) -> None:
        self.status = status
        self.error = error
        self.end_unix_nano = time.time_ns()
        self.duration_s = time.perf_counter() - self._t0
        self.stats.total_seconds = self.duration_s
        self.root.end_unix_nano = self.end_unix_nano
        self._finalize_usage()
        self.root.attributes.update({
            "status": status,
            "script_hash": self.script_hash,
            "kind": self.kind,
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "bytes_staged": self.usage.bytes_staged,
            "device_ms": round(self.usage.device_ms, 3),
            "wire_bytes": self.usage.wire_bytes,
        })
        if self.qid:
            self.root.attributes["qid"] = self.qid
        if self.agent_id:
            self.root.attributes["agent_id"] = self.agent_id
        if error:
            self.root.attributes["error"] = error
        if self.pipeline:
            self.root.attributes["pipeline_stall_seconds"] = round(
                self.pipeline.get("stall_secs", 0.0), 6
            )
        for f in self.stats.fragments:
            if isinstance(f, TracedFragment):
                f.finish(self.end_unix_nano)

    def _finalize_usage(self) -> None:
        """Derive the resource record from the stats spine + spans.
        Purely host-side arithmetic over already-collected counters."""
        u = self.usage
        # Additive: a broker trace pre-merged its agents' usage (its own
        # stats spine is empty); an engine trace starts from zeros.
        u.rows_in += self.rows_in
        u.rows_out += self.rows_out
        u.windows += self.windows
        for f in self.stats.fragments:
            with f._lock:
                stages = {k: (v.seconds, v.nbytes, v.count)
                          for k, v in f.stages.items()}
            u.bytes_staged += stages.get("stage", (0.0, 0, 0))[1]
            u.device_ms += (
                stages.get("compute", (0.0, 0, 0))[0]
                + stages.get("finalize", (0.0, 0, 0))[0]
            ) * 1e3
            u.stall_ms += stages.get("stall", (0.0, 0, 0))[0] * 1e3
            # Cold-tier stage adds: "decode" seconds ride the stage
            # timeline (producer thread); "skip" counts windows a zone
            # map pruned before stage/decode (one add() per window).
            u.decode_ms += stages.get("decode", (0.0, 0, 0))[0] * 1e3
            u.skipped_windows += stages.get("skip", (0.0, 0, 0))[2]
        compile_span = next(
            (s for s in self.spans if s.name == "compile"), None
        )
        if compile_span is not None and compile_span.end_unix_nano:
            u.compile_ms += (
                compile_span.end_unix_nano - compile_span.start_unix_nano
            ) / 1e6

    def to_dict(self) -> dict:
        """The /debug/queryz row (and slow-query log body)."""
        d = {
            "id": self.trace_id,
            "kind": self.kind,
            "script_hash": self.script_hash,
            "query": self.script[:200],
            "status": self.status,
            "start_unix_nano": self.start_unix_nano,
            "duration_ms": round(
                (self.duration_s if self.end_unix_nano
                 else time.perf_counter() - self._t0) * 1e3, 3
            ),
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "windows": self.windows,
            "spans": len(self.spans),
            "usage": self.usage.to_dict(),
            "fragments": [f.to_dict() for f in self.stats.fragments],
        }
        if self.qid:
            d["qid"] = self.qid
        if self.agent_id:
            d["agent_id"] = self.agent_id
        if self.tenant:
            d["tenant"] = self.tenant
        if self.cache:
            d["cache"] = self.cache
        if self.agent_usage:
            d["agent_usage"] = dict(self.agent_usage)
        if self.predicted:
            d["predicted"] = dict(self.predicted)
        if self.freshness:
            # dict() snapshot first: queryz renders in-flight traces
            # while the query thread may still note scans.
            d["freshness"] = {
                t: round(v, 3) for t, v in dict(self.freshness).items()
            }
        if self.parent_ctx:
            d["parent"] = dict(self.parent_ctx)
        if self.error:
            d["error"] = self.error
        if self.pipeline:
            d["pipeline"] = {
                k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.pipeline.items()
            }
        if self.dropped_spans:
            d["dropped_spans"] = self.dropped_spans
        return d

    def to_otlp(self) -> dict:
        """OTLP-JSON ResourceSpans payload — the exact shape
        ``OTLPHttpExporter`` POSTs to ``/v1/traces``."""
        from .otel import _attr_kvs

        res = [
            ("service.name", "pixie-tpu-engine"),
            ("query.script_hash", self.script_hash),
        ]
        if self.agent_id:
            res.append(("service.instance.id", self.agent_id))
        return {
            "resourceSpans": [{
                "resource": {
                    "attributes": _attr_kvs(res)
                },
                "scopeSpans": [{
                    "scope": {"name": "pixie_tpu.exec.trace"},
                    "spans": [s.to_otlp() for s in self.spans],
                }],
            }]
        }


class Tracer:
    """Per-engine trace sink: bounded ring of finished traces, the
    in-flight set, histogram/counter recording, slow-query log, and the
    optional OTLP push. All methods are thread-safe."""

    def __init__(self, registry=None, ring_size: int | None = None):
        self._registry = registry  # lazy: services import at first use
        self._ring: deque = deque(
            maxlen=int(ring_size or get_flag("trace_ring_size"))
        )
        self._inflight: dict[str, QueryTrace] = {}
        self._lock = threading.Lock()
        self._metrics: dict | None = None
        self._stage_hist: dict = {}  # stage -> bound Histogram
        self._exporter = None
        self._exporter_url = None
        # Finished-trace listeners (the TelemetryCollector hook): called
        # AFTER metrics/export, exceptions contained — telemetry folding
        # must never fail or slow the query that produced the trace.
        self._listeners: list = []
        self._closed = False

    def add_listener(self, fn) -> None:
        """Register ``fn(trace)`` to run on every finished trace."""
        self._listeners.append(fn)

    def shutdown(self) -> None:
        """Stop exporting/notifying: traces finished after shutdown
        still finalize into the ring (queryz keeps working) but no OTLP
        push or listener runs — the teardown contract for processes
        whose collector endpoint is already gone."""
        self._closed = True

    # -- metrics -------------------------------------------------------------
    @property
    def registry(self):
        if self._registry is None:
            from ..services.observability import default_registry

            self._registry = default_registry
        return self._registry

    def _m(self) -> dict:
        if self._metrics is None:
            reg = self.registry
            self._metrics = {
                "queries": reg.counter(
                    "pixie_queries_total",
                    "Queries finished, by terminal status",
                ),
                "duration": reg.histogram(
                    "pixie_query_duration_seconds",
                    "End-to-end query wall time (compile + execute)",
                ),
                "stage": reg.histogram(
                    "pixie_window_stage_seconds",
                    "Per-window host-side stage intervals (stage/compute/"
                    "stall/finalize/materialize; timestamps, not device "
                    "sync)",
                    buckets=STAGE_BUCKETS,
                ),
                "stall": reg.histogram(
                    "pixie_pipeline_stall_seconds",
                    "Per-query total window-pipeline stall",
                ),
                "slow": reg.counter(
                    "pixie_slow_queries_total",
                    "Queries over slow_query_threshold_ms",
                ),
                "export_errors": reg.counter(
                    "pixie_trace_export_errors_total",
                    "Failed OTLP trace pushes (trace_export_url)",
                ),
                "dropped": reg.counter(
                    "pixie_trace_dropped_total",
                    "Finished traces evicted from the ring buffer "
                    "without having been OTLP-exported",
                ),
                "bytes_staged": reg.histogram(
                    "pixie_query_bytes_staged",
                    "Per-query host->device staging bytes (0 = fully "
                    "device-cache-resident)",
                    buckets=BYTES_BUCKETS,
                ),
                "device_ms": reg.histogram(
                    "pixie_query_device_ms",
                    "Per-query device program dispatch milliseconds "
                    "(compute + finalize stages; host-side, unfenced)",
                    buckets=MS_BUCKETS,
                ),
                "wire_bytes": reg.histogram(
                    "pixie_query_wire_bytes",
                    "Per-query bridge payload egress bytes (agent "
                    "fragments shipping partial states/rows)",
                    buckets=BYTES_BUCKETS,
                ),
            }
        return self._metrics

    def _observe_stage(self, stage: str, seconds: float) -> None:
        h = self._stage_hist.get(stage)
        if h is None:
            h = self._stage_hist[stage] = self._m()["stage"].labels(
                stage=stage
            )
        h.observe(seconds)

    # -- lifecycle -----------------------------------------------------------
    def begin_query(self, script: str = "", analyze: bool = False,
                    kind: str = "query",
                    parent_ctx: dict | None = None) -> QueryTrace:
        """Start a trace. ``parent_ctx`` defaults to the AMBIENT
        distributed context (tracectx.current(), bound by the bus
        dispatcher that delivered the triggering message) — so a
        fragment executed inside an agent handler automatically joins
        the broker's trace without explicit plumbing."""
        if parent_ctx is None:
            parent_ctx = tracectx.current()
        tr = QueryTrace(
            self, script=script, analyze=analyze, kind=kind,
            parent_ctx=parent_ctx,
        )
        with self._lock:
            # Keyed by root span id, not trace id: N fragments of one
            # distributed query SHARE a trace id but are distinct
            # in-flight entries.
            self._inflight[tr.root.span_id] = tr
        return tr

    def end_query(self, trace: QueryTrace, status: str = "ok",
                  error: str = "") -> None:
        """Finalize a trace: seal spans, move it to the ring, record
        metrics, run the slow-query log and the OTLP export. Idempotent
        (a second end is a no-op) so both StreamingQuery.run's finally
        and an explicit close() can call it."""
        with self._lock:
            if self._inflight.pop(trace.root.span_id, None) is None:
                return  # already ended (or foreign trace)
        trace._finalize(status, error)
        m = self._m()
        with self._lock:
            # Ring-drop accounting (satellite): an evicted trace that
            # never made it out over OTLP is telemetry LOST — count it
            # so operators can size trace_ring_size / wire an exporter.
            if (
                self._ring.maxlen is not None
                and len(self._ring) == self._ring.maxlen
                and self._ring
                and not self._ring[0].exported
            ):
                m["dropped"].inc()
            self._ring.append(trace)
        m["queries"].labels(status=status).inc()
        m["duration"].labels(status=status).observe(trace.duration_s)
        u = trace.usage
        m["bytes_staged"].observe(u.bytes_staged)
        m["device_ms"].observe(u.device_ms)
        m["wire_bytes"].observe(u.wire_bytes)
        if trace.pipeline:
            m["stall"].observe(trace.pipeline.get("stall_secs", 0.0))
        self._slow_query_check(trace, m)
        self._export(trace, m)
        self._notify(trace)

    def _notify(self, trace: QueryTrace) -> None:
        if self._closed:
            return
        for fn in list(self._listeners):
            try:
                fn(trace)
            except Exception:
                # A broken telemetry consumer must never fail queries.
                logging.getLogger("pixie_tpu.trace").warning(
                    "trace listener %r failed", fn, exc_info=True
                )

    def _slow_query_check(self, trace: QueryTrace, m: dict) -> None:
        thresh_ms = float(get_flag("slow_query_threshold_ms"))
        if thresh_ms <= 0 or trace.duration_s * 1e3 < thresh_ms:
            return
        m["slow"].inc()
        logger.warning(
            "slow query (%.1fms > %.1fms): %s",
            trace.duration_s * 1e3, thresh_ms,
            json.dumps(trace.to_dict(), default=str),
        )

    def _export(self, trace: QueryTrace, m: dict) -> None:
        url = str(get_flag("trace_export_url"))
        if not url or self._closed:
            return
        if self._exporter is None or self._exporter_url != url:
            from .otel import OTLPHttpExporter

            self._exporter = OTLPHttpExporter(url)
            self._exporter_url = url
        try:
            self._exporter(trace.to_otlp())
            trace.exported = True
        except Exception:
            # Telemetry must never fail the query; the counter is the
            # operator's signal that the collector is down. A shutdown
            # racing a slow in-flight push lands here too (socket torn
            # down mid-POST) — counted, not raised.
            m["export_errors"].inc()

    # -- accessors (the /debug/queryz surface) -------------------------------
    def in_flight(self) -> list:
        with self._lock:
            traces = sorted(
                self._inflight.values(), key=lambda t: t.start_unix_nano
            )
        return [t.to_dict() for t in traces]

    def recent(self) -> list:
        with self._lock:
            traces = list(self._ring)
        return [t.to_dict() for t in reversed(traces)]

    def get(self, trace_id: str) -> QueryTrace | None:
        with self._lock:
            for t in self._inflight.values():
                if t.trace_id == trace_id:
                    return t
            for t in self._ring:
                if t.trace_id == trace_id:
                    return t
        return None

    def last(self) -> QueryTrace | None:
        """Most recently finished trace (None if the ring is empty)."""
        with self._lock:
            return self._ring[-1] if self._ring else None


def plan_script(plan) -> str:
    """Stable pseudo-script for direct ``execute_plan`` calls (no PxL
    source): the op-type chain in topo order, so equal plans share a
    script hash in /debug/queryz."""
    try:
        ops = [type(plan.nodes[nid].op).__name__ for nid in plan.topo_order()]
    except Exception:
        return "<plan>"
    return "plan:" + ">".join(ops)
