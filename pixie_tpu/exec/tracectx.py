"""Distributed trace-context propagation (the W3C traceparent analog).

One distributed query is ONE trace: the broker's dispatch span is the
parent of every agent-side fragment/merge span. The context that makes
that stitching possible is a tiny envelope — ``{"trace_id", "span_id"}``
— carried two ways:

- **in-band**: ``attach(msg, ctx)`` stamps the envelope into a bus
  message under ``_trace_ctx`` (wire-codec friendly: a dict of two hex
  strings), and ``extract(msg)`` validates + reads it back;
- **ambient**: the bus subscription dispatchers (``services/msgbus.py``,
  ``services/netbus.py``) bind an extracted context around each handler
  invocation via a ``contextvars.ContextVar``, so anything the handler
  does — including ``Engine.execute_plan`` beginning a query trace —
  inherits the distributed parent without explicit plumbing, and
  ``MessageBus.publish`` re-stamps it onto nested publishes (a data
  agent's bridge chunks carry its fragment context to the merge agent).

``Tracer.begin_query`` defaults its ``parent_ctx`` to ``current()``, so
a fragment executed inside a bus handler automatically parents under
the broker's dispatch span. Contexts are validated (32-hex trace id,
16-hex span id) — a malformed envelope is ignored, never raised.
"""

from __future__ import annotations

import contextlib
import contextvars

#: Message key the envelope rides under (dict of two hex strings; the
#: wire codec carries it unchanged across the netbus).
TRACE_CTX_KEY = "_trace_ctx"

_current: contextvars.ContextVar = contextvars.ContextVar(
    "pixie_trace_ctx", default=None
)


def _is_hex(s, n: int) -> bool:
    if not isinstance(s, str) or len(s) != n:
        return False
    try:
        int(s, 16)
    except ValueError:
        return False
    return True


def valid(ctx) -> bool:
    """True when ``ctx`` is a well-formed context envelope."""
    return (
        isinstance(ctx, dict)
        and _is_hex(ctx.get("trace_id"), 32)
        and _is_hex(ctx.get("span_id"), 16)
    )


def make(trace_id: str, span_id: str) -> dict:
    return {"trace_id": trace_id, "span_id": span_id}


def current() -> dict | None:
    """The ambient context bound by the enclosing bus dispatch (None
    outside any distributed trace)."""
    return _current.get()


@contextlib.contextmanager
def bound(ctx):
    """Bind ``ctx`` as the ambient context for the dynamic extent of the
    block (token-reset on exit, so dispatcher threads never leak a stale
    context into the next message). ``None``/invalid binds nothing.

    Also registers the context in the profiler's thread attribution map
    (``exec/threadmap.py``) so CPU samples taken inside a bus handler
    carry at least the distributed trace id."""
    from . import threadmap

    ctx = ctx if valid(ctx) else None
    token = _current.set(ctx)
    tm_token = threadmap.bind(ctx=ctx) if ctx is not None else None
    try:
        yield
    finally:
        threadmap.unbind(tm_token)
        _current.reset(token)


def attach(msg: dict, ctx=None) -> dict:
    """Return ``msg`` with the context envelope stamped in (a copy when
    stamping — publishers share message dicts across retries). ``ctx``
    defaults to the ambient context; an existing envelope is preserved
    (the originator's stamp wins over relay ambience)."""
    if TRACE_CTX_KEY in msg:
        return msg
    ctx = ctx if ctx is not None else current()
    if not valid(ctx):
        return msg
    return {**msg, TRACE_CTX_KEY: dict(ctx)}


def extract(msg) -> dict | None:
    """Read a validated context envelope out of a bus message."""
    ctx = msg.get(TRACE_CTX_KEY) if isinstance(msg, dict) else None
    return dict(ctx) if valid(ctx) else None
