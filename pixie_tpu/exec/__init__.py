from .engine import Engine, QueryError
from .trace import QueryTrace, Tracer
from .plan import (
    AggExpr,
    AggOp,
    ColumnRef,
    FilterOp,
    FuncCall,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
    UnionOp,
)

__all__ = [
    "Engine",
    "QueryError",
    "QueryTrace",
    "Tracer",
    "Plan",
    "MemorySourceOp",
    "MapOp",
    "FilterOp",
    "AggOp",
    "AggExpr",
    "JoinOp",
    "LimitOp",
    "UnionOp",
    "ResultSinkOp",
    "ColumnRef",
    "Literal",
    "FuncCall",
]
