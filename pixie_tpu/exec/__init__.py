from .engine import Engine, QueryError
from .plan import (
    AggExpr,
    AggOp,
    ColumnRef,
    FilterOp,
    FuncCall,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
    UnionOp,
)

__all__ = [
    "Engine",
    "QueryError",
    "Plan",
    "MemorySourceOp",
    "MapOp",
    "FilterOp",
    "AggOp",
    "AggExpr",
    "JoinOp",
    "LimitOp",
    "UnionOp",
    "ResultSinkOp",
    "ColumnRef",
    "Literal",
    "FuncCall",
]
