"""Agent-mode bridge payloads + merge: partial-agg state shipping.

Reference parity: GRPCSinkNode/GRPCSourceNode pairs plus the UDA
``Serialize``/``DeSerialize`` contract (``src/carnot/exec/
grpc_sink_node.h:54``, ``udf/udf.h:99-100``). The TPU redesign ships the
fragment's carry pytree itself — the merge tier recompiles the identical
fragment and folds states through its associative merge, instead of
streaming serialized row batches.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.dtypes import DataType
from ..types.strings import NULL_ID, StringDictionary
from .fragment import ColumnMeta, compile_fragment_cached as compile_fragment
from .plan import AggOp
from .stream import (
    QueryError,
    _double_agg_groups,
    _Stream,
    _stream_col_stats,
    _to_host_batch,
)


@dataclass
class AggStatePayload:
    """Partial-agg state shipped across a bridge (agent mode).

    The UDA ``Serialize``/``DeSerialize`` analog (``udf.h:99-100``): the
    serialized form IS the carry pytree plus enough metadata for the
    merge tier to recompile the identical fragment and realign string
    dictionary ids. String-valued *carries* (e.g. ``any`` over a string
    column) are not realigned — only group keys are; such UDAs need a
    shared dictionary to cross agents.
    """

    chain: tuple  # fragment ops [pre..., AggOp]
    input_relation: object  # Relation at fragment input
    input_dicts: dict  # {col: StringDictionary} at fragment input
    state: dict  # group-state pytree (numpy leaves)
    # Dense-domain states ship no key planes (slot index IS the packed
    # key); the producing fragment's domains let the merge side expand
    # them back to explicit keys (dictionaries may differ per agent).
    # ``dense_offsets`` shifts stats-derived integer codes back to values;
    # ``dense_strides`` scales step-indexed codes (binned time keys).
    dense_domains: tuple = ()
    dense_offsets: tuple = ()
    dense_strides: tuple = ()


@dataclass
class RowsPayload:
    """Materialized rows shipped across a bridge (plain GRPCSink analog)."""

    batch: HostBatch


@dataclass
class _PendingAggBridge:
    """Agg-bridge payloads awaiting their finalize AggOp."""

    payloads: list  # list[AggStatePayload]


def _expand_dense_payload(p, group_rel, key_plane_index):
    """Expand a dense-domain AggStatePayload to explicit key planes.

    Dense states carry no keys (slot index IS the packed key); the merge
    tier reconstructs them with the same unpack arithmetic the producing
    fragment's finalize uses, so the generic realign/merge path applies.
    """
    import dataclasses

    from .fragment import unpack_dense_slots

    doms = getattr(p, "dense_domains", ())
    if not doms:
        return p
    gd = len(p.state["valid"])
    keys = unpack_dense_slots(
        np.arange(gd, dtype=np.int64),
        doms,
        [group_rel.col_type(c) for c, _i in key_plane_index],
        np,
        offsets=getattr(p, "dense_offsets", ()),
        strides=getattr(p, "dense_strides", ()),
    )
    return dataclasses.replace(
        p, state={**p.state, "keys": tuple(keys)}, dense_domains=(),
        dense_offsets=(), dense_strides=(),
    )


def _compact_payload(p):
    """Shrink an expanded dense-domain payload to its live slots.

    A dense state is domain-sized (up to ``dense_domain_limit`` slots)
    however few groups are live; merging every payload at that capacity
    is a large avoidable cost for small aggregates. Live slots compact to
    the front (padded to a power-of-two bucket with neutral invalid
    slots, so merge-fragment compiles stay shape-bucketed).
    """
    import dataclasses

    import jax

    valid = np.asarray(p.state["valid"])
    g = len(valid)
    live = int(valid.sum())
    cap = bucket_capacity(max(live, 1))
    if cap >= g:
        return p
    idx = np.nonzero(valid)[0]
    if len(idx) < cap:
        # Invalid slots hold uda-neutral carries by construction, so any
        # one of them is safe padding.
        fill = int(np.nonzero(~valid)[0][0])
        idx = np.concatenate(
            [idx, np.full(cap - len(idx), fill, dtype=np.int64)]
        )

    def take(leaf):
        a = np.asarray(leaf)
        return a[idx] if a.ndim and a.shape[0] == g else a

    return dataclasses.replace(p, state={
        "keys": tuple(take(k) for k in p.state["keys"]),
        "valid": valid[idx],
        "carries": jax.tree_util.tree_map(take, p.state["carries"]),
        "overflow": p.state["overflow"],
    })


def payload_nbytes(p) -> int:
    """Approximate wire size of one bridge payload: the plane bytes the
    transport actually moves (metadata/dicts excluded). Host numpy
    arithmetic only — feeds ``QueryResourceUsage.wire_bytes``."""
    if isinstance(p, RowsPayload):
        return p.batch.nbytes
    if isinstance(p, AggStatePayload):
        import jax

        return int(sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(p.state)
        ))
    return 0


def bridge_payload(engine, res):
    """Produce a BridgeSink payload: partial-agg state for agg chains,
    materialized rows otherwise (GRPCSinkNode's two modes)."""
    if isinstance(res, _Stream) and any(
        isinstance(o, AggOp) for o in res.chain
    ):
        import jax

        # The agent-mode agg fold records onto the query's trace spine
        # like any other fragment (rows/windows/stage/compute feed the
        # per-agent QueryResourceUsage attribution).
        qstats = getattr(engine, "_query_stats", None)
        while True:
            frag = compile_fragment(
                res.chain, res.relation, res.dicts, engine.registry,
                col_stats=_stream_col_stats(res),
            )
            stats = (
                qstats.new_fragment(res.chain) if qstats is not None
                else None
            )
            state = engine._fold_agg_state(res, frag, stats)
            if not bool(np.asarray(state["overflow"])):
                break
            res = _double_agg_groups(res)  # rebucket before shipping
        return AggStatePayload(
            chain=tuple(res.chain),
            input_relation=res.relation,
            input_dicts=dict(res.dicts),
            state=jax.tree_util.tree_map(np.asarray, state),
            dense_domains=frag.dense_domains,
            dense_offsets=frag.dense_offsets,
            dense_strides=frag.dense_strides,
        )
    return RowsPayload(batch=engine._materialize(res))


def bind_bridge(payloads):
    from .joins import _union_host

    payloads = payloads if isinstance(payloads, list) else [payloads]
    if not payloads:
        raise QueryError("bridge received no payloads")
    if all(isinstance(p, RowsPayload) for p in payloads):
        return _union_host([p.batch for p in payloads])
    if all(isinstance(p, AggStatePayload) for p in payloads):
        return _PendingAggBridge(payloads)
    raise QueryError("mixed payload kinds on one bridge")


def merge_agg_bridge(engine, pending: _PendingAggBridge) -> HostBatch:
    """Merge shipped partial-agg states and finalize.

    The agent-mode replacement for the on-mesh collective: states from
    k agents fold through the fragment's associative merge, after the
    group-key string ids of every agent are remapped into one
    canonical dictionary (the reference ships raw strings over GRPC,
    so alignment is implicit there; here ids must be reconciled).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp

    from .fragment import _bind_pre_stage, _split_chain
    from ..types.dtypes import device_dtypes

    p0 = pending.payloads[0]
    # The merge fragment is compiled WITHOUT dense mode: agents encode
    # against their own dictionaries, so dense slot spaces are not
    # comparable across payloads — expand each dense state to explicit
    # key planes (then compact to live slots: a dense state is
    # domain-sized regardless of how few groups are live, and the
    # merge must not inherit that capacity) and realign through the
    # generic (sort-space) path. The group relation / key planes come
    # from binding the pre-stage directly — no compile needed before
    # the payload sizes are known.
    pre0, agg0, _post0, _limit0 = _split_chain(list(p0.chain))
    _, rel1, _ = _bind_pre_stage(
        pre0, p0.input_relation, dict(p0.input_dicts), engine.registry
    )
    key_plane_index = tuple(
        (c, i)
        for c in agg0.group_cols
        for i in range(len(device_dtypes(rel1.col_type(c))))
    )
    group_rel = rel1
    pending = _PendingAggBridge(payloads=[
        _compact_payload(_expand_dense_payload(p, rel1, key_plane_index))
        for p in pending.payloads
    ])
    p0 = pending.payloads[0]
    # Merge at the largest payload capacity (smaller states pad with
    # neutral slots below); overflow rebucketing grows it if the
    # union of live groups spills.
    g = max(
        op.max_groups
        for p in pending.payloads
        for op in p.chain
        if isinstance(op, AggOp)
    )
    g = max([g] + [len(p.state["valid"]) for p in pending.payloads])
    chain = [
        dataclasses.replace(op, max_groups=g) if isinstance(op, AggOp) else op
        for op in p0.chain
    ]
    frag = compile_fragment(
        chain, p0.input_relation, dict(p0.input_dicts), engine.registry,
        allow_dense=False,
    )
    if frag.string_carry_sources and len(pending.payloads) > 1:
        # String ids inside a CARRY (not a group key) cannot be
        # realigned after the fact; reject unless every agent encoded
        # from the very same dictionary objects (keys only are realigned
        # here — reference ships raw strings over GRPC instead).
        for out_name, src_cols in frag.string_carry_sources:
            for c in src_cols:
                d0 = pending.payloads[0].input_dicts.get(c)
                s0 = list(d0.strings) if d0 is not None else None
                for p in pending.payloads[1:]:
                    d = p.input_dicts.get(c)
                    same = (
                        d is d0
                        or (d is not None and s0 is not None
                            and list(d.strings) == s0)
                    )
                    if not same:
                        raise QueryError(
                            f"aggregate {out_name!r} carries string ids "
                            f"of column {c!r} across agents whose "
                            "dictionaries disagree; results would be "
                            "garbage. Share one dictionary or aggregate "
                            "after merge."
                        )
    # Per-agent post-pre-stage dictionaries for the group columns.
    per_agent_dicts = []
    for p in pending.payloads:
        _, rel1_a, dicts1 = _bind_pre_stage(
            pre0, p.input_relation, dict(p.input_dicts), engine.registry
        )
        if tuple(rel1_a.items()) != tuple(group_rel.items()):
            raise QueryError(
                f"bridge schema mismatch: {rel1_a} vs {group_rel}"
            )
        per_agent_dicts.append(dicts1)
    # Canonical dictionary + id remap per string group column.
    canonical: dict[str, StringDictionary] = {}
    states = []
    for p, dicts1 in zip(pending.payloads, per_agent_dicts):
        keys = list(p.state["keys"])
        for pi, (c, i) in enumerate(key_plane_index):
            if group_rel.col_type(c) != DataType.STRING or i != 0:
                continue
            src = dicts1.get(c)
            if src is None:
                continue
            dst = canonical.setdefault(c, StringDictionary())
            remap = np.fromiter(
                (dst.get_or_add(s) for s in src.strings),
                dtype=np.int32,
                count=len(src),
            )
            ids = np.asarray(keys[pi])
            if len(remap) == 0:
                # Empty dictionary (agent had no rows): every slot is
                # already the null id — nothing to remap.
                keys[pi] = np.full_like(ids, NULL_ID, dtype=np.int32)
            else:
                keys[pi] = np.where(
                    ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID
                ).astype(np.int32)
        if bool(np.asarray(p.state["overflow"])):
            # Lost groups at the source cannot be recovered here; the
            # producing agent rebuckets before shipping (bridge_payload).
            raise QueryError(
                "bridge payload arrived with group overflow; producing "
                "agent failed to rebucket"
            )
        states.append({**p.state, "keys": tuple(keys)})
    while True:
        # Pad smaller states into g neutral slots, fold-merge, and on
        # merged-distinct overflow double g and retry from the (still
        # intact) original states.
        init = frag.init_state()

        def pad(a, i):
            a = jnp.asarray(a)
            if a.ndim == 0 or a.shape[0] >= i.shape[0]:
                return a
            return jnp.concatenate([a, i[a.shape[0]:]])

        merge = jax.jit(frag.merge_states)
        padded = [jax.tree_util.tree_map(pad, s, init) for s in states]
        acc = padded[0]
        for s in padded[1:]:
            acc = merge(acc, s)
        cols, valid, overflow = frag.finalize(acc)
        if not bool(overflow):
            break
        from ..config import get_flag

        if g * 2 > get_flag("max_groups_limit"):
            raise QueryError(
                f"group-by overflow merging bridge states at "
                f"max_groups={g}; rebucketing past the "
                f"{get_flag('max_groups_limit')} cap refused "
                "(PIXIE_TPU_MAX_GROUPS_LIMIT)"
            )
        g *= 2
        chain = [
            dataclasses.replace(op, max_groups=g)
            if isinstance(op, AggOp)
            else op
            for op in chain
        ]
        frag = compile_fragment(
            chain, p0.input_relation, dict(p0.input_dicts), engine.registry,
            allow_dense=False,  # states carry explicit key planes
        )
    meta = [
        (
            ColumnMeta(m.name, m.dtype, dict=canonical[m.name])
            if m.name in canonical
            else m
        )
        for m in frag.out_meta
    ]
    return _to_host_batch(meta, cols, np.asarray(valid))
