"""Incremental materialized views: hot scripts answered as
finalize-over-state instead of a full rescan.

The streaming cursor (``exec/streaming.py``) already IS incremental
view maintenance — a blocking aggregate's group state persists across
polls and each poll folds only the windows appended since the last
one. This module wraps one :class:`StreamingQuery` per registered hot
script as a :class:`MaterializedView`: a dashboard repeat triggers one
``poll()`` (folding only the NEW ingest windows — O(new data), not
O(data)) and is answered from the captured ``mode="replace"`` batch.

Registration is manifest opt-in (``materialize: true`` in a bundled
script's ``manifest.yaml``) plus an observed-frequency heuristic: a
script executed at least ``view_auto_min_runs`` times (live run counts
seeded from the ``ObservedCostIndex``/telemetry ``runs`` history — the
arXiv:2102.02440 feedback loop steering what to materialize)
auto-registers. 0 disables auto-registration.

Correctness properties, tested in ``tests/test_result_cache.py``:

- **bit-identity** — a view answer equals the full one-shot rescan at
  the same ``now``: same fragment update path, same window order, same
  finalize.
- **rebucket survival** — group overflow recompiles at doubled
  capacity and refolds from the source start (StreamingQuery's
  ``_rebucket``); the next answer is still exact.
- **expiry churn** — ring expiry crossing the state's fold-start mark
  refolds from the live rows (``StreamingQuery._fold_new``), so the
  view never keeps counting rows a rescan would no longer see.

A view registered at time T serves time-windowed scripts (relative
``start_time``) only while the requested ``now`` stays within the
script's staleness budget of T (same budget source as the result
cache); past it the view re-registers at the new ``now`` — one full
refold, then incremental again. Views expose their own freshness
(source-table watermark lag at answer time) and show up in
``/debug/cachez``.
"""

from __future__ import annotations

import threading
import time

from ..config import get_flag
from .engine import QueryError
from .result_cache import manifest_budgets, script_sha

_MAT_LOCK = threading.Lock()
_MAT_CACHE: set | None = None


def manifest_materialized() -> set:
    """sha256(pxl) of every bundled script opting in via
    ``materialize: true`` — loaded once per process."""
    global _MAT_CACHE
    with _MAT_LOCK:
        if _MAT_CACHE is None:
            shas: set = set()
            try:
                from ..scripts import load_all

                for sd in load_all():
                    if sd.manifest.get("materialize"):
                        shas.add(script_sha(sd.pxl))
            except Exception:
                pass  # no script library: heuristic-only registration
            _MAT_CACHE = shas
        return _MAT_CACHE


def view_candidates_enabled(query: str) -> bool:
    """Cheap pre-gate for the engine's execute path: views are in play
    only when auto-registration is on, or when the repeat-serving tier
    (``result_cache_mb``) is enabled AND this script text opted in via
    its manifest — a manifest ``materialize: true`` is a hint that only
    activates with the tier, so the all-flags-default path stays
    byte-for-byte the plain execute path. Costs one/two flag reads +
    (at most) one sha per query."""
    if "pxtrace" in query:
        return False
    if int(get_flag("view_auto_min_runs")) > 0:
        return True
    if int(get_flag("result_cache_mb")) <= 0:
        return False
    mats = manifest_materialized()
    return bool(mats) and script_sha(query) in mats


class MaterializedView:
    """One continuously maintained view: a StreamingQuery over an
    aggregate chain + the latest captured finalize batch."""

    def __init__(self, engine, script: str, now_ns: int = 0,
                 max_output_rows: int = 10_000):
        from .streaming import stream_query

        self.script = script
        self.sha = script_sha(script)
        self.now_ns = int(now_ns) or time.time_ns()
        self.max_output_rows = int(max_output_rows)
        self.registered_unix_ns = time.time_ns()
        self._last: dict = {}
        self._lock = threading.Lock()
        self.answers = 0
        self.sq = stream_query(
            engine, script, emit=self._capture,
            now_ns=self.now_ns, max_output_rows=self.max_output_rows,
        )
        if not self.sq.chain.is_agg:
            self.sq.close()
            raise QueryError(
                "only aggregate chains materialize: an append stream "
                "has no finalize-over-state to answer from"
            )

    def _capture(self, update) -> None:
        if update.mode == "replace":
            self._last[update.table] = update.batch

    @property
    def time_dependent(self) -> bool:
        return self.sq.chain.source.start_time is not None

    def freshness_lag_ms(self) -> float:
        """How far the view's source table trails the clock right now
        (its own freshness surface; 0 = fresh / no time index)."""
        from ..table_store import table as _table_mod

        wm = _table_mod.max_watermark_ns(self.sq.tablets)
        if wm is None:
            return 0.0
        return max(0.0, round((time.time_ns() - wm) / 1e6, 3))

    def answer(self) -> dict:
        """Fold the windows appended since the last answer (O(new
        data)) and return {sink: HostBatch} — the finalize-over-state
        result a full rescan would have recomputed."""
        with self._lock:
            self.sq.poll()
            self.answers += 1
            return dict(self._last)

    def close(self) -> None:
        self.sq.close()

    def to_dict(self) -> dict:
        return {
            "script_hash": self.sha[:12],
            "table": self.sq.chain.source.table,
            "sink": self.sq.chain.sink_name,
            "time_dependent": self.time_dependent,
            "max_output_rows": self.max_output_rows,
            "answers": self.answers,
            "polls": self.sq.seq,
            "registered_unix_ns": self.registered_unix_ns,
            "freshness_lag_ms": self.freshness_lag_ms(),
        }


class ViewRegistry:
    """Per-engine registry: run counting, manifest/heuristic
    registration, drift re-registration, and the answer path."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.RLock()
        self._views: dict = {}   # (sha, max_output_rows) -> view
        self._runs: dict = {}    # sha -> live run count (this process)
        self._failed: set = set()  # shas that cannot stream — don't retry

    # -- registration --------------------------------------------------------
    def _observed_runs(self, sha: str) -> int:
        """Telemetry-seeded run history (ObservedCostIndex ``runs`` per
        short script hash): the observed-frequency heuristic counts
        past sessions' repeats, not just this process's."""
        tel = getattr(self.engine, "telemetry", None)
        if tel is None:
            return 0
        try:
            return int((tel.observed().get(sha[:12]) or {}).get("runs", 0))
        except Exception:
            return 0

    def _should_register(self, sha: str) -> bool:
        if sha in manifest_materialized():
            return True
        min_runs = int(get_flag("view_auto_min_runs"))
        if min_runs <= 0:
            return False
        return (
            self._runs.get(sha, 0) + self._observed_runs(sha) >= min_runs
        )

    def register(self, query: str, now_ns: int = 0,
                 max_output_rows: int = 10_000) -> MaterializedView:
        """Explicit registration (tests / ops); raises QueryError when
        the script is not streamable (joins, unions, bounded sources)."""
        sha = script_sha(query)
        v = MaterializedView(
            self.engine, query, now_ns=now_ns,
            max_output_rows=max_output_rows,
        )
        with self._lock:
            old = self._views.pop((sha, int(max_output_rows)), None)
            self._views[(sha, int(max_output_rows))] = v
        if old is not None:
            old.close()
        return v

    # -- the execute-path hook -----------------------------------------------
    def serve(self, query: str, now_ns: int = 0,
              max_output_rows: int = 10_000, trace=None):
        """Answer ``query`` from a registered view, registering one
        first when the manifest/heuristic says so. None = no view
        covers this query; execute normally."""
        from .result_cache import ResultCache

        sha = script_sha(query)
        key = (sha, int(max_output_rows))
        req_now = int(now_ns) or time.time_ns()
        with self._lock:
            self._runs[sha] = self._runs.get(sha, 0) + 1
            v = self._views.get(key)
            if v is None:
                if sha in self._failed or not self._should_register(sha):
                    return None
                try:
                    v = self.register(
                        query, now_ns=now_ns,
                        max_output_rows=max_output_rows,
                    )
                except QueryError:
                    # Not streamable (joins/unions/bounded sources):
                    # remember, so every later repeat skips the compile.
                    self._failed.add(sha)
                    return None
            elif v.time_dependent:
                budget_ms = ResultCache.staleness_budget_ms(sha)
                if (req_now - v.now_ns) / 1e6 > budget_ms:
                    # The requested window drifted past the budget:
                    # re-register at the new now — one full refold,
                    # then incremental again.
                    try:
                        v = self.register(
                            query, now_ns=req_now,
                            max_output_rows=max_output_rows,
                        )
                    except QueryError:
                        self._failed.add(sha)
                        return None
        result = v.answer()
        if trace is not None:
            trace.note_freshness_lag(
                v.sq.chain.source.table, v.freshness_lag_ms()
            )
        return result

    # -- introspection (/debug/cachez "views" section) -----------------------
    def viewz(self) -> list:
        with self._lock:
            views = list(self._views.values())
        return [v.to_dict() for v in views]

    def close(self) -> None:
        with self._lock:
            views = list(self._views.values())
            self._views.clear()
        for v in views:
            v.close()
