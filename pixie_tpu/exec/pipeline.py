"""Pipelined window executor: overlap host staging with device compute.

The serial window loop stages (host slice -> pack -> ``device_put``)
window N and only then dispatches compute for it, so the host idles
during compute and the device idles during staging. This module runs the
staging generator on a background *prefetch thread* while the consumer
computes, with a bounded number of windows in flight — the standard
near-data-execution overlap lever, and on TPU (where each host->device
transfer costs a tunnel round trip) the difference between a stalled and
a saturated device.

Design:

- The producer thread pulls from the underlying staged-window generator
  (which performs all the staging work — for device-cache-resident
  windows that work is ~zero and the prefetcher degenerates to a cheap
  hand-off) and enqueues items.
- A semaphore with ``depth`` permits bounds in-flight windows: the
  producer acquires a permit *before* staging the next window; the
  consumer releases it only after it finishes computing that window.
  ``depth=1`` disables the thread entirely (bit-for-bit the serial
  executor).
- Errors raised during background staging are captured and re-raised in
  the consumer with the original traceback — a staging failure is the
  query's failure, never a hang or a secondary ``queue.Empty``.
- ``close()`` is idempotent and *always* joins the prefetch thread and
  drains staged-but-unconsumed device buffers; every consumer wraps its
  loop in try/finally so cancellation, limits, and compute errors can
  never leak a thread or touch a buffer after cancel.

Instrumentation: the pipeline tracks ``windows``, ``stage_secs``
(producer time spent staging), and ``stall_secs`` (consumer time blocked
waiting for a window). The per-window stall intervals also land in the
query's fragment stats (stage ``"stall"``) — always on since the trace
spine (``trace.py``) passes stats for every query, feeding the
``pixie_window_stage_seconds{stage="stall"}`` histogram and sampled
``window.stall`` spans; engines accumulate per-query and lifetime
totals for bench.py's overlap report and the observability gauges.
"""

from __future__ import annotations

import queue
import threading
import time

from . import threadmap

#: Poll period for interruptible blocking waits (slot acquire / queue
#: get). Bounds how long cancellation/teardown can lag, not throughput —
#: steady-state hand-offs never hit the timeout.
_POLL_S = 0.05

#: Hot regions of the per-window execution path, registered for the
#: ``host-sync-hot-path`` lint (pixie_tpu/analysis/lint.py): a host
#: sync inside any of these runs once PER WINDOW, serializing the
#: prefetch overlap this module exists to provide (and costing a full
#: tunnel round trip per call on TPU). Entries are
#: "path-suffix:qualname-glob"; the lint engine reads this assignment
#: statically.
PXLINT_HOT_REGIONS = (
    "exec/pipeline.py:WindowPipeline*",
    "exec/engine.py:Engine._fold_agg_state",
    "exec/engine.py:Engine._fold_agg_state_native",
    "exec/engine.py:Engine._staged_windows*",
    "exec/engine.py:Engine._windows",
    "exec/engine.py:Engine._stage",
    # Windowed device-join drivers: their per-window loops ride the same
    # prefetch pipeline; an unjustified host sync there serializes the
    # probe stream exactly like one in the fold loops.
    "exec/joins.py:_join_device_windowed*",
    # Telemetry fold (services/telemetry.py): runs in Tracer.end_query
    # on the query thread right after the exec guard releases — a host
    # sync there would serialize the NEXT query behind telemetry
    # bookkeeping, so the fold must stay pure host-list arithmetic.
    "services/telemetry.py:TelemetryCollector*",
    "services/telemetry.py:ClusterTraceView*",
    # Storage-tier fold (__tables__): runs per finished trace on the
    # query thread and per heartbeat — host-counter arithmetic only.
    "services/telemetry.py:TableStatsCollector*",
    # Resource accounting on the trace spine: _finalize_usage and the
    # per-window stage/add paths run per query/window with the same
    # no-sync contract.
    "exec/trace.py:QueryTrace._finalize_usage",
    "exec/trace.py:TracedFragment.add",
    # Program registry (exec/programs.py): TrackedProgram.__call__ runs
    # once per tracked dispatch (i.e. per window) and the registry's
    # lookup/record/drain paths run under its lock — a host sync in any
    # of them would serialize every fold loop in the process. The
    # device-memory query brackets run per query with the same
    # contract (memory_stats() is a host call, not a device fence).
    "exec/programs.py:TrackedProgram*",
    "exec/programs.py:ProgramRegistry*",
    "exec/programs.py:DeviceMemoryMonitor*",
    # Profiling tier: the 100Hz sampler and the thread attribution
    # registry it reads. A host sync (or any blocking call) inside the
    # sample/fold path stalls EVERY thread's profile and turns the
    # profiler into a periodic global pause; the attribution reads are
    # GIL-atomic dict gets by design — keep them that way.
    "ingest/profiler.py:PerfProfilerConnector*",
    "ingest/profiler.py:_fold_stack",
    "exec/threadmap.py:*",
    # Transport tier: publish/deliver stamping runs on EVERY bus
    # message (dispatch, acks, partials, heartbeats) on the
    # publisher's and dispatcher's threads, and the __bus__ fold runs
    # per heartbeat — host-counter arithmetic only; a host sync here
    # would serialize the whole message path.
    "services/msgbus.py:Subscription._deliver",
    "services/msgbus.py:Subscription._run",
    "services/msgbus.py:MessageBus.publish",
    "services/msgbus.py:MessageBus._fanout",
    "services/busstats.py:BusStats*",
    "services/telemetry.py:BusStatsCollector*",
    # Storage tier (ISSUE 20): cold-window decode runs on the prefetch
    # thread once per staged window, and the zone-map pruner + the
    # tier-merged read path run per window on the scan spine — pure
    # numpy/host arithmetic; a host sync in any of them stalls the
    # decode-on-stage overlap exactly like one in WindowPipeline.
    "table_store/coldstore.py:EncodedPlane.decode",
    "table_store/coldstore.py:ColdStore._decode_window",
    "table_store/coldstore.py:ColdStore.read",
    "table_store/table.py:Table.read_rows",
    "exec/zoneskip.py:make_pruner*",
    "exec/zoneskip.py:chain_pruner*",
)


class DeadlineEvent:
    """Event-like cancel handle that also trips at an absolute
    wall-clock deadline (``time.time()`` seconds).

    The cooperative-cancellation seam polls ``cancel.is_set()`` at
    every window boundary (``Engine._check_cancel`` and
    :meth:`WindowPipeline._check_cancel`, which also polls every
    ``_POLL_S`` while blocked), so wrapping a query's cancel event in
    one of these makes an expired deadline abort the query between
    windows — dead work is dropped within one window boundary instead
    of computed to completion. Wall-clock (not monotonic) because the
    deadline is stamped by the BROKER and rides the dispatch message
    across processes; agents and broker are assumed loosely
    clock-synced (the same assumption the tracker's heartbeat expiry
    already makes).
    """

    __slots__ = ("_event", "deadline_unix_s")

    def __init__(self, event, deadline_unix_s: float):
        self._event = event
        self.deadline_unix_s = float(deadline_unix_s)

    def set(self) -> None:
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set() or self.deadline_exceeded()

    def deadline_exceeded(self) -> bool:
        return time.time() >= self.deadline_unix_s


class WindowPipeline:
    """Bounded-depth prefetch over a staged-window generator.

    Iterate it exactly once; call :meth:`close` when done (iteration
    wrapped in try/finally — see module docstring). ``cancel`` is an
    optional ``threading.Event``-like object polled on both sides;
    when set, iteration raises ``QueryCancelled``.
    """

    def __init__(self, gen, depth: int, cancel=None, stats=None):
        self._gen = gen
        self.depth = max(1, int(depth))
        self._cancel = cancel
        self._stats = stats
        self.windows = 0
        self.stage_secs = 0.0
        self.stall_secs = 0.0
        self._slots = threading.Semaphore(self.depth)
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._iterated = False
        # Profiler attribution: the prefetch thread does the creating
        # query's staging work, so it inherits the creator's entry
        # (rebound with phase "stage" in _produce) — otherwise its CPU
        # samples would show up unattributed.
        self._owner_entry = threadmap.current_entry()

    # -- consumer side -------------------------------------------------------
    def __iter__(self):
        if self._iterated:
            raise RuntimeError("WindowPipeline is single-use")
        self._iterated = True
        if self.depth <= 1:
            # Serial mode: no thread, no queue — today's loop, but the
            # cancel handle is still polled per window so generators
            # without their own check (e.g. the windowed join driver)
            # keep the both-sides cancellation contract.
            for item in self._gen:
                self._check_cancel()
                self.windows += 1
                yield item
            return
        self._thread = threading.Thread(
            target=self._produce, name="pixie-window-prefetch", daemon=True
        )
        self._thread.start()
        try:
            while True:
                self._check_cancel()
                t0 = time.perf_counter()
                # Samples landing while we block on the producer are
                # wait-for-staging, not compute: flag them "stall" so
                # the flame separates starvation from real host work.
                tm = threadmap.set_phase("stall")
                try:
                    kind, val = self._get()
                finally:
                    threadmap.restore(tm)
                dt = time.perf_counter() - t0
                self.stall_secs += dt
                if self._stats is not None:
                    self._stats.add("stall", dt)
                if kind == "done":
                    return
                if kind == "error":
                    # Surface the background staging failure as the
                    # query's own error, original traceback intact.
                    raise val
                self._check_cancel()
                self.windows += 1
                yield val
                val = None  # drop the device refs before freeing the slot
                self._slots.release()
        finally:
            self.close()

    def _get(self):
        while True:
            try:
                return self._q.get(timeout=_POLL_S)
            except queue.Empty:
                self._check_cancel()
                t = self._thread
                if (t is None or not t.is_alive()) and self._q.empty():
                    # Defensive: the producer always enqueues a terminal
                    # sentinel, so this is unreachable unless the thread
                    # was killed externally. Fail loudly, don't hang.
                    raise RuntimeError("window prefetch thread died")

    def _check_cancel(self):
        if self._cancel is not None and self._cancel.is_set():
            from .stream import QueryCancelled

            raise QueryCancelled("query cancelled")

    def counters(self) -> dict:
        """Counter snapshot ({depth, windows, stage_secs, stall_secs}) —
        what ``Engine._note_pipeline`` folds into the per-query trace
        and the engine-lifetime totals."""
        return {
            "depth": self.depth,
            "windows": self.windows,
            "stage_secs": self.stage_secs,
            "stall_secs": self.stall_secs,
        }

    def close(self) -> None:
        """Stop the producer, join its thread, drop staged buffers.

        Idempotent; safe on partially-consumed, cancelled, and errored
        pipelines. After close() returns no prefetch thread is alive and
        no staged window remains referenced by the pipeline.
        """
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        gen, self._gen = self._gen, iter(())
        try:
            gen.close()
        except AttributeError:
            pass

    # -- producer side -------------------------------------------------------
    def _produce(self):
        tm = (
            threadmap.bind(base=self._owner_entry, phase="stage")
            if self._owner_entry is not None else None
        )
        try:
            while True:
                if not self._acquire_slot():
                    return  # consumer closed the pipeline
                t0 = time.perf_counter()
                try:
                    item = next(self._gen)
                except StopIteration:
                    self._put(("done", None))
                    return
                self.stage_secs += time.perf_counter() - t0
                if not self._put(("item", item)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed, not swallowed
            self._put(("error", e))
        finally:
            threadmap.unbind(tm)

    def _acquire_slot(self) -> bool:
        while not self._stop.is_set():
            if self._slots.acquire(timeout=_POLL_S):
                return True
        return False

    def _put(self, item) -> bool:
        # The queue is unbounded (the slot semaphore bounds in-flight
        # windows), so put never blocks; stop just discards late items.
        if self._stop.is_set():
            return False
        self._q.put(item)
        return True
