"""Physical plan representation: operators + scalar expression trees.

Reference parity: ``src/carnot/plan/operators.h:49`` (Operator hierarchy:
MemorySource/Map/Filter/BlockingAgg/Join/Limit/MemorySink/GRPCSink...) and
``src/carnot/plan/scalar_expression.h`` (ScalarValue/Column/ScalarFunc/
AggregateExpression). The plan is a DAG of nodes; linear runs of
Map/Filter/Agg compile into ONE jitted fragment program instead of a
push-based exec-node chain.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

from ..types.dtypes import DataType


# -- scalar expressions ------------------------------------------------------
class Expr:
    pass


@dataclass(frozen=True)
class ColumnRef(Expr):
    name: str

    def __repr__(self):
        return f"col({self.name})"


@dataclass(frozen=True)
class Literal(Expr):
    value: object
    dtype: DataType

    def __repr__(self):
        return f"lit({self.value!r})"


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str
    args: tuple

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclass(frozen=True)
class AggExpr:
    """One aggregate output: out_name = uda_name(*args)."""

    out_name: str
    uda_name: str
    args: tuple  # tuple[Expr]; evaluated pre-aggregation


def trace_map_renames(map_op: "MapOp", mapping: dict) -> dict | None:
    """One reverse step of column-provenance tracing through a MapOp:
    remap each tracked (output name -> current name) entry through the
    map's exprs, or None when any tracked column is computed rather
    than a pure ``ColumnRef`` — upstream statistics (ingest sketches)
    then no longer describe its values. Shared by the executor's join
    stream walk and the planner's plan walk so the two can never
    disagree about when sketches apply."""
    exprs = dict(map_op.exprs)
    new = {}
    for out, src in mapping.items():
        e = exprs.get(src)
        if not isinstance(e, ColumnRef):
            return None
        new[out] = e.name
    return new


# -- operators ---------------------------------------------------------------
class Op:
    pass


@dataclass(frozen=True)
class MemorySourceOp(Op):
    """Stream a table out of the table store, time-bounded.

    Reference: ``src/carnot/exec/memory_source_node.h:42``.
    """

    table: str
    columns: Optional[tuple] = None  # None = all
    start_time: Optional[int] = None
    stop_time: Optional[int] = None


@dataclass(frozen=True)
class MapOp(Op):
    """Full projection: output columns are exactly ``exprs``.

    Reference: ``src/carnot/exec/map_node.h``.
    """

    exprs: tuple  # tuple[(name, Expr)]


@dataclass(frozen=True)
class FilterOp(Op):
    """Reference: ``src/carnot/exec/filter_node.h`` — here a mask &=, no copy."""

    predicate: Expr


@dataclass(frozen=True)
class AggOp(Op):
    """Group-by aggregate (blocking).

    Reference: ``src/carnot/exec/agg_node.h:66``. ``partial``/``finalize``
    mirror the distributed splitter's partial-op protocol
    (``planner/distributed/splitter/partial_op_mgr``): a partial agg emits
    carries; a finalize agg merges carries. The single-chip path runs both
    fused.
    """

    group_cols: tuple  # tuple[str]
    aggs: tuple  # tuple[AggExpr]
    max_groups: int = 4096
    # 'full' (single-fragment), 'partial' (emit mergeable carries — the
    # PEM/prepare half), 'finalize' (merge carries — the Kelvin half).
    mode: str = "full"


@dataclass(frozen=True)
class JoinOp(Op):
    """Equijoin of the left (probe) side against the right (build) side.

    Reference: ``src/carnot/exec/equijoin_node.h:48``. Small unique-key
    (N:1) inner/left joins run on host; everything else — N:M fan-out,
    right/outer, large inputs — routes to the sort-based device join
    (``pixie_tpu.ops.join``). how: 'inner' | 'left' | 'right' | 'outer'.
    """

    left_on: tuple
    right_on: tuple
    how: str = "inner"
    suffix: str = "_y"


@dataclass(frozen=True)
class LookupJoinOp(Op):
    """Fused N:1 equijoin stage inside a streaming fragment.

    Engine-internal (never produced by the planner): when a JoinOp's
    build side resolves to a dense-domain table — a dense aggregate's
    slot-aligned device state, or a unique-key host batch — the probe
    side's fragment gains this stage instead of materializing the join.
    Each probe row maps its key to a slot (``slot = key - lo``), checks a
    found bitmap, and gathers the build side's value columns on device —
    the TPU-first form of ``equijoin_node.cc``'s build+probe (output-row
    assembly never leaves the device; cf. VERDICT r03 device_join).

    The build arrays ride the fragment's side-input pytree
    (``cols['__side__']``), keyed ``{prefix}:found`` and
    ``{prefix}:{out_name}:{plane}`` — runtime arguments, not closure
    constants, so compiled fragments cache across queries.
    """

    key_col: str  # probe key column (single device plane)
    how: str  # 'inner' | 'left'
    prefix: str  # side-input key prefix, unique per join in a query
    lo: int  # dense domain offset (0 for dictionary codes)
    dom: int  # dense domain size
    out_cols: tuple  # ((out_name, DataType, n_planes), ...)


@dataclass(frozen=True)
class LimitOp(Op):
    """Reference: ``src/carnot/exec/limit_node.h`` (+ source abort signal)."""

    n: int


@dataclass(frozen=True)
class UnionOp(Op):
    """Concatenate inputs with identical schemas (k-way, time-ordered at
    materialization). Reference: ``src/carnot/exec/union_node.h``."""


@dataclass(frozen=True)
class UDTFSourceOp(Op):
    """Run a registered UDTF as a source.

    Reference: ``src/carnot/exec/udtf_source_node.h`` — used for cluster
    introspection (agent status, schema listing, registry listing).
    ``args`` are the compile-time init args (udtf.h UDTFInitArgs).
    """

    name: str
    args: tuple = ()  # tuple[(name, value)]


@dataclass(frozen=True)
class EmptySourceOp(Op):
    """Zero-row source with a declared relation
    (``src/carnot/exec/empty_source_node.h``)."""

    relation_items: tuple = ()  # tuple[(name, DataType)]


@dataclass(frozen=True)
class BridgeSinkOp(Op):
    """End of a per-agent fragment: hand the fragment's output to a
    cross-fragment bridge. GRPCSinkNode analog
    (``src/carnot/exec/grpc_sink_node.h:54``); on TPU the bridge is an XLA
    collective over the mesh, not a gRPC stream (SURVEY.md §2.7)."""

    bridge_id: int


@dataclass(frozen=True)
class BridgeSourceOp(Op):
    """Start of a merge fragment: consume a bridge's output.
    GRPCSourceNode analog (``src/carnot/exec/grpc_source_node.h``)."""

    bridge_id: int


@dataclass(frozen=True)
class OTelExportSinkOp(Op):
    """Export result rows as OTel metrics/spans.

    Reference: ``src/carnot/exec/otel_export_sink_node.h:40``; ``spec``
    is an ``exec.otel.OTelDataSpec``.
    """

    spec: object = None


@dataclass(frozen=True)
class TableSinkOp(Op):
    """Write result rows back into a named table-store table.

    Reference: MemorySinkNode (``src/carnot/exec/memory_sink_node.h``) —
    query outputs land in the table store so later queries (or a cron
    ScriptRunner stage) can read them.
    """

    table: str = "output"


@dataclass(frozen=True)
class ResultSinkOp(Op):
    """Terminal sink: materialize to the client result stream.

    Reference: GRPCSinkNode/MemorySinkNode (``src/carnot/exec/grpc_sink_node.h:54``).
    """

    name: str = "output"


@dataclass
class PlanNode:
    id: int
    op: Op
    inputs: list = field(default_factory=list)  # list[int]
    # Output schema, populated by the planner for rule passes (the engine
    # resolves schemas itself; manual plans may leave this None).
    relation: object = None


@dataclass
class Plan:
    """Operator DAG. Nodes are topologically ordered by construction."""

    nodes: dict = field(default_factory=dict)  # id -> PlanNode
    _counter: itertools.count = field(default_factory=itertools.count)

    def add(self, op: Op, inputs: list | None = None, relation=None) -> int:
        nid = next(self._counter)
        self.nodes[nid] = PlanNode(
            id=nid, op=op, inputs=list(inputs or []), relation=relation
        )
        return nid

    def sinks(self) -> list:
        used = {i for n in self.nodes.values() for i in n.inputs}
        return [nid for nid in self.nodes if nid not in used]

    def topo_order(self) -> list:
        seen, out = set(), []

        def visit(nid):
            if nid in seen:
                return
            seen.add(nid)
            for i in self.nodes[nid].inputs:
                visit(i)
            out.append(nid)

        for s in self.sinks():
            visit(s)
        return out
