"""Predicate-driven window skipping: zone maps vs FilterOp predicates.

Generalizes the join drivers' key-range window skipping
(``join_zone_skip``) to plain table scans: a FilterOp predicate over a
sketched column implies a per-column value interval; any scan window
whose ingest zone map (``table_store/sketches.py``) cannot intersect
that interval is pruned BEFORE it is staged — and, for cold-tier
windows, before it is *decoded* (``Table.scan`` / ``device_scan`` /
the streaming cursor call the pruner first). PAPERS.md
"Provenance-based Data Skipping" (2104.12815) is the shape.

Two halves:

- ``predicate_ranges(ops, dicts)`` — compile-time: walk the linear
  Map/Filter/Limit chain, intersect every conjunctive comparison of a
  *source* column against a literal into ``{col: (lo, hi)}``. Column
  provenance goes backwards through MapOps via ``trace_map_renames``
  (a computed column's values are no longer described by the ingest
  sketch, so its constraints are dropped). String literals resolve
  through the table dictionaries — ids ARE the sketch domain; a string
  absent from the dictionary matches nothing, so equality on it prunes
  every window (``EMPTY``).
- ``make_pruner(table, ranges, stats)`` — run-time: a
  ``prune(row_lo, row_hi) -> bool`` closure over the tablet's sketches.
  ``window_zone`` returning None means *unbounded* — never skip on
  missing information. Each skip charges one "skip" add to the
  fragment stats (the pruner runs on the pipeline producer thread, so
  per-query accounting must go through the locked TracedFragment, not
  thread-local scratch); ``QueryTrace._finalize_usage`` folds the count
  into ``usage.skipped_windows``.

Disable with the ``scan_zone_skip`` flag (bench A/B, debugging).
"""

from __future__ import annotations

from ..config import get_flag
from .plan import ColumnRef, FilterOp, FuncCall, LimitOp, Literal, MapOp, \
    trace_map_renames

#: Sentinel: the predicate is unsatisfiable against the table (e.g.
#: equality with a string the dictionary has never seen) — every window
#: prunes.
EMPTY = "empty"

_CMP = {
    "equal": ("eq", None),
    "lessThan": ("lt", None),
    "lessThanEqual": ("le", None),
    "greaterThan": ("gt", None),
    "greaterThanEqual": ("ge", None),
}

_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _literal_value(lit: Literal, col: str, dicts) -> int | None | str:
    """Literal -> sketch-domain int. Strings go through the table
    dictionary (ids are the sketched values); an unknown string returns
    EMPTY (matches nothing). None = not comparable (float, etc.)."""
    v = lit.value
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        return v
    if isinstance(v, str):
        d = dicts.get(col)
        if d is None:
            return None
        sid = d.lookup(v)
        # lookup returns NULL_ID for unseen strings; stored window codes
        # are always >= 0, so no window can match.
        return EMPTY if sid is None or int(sid) < 0 else int(sid)
    return None


def _constraints(pred, out: dict, dicts) -> bool:
    """Fold one predicate tree into ``out`` ({col: (lo, hi)}).
    Returns False when the predicate is unsatisfiable (EMPTY).
    Unrecognized subtrees contribute nothing (conservative: a
    conjunction can only narrow, so ignoring a conjunct is safe;
    disjunctions/negations are skipped wholesale)."""
    if not isinstance(pred, FuncCall):
        return True
    if pred.name == "logicalAnd":
        return all(_constraints(a, out, dicts) for a in pred.args)
    if pred.name not in _CMP or len(pred.args) != 2:
        return True
    a, b = pred.args
    op = _CMP[pred.name][0]
    if isinstance(a, Literal) and isinstance(b, ColumnRef):
        a, b, op = b, a, _FLIP[op]
    if not (isinstance(a, ColumnRef) and isinstance(b, Literal)):
        return True
    v = _literal_value(b, a.name, dicts)
    if v is EMPTY:
        return False
    if v is None:
        return True
    lo, hi = out.get(a.name, (None, None))
    if op == "eq":
        lo = v if lo is None else max(lo, v)
        hi = v if hi is None else min(hi, v)
    elif op in ("lt", "le"):
        b_hi = v - 1 if op == "lt" else v
        hi = b_hi if hi is None else min(hi, b_hi)
    else:  # gt / ge
        b_lo = v + 1 if op == "gt" else v
        lo = b_lo if lo is None else max(lo, b_lo)
    out[a.name] = (lo, hi)
    return True


def predicate_ranges(ops, dicts):
    """Walk a linear op chain; return {source_col: (lo|None, hi|None)},
    EMPTY (prune everything), or None (nothing to skip on).

    Constraints from a FilterOp apply to the chain's CURRENT column
    names; mapping them back to source columns goes through every
    earlier MapOp via trace_map_renames — a rename survives, a computed
    column kills that constraint (its sketch no longer describes it).
    """
    ranges: dict = {}
    maps_before: list = []
    for op in ops:
        if isinstance(op, MapOp):
            maps_before.append(op)
        elif isinstance(op, FilterOp):
            local: dict = {}
            if not _constraints(op.predicate, local, dicts):
                return EMPTY
            # Trace each constrained name back through the MapOps that
            # ran before this filter.
            mapping = {c: c for c in local}
            for m in reversed(maps_before):
                mapping = trace_map_renames(m, mapping)
                if mapping is None:
                    mapping = {}
                    break
            for out_name, src_name in mapping.items():
                lo, hi = local[out_name]
                cur = ranges.get(src_name, (None, None))
                ranges[src_name] = (
                    lo if cur[0] is None else (cur[0] if lo is None else max(cur[0], lo)),
                    hi if cur[1] is None else (cur[1] if hi is None else min(cur[1], hi)),
                )
        elif isinstance(op, LimitOp):
            continue
        else:
            break  # agg/join/etc: later filters see derived rows
    ranges = {
        c: (lo, hi) for c, (lo, hi) in ranges.items()
        if lo is not None or hi is not None
    }
    for lo, hi in ranges.values():
        if lo is not None and hi is not None and lo > hi:
            return EMPTY
    return ranges or None


def make_pruner(table, ranges, stats=None):
    """Build ``prune(row_lo, row_hi) -> bool`` for one tablet, or None
    when there is nothing to prune on. ``ranges`` comes from
    ``predicate_ranges``; EMPTY prunes every window."""
    if ranges is None:
        return None
    if ranges is EMPTY:
        def prune_all(row_lo: int, row_hi: int) -> bool:
            if stats is not None:
                stats.add("skip", 0.0, rows=row_hi - row_lo)
            return True

        return prune_all
    sk = getattr(table, "sketches", None)
    if sk is None:
        return None
    cols = {c: b for c, b in ranges.items() if c in sk.cols}
    if not cols:
        return None

    def prune(row_lo: int, row_hi: int) -> bool:
        for c, (lo, hi) in cols.items():
            zone = sk.cols[c].window_zone(row_lo, row_hi)
            if zone is None:
                continue  # unbounded: never skip on missing info
            zlo, zhi = zone
            if (hi is not None and zlo > hi) or (lo is not None and zhi < lo):
                if stats is not None:
                    stats.add("skip", 0.0, rows=row_hi - row_lo)
                return True
        return False

    return prune


def chain_pruner(table, ops, dicts, stats=None):
    """predicate_ranges + make_pruner + the scan_zone_skip flag gate, in
    one call — the shape every scan site uses."""
    if not get_flag("scan_zone_skip"):
        return None
    return make_pruner(table, predicate_ranges(ops, dicts), stats=stats)
