"""Fragment compiler: a linear operator chain -> one jitted XLA program.

Reference contrast: Carnot instantiates an ExecutionGraph of exec nodes and
pushes RowBatches through virtual ConsumeNext calls
(``src/carnot/exec/exec_graph.cc:295``). Here the whole chain
{Map/Filter -> BlockingAgg -> Map/Filter/Limit} is traced into TWO
functions:

- ``update(state, cols, valid)``: folds one staged window into the group
  state (or, for non-aggregating chains, produces the window's output
  batch). Runs once per window under jit — XLA fuses projections, filter
  masks, group-id sorts and UDA segment updates into one program.
- ``finalize(state)``: UDA finalize + post-agg ops -> output columns.

Group state is a pytree {keys, valid, carries, overflow}; windows merge
via the regroup machinery (``pixie_tpu.ops.groupby``), the same path a
multi-device partial-agg merge uses.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from ..config import get_flag
from ..ops.groupby import (
    dense_group_ids,
    dense_group_ids_hash,
    regroup_pair,
    scatter_carry,
)
from ..types.dtypes import DataType, device_dtypes, pad_values
from ..types.relation import Relation
from ..udf.registry import Registry
from ..udf.udf import UDADef, apply_cast
from .expr import BindError, BoundExpr, bind_expr
from .plan import (
    AggOp,
    ColumnRef,
    FilterOp,
    FuncCall,
    LimitOp,
    Literal,
    LookupJoinOp,
    MapOp,
)

# Integer-typed key columns that qualify for stats-derived dense domains.
_INT_KEY_TYPES = (DataType.INT64, DataType.TIME64NS)


@dataclass
class ColumnMeta:
    """Host-side metadata for one output column."""

    name: str
    dtype: DataType
    dict: object = None  # StringDictionary for STRING columns
    struct_fields: Optional[tuple] = None  # sketch JSON struct (quantiles)


@dataclass
class CompiledFragment:
    relation: Relation  # device-visible output relation
    out_meta: list  # list[ColumnMeta] incl. struct columns
    is_agg: bool
    update: object = None  # jitted
    update_all: object = None  # jitted scan-fold over stacked windows (agg)
    finalize: object = None  # jitted (agg only)
    init_state: object = None  # callable -> state pytree (agg only)
    limit: Optional[int] = None  # host-enforced row cap (non-agg chains)
    # Unjitted building blocks, traceable inside shard_map (the distributed
    # partial-agg path, ``pixie_tpu.parallel``):
    window_state: object = None  # (cols, valid) -> per-window group state
    merge_states: object = None  # (state_a, state_b) -> merged state
    # Dense fragments whose aggregates are all count/sum/mean/min/max
    # expose the native-fold seam: {"inputs_jit": (cols, valid) ->
    # (gids, per-agg args, oob), "plan": ((out_name, uda_name, init),...)}.
    # The engine's CPU backend runs the scatter passes in the native
    # multi-core kernel (native/seg_fold.cc) — XLA:CPU scatters are
    # single-threaded. None = not eligible.
    native_fold: object = None
    apply_rows: object = None  # (cols, valid) -> (cols, valid), non-agg chain
    # (col, plane_i) per entry of state["keys"], and the post-pre-stage
    # relation the group columns are typed against (agg only) — consumed by
    # the agent-mode bridge merge to realign string key dictionaries.
    key_plane_index: tuple = ()
    group_relation: Relation = None
    # Agg outputs whose CARRY holds string-dictionary ids (e.g. ``any``
    # over a string column) mapped to the input columns those ids encode.
    # Group keys realign across agents; carries do not — the bridge merge
    # rejects such payloads unless every agent shares the dictionaries.
    string_carry_sources: tuple = ()  # tuple[(out_name, tuple[col, ...])]
    # Dense-domain mode: per-group-col static domain sizes (the packed key
    # IS the group id; state["keys"] is empty). () = not dense.
    # ``dense_offsets`` shifts stats-derived integer keys to zero base
    # (0 for dictionary/bool columns).
    dense_domains: tuple = ()
    dense_offsets: tuple = ()
    # Per-key value stride (1 except binned/affine integer keys, where
    # slot codes count stride steps: value = code * stride + offset).
    dense_strides: tuple = ()


_FRAGMENT_CACHE: dict = {}
_FRAGMENT_CACHE_MAX = 128
# Guards insert/evict (concurrent queries compile concurrently; two
# threads evicting the same oldest key would KeyError, and the loser of
# a duplicate-miss race must adopt the winner's fragment so id()-keyed
# downstream caches — the distributed step cache — stay canonical).
_FRAGMENT_CACHE_LOCK = threading.Lock()


def _struct_key(x):
    """Canonical hashable form of a plan-op / expr tree (class names keep
    e.g. ColumnRef('x') distinct from a bare string)."""
    import dataclasses

    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return (type(x).__name__,) + tuple(
            _struct_key(getattr(x, f.name)) for f in dataclasses.fields(x)
        )
    if isinstance(x, (list, tuple)):
        return tuple(_struct_key(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _struct_key(v)) for k, v in x.items()))
    return x


def _stats_cache_key(ops, col_stats):
    """The col_stats facts that can influence compilation: rounded bounds
    of columns reaching the chain's agg group keys. Keying on anything
    more (e.g. time_ bounds, which move every append) would defeat the
    fragment cache."""
    if not col_stats:
        return ()
    try:
        pre, agg, _post, _limit = _split_chain(list(ops))
    except BindError:
        return tuple(sorted(col_stats.items()))
    if agg is None:
        return ()
    stats = _propagate_stats(pre, col_stats)
    return tuple(
        (c, _round_stat_bounds(*stats[c]))
        for c in agg.group_cols
        if c in stats
    )


def compile_fragment_cached(ops, input_relation, input_dicts, registry,
                            allow_dense: bool = True, col_stats=None):
    """``compile_fragment`` memoized on plan structure.

    A fragment's jitted ``update``/``finalize`` closures hold the XLA
    executables; rebuilding them per query forces a re-trace + compile
    every ``execute_query`` (~10s/query over the TPU tunnel, BENCH r02's
    real bottleneck — Carnot similarly reuses compiled plan state,
    ``src/carnot/carnot.cc:122``). Keyed on the op chain, input schema,
    the CONTENT identity of every string dictionary
    (``StringDictionary.content_key``: an append-only dictionary's
    compile-time behavior — literal ``lookup`` ids, out_meta decode —
    is a pure function of its ordered contents, and growth re-encodes
    string literals under a new key), and the registry identity.
    Content- rather than id()-keyed because the merge tier's bridge
    payloads decode FRESH dictionary objects from the wire on every
    distributed query: identity keying missed the cache (and recompiled
    the merge/limit XLA programs) once per run. Unhashable chains (not
    produced by the planner today) fall back to uncached compilation.
    """
    from ..config import get_flag

    try:
        key = (
            _struct_key(tuple(ops)),
            input_relation.items_tuple(),
            tuple(sorted(
                (n, d.content_key()) for n, d in input_dicts.items()
            )),
            id(registry),
            get_flag("groupby_impl"),
            get_flag("pallas_dense_fold"),
            get_flag("pallas_tdigest"),
            get_flag("dense_domain_limit") if allow_dense else -1,
            get_flag("int_dense_domain_limit") if allow_dense else -1,
            _stats_cache_key(ops, col_stats),
        )
        hash(key)
    except TypeError:
        return compile_fragment(
            ops, input_relation, input_dicts, registry, allow_dense,
            col_stats=col_stats,
        )
    hit = _FRAGMENT_CACHE.get(key)
    if hit is None:
        # Compile OUTSIDE the cache lock (compiles are slow and must
        # not serialize concurrent queries' unrelated misses); a
        # duplicate-miss race costs one redundant compile and the
        # loser adopts the winner's fragment below.
        frag = compile_fragment(
            ops, input_relation, input_dicts, registry, allow_dense,
            col_stats=col_stats,
        )
        _track_fragment_programs(frag, ops, key, input_dicts, registry)
        with _FRAGMENT_CACHE_LOCK:
            raced = _FRAGMENT_CACHE.get(key)
            if raced is not None:
                return raced[0]
            while len(_FRAGMENT_CACHE) >= _FRAGMENT_CACHE_MAX:
                _FRAGMENT_CACHE.pop(next(iter(_FRAGMENT_CACHE)))
            # The entry pins the registry (still id()-keyed: a freed
            # registry's address could be recycled into a false hit) and
            # the compile-time dictionaries (the fragment's out_meta
            # resolves ids through them; content-equal callers may
            # outlive their own copies).
            _FRAGMENT_CACHE[key] = (
                frag, tuple(input_dicts.values()), registry
            )
    else:
        frag = hit[0]
    return frag


def _track_fragment_programs(frag, ops, cache_key, input_dicts,
                             registry) -> None:
    """Wrap a fresh fragment's jit entry points in the process program
    registry (exec/programs.py): per-shape compile wall-time + XLA
    cost/memory analysis, hit/miss counts, /debug/programz and the
    ``__programs__`` telemetry table. Keyed by the fragment cache key —
    the same structural identity that keys THIS cache — so a repeated
    plan's second run is a registry hit, and a fragment-cache eviction
    can still reuse the registry's executable instead of recompiling
    (the registry pins the id()-keyed objects exactly like the entry
    above). No-op when program_registry_size is 0."""
    from .programs import default_program_registry

    preg = default_program_registry()
    label = ",".join(type(o).__name__ for o in ops) or "(scan)"
    pins = (tuple(input_dicts.values()), registry)
    frag.update = preg.wrap(
        frag.update, "fragment_update", (cache_key, "update"), label,
        pins=pins,
    )
    frag.update_all = preg.wrap(
        frag.update_all, "fragment_scan_fold", (cache_key, "update_all"),
        label, pins=pins,
    )
    frag.finalize = preg.wrap(
        frag.finalize, "fragment_finalize", (cache_key, "finalize"),
        label, pins=pins,
    )
    if frag.native_fold is not None:
        frag.native_fold["inputs_jit"] = preg.wrap(
            frag.native_fold["inputs_jit"], "native_fold_inputs",
            (cache_key, "native_inputs"), label, pins=pins,
        )


def _range_valid(cols, valid):
    """Materialize ``valid`` when it arrives as a (lo, hi) row-range pair
    (device-resident windows carry no mask; computing it in a separate
    dispatch costs a full tunnel round trip per window, so the mask is
    built INSIDE the fragment program from two scalars)."""
    if isinstance(valid, tuple):
        lo, hi = valid
        n = next(
            p for c, p in cols.items() if c != "__side__"
        )[0].shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        return (iota >= lo) & (iota < hi)
    return valid


def _bind_pre_stage(ops, relation, dicts, registry):
    """Bind leading Map/Filter/LookupJoin ops; returns
    (apply_fn, relation, dicts)."""
    steps = []  # ("map", [(name, BoundExpr)]) | ("filter", BoundExpr)
    #           | ("lookup", LookupJoinOp)
    for op in ops:
        if isinstance(op, MapOp):
            bound = [(name, bind_expr(e, relation, dicts, registry)) for name, e in op.exprs]
            steps.append(("map", bound))
            relation = Relation([(n, b.dtype) for n, b in bound])
            dicts = {n: b.dict for n, b in bound if b.dict is not None}
        elif isinstance(op, FilterOp):
            b = bind_expr(op.predicate, relation, dicts, registry)
            if b.dtype != DataType.BOOLEAN:
                raise BindError(f"filter predicate has type {b.dtype}, want BOOLEAN")
            steps.append(("filter", b))
        elif isinstance(op, LookupJoinOp):
            if not relation.has_column(op.key_col):
                raise BindError(f"lookup key {op.key_col!r} not in {relation}")
            steps.append(("lookup", op))
            relation = Relation(
                list(relation.items())
                + [(n, dt) for n, dt, _np in op.out_cols]
            )
        else:
            raise AssertionError(op)

    def apply_lookup(op, cols, valid, side):
        if side is None:
            raise BindError(
                "LookupJoinOp fragment ran without its side-input tables "
                "(cols['__side__'] missing — engine-internal op misuse)"
            )
        k = cols[op.key_col][0]
        idx = k - op.lo
        inb = (idx >= 0) & (idx < op.dom)
        slot = jnp.clip(idx, 0, op.dom - 1).astype(jnp.int32)
        found = inb & side[f"{op.prefix}:found"][slot]
        cols = dict(cols)
        for name, _dt, n_planes in op.out_cols:
            planes = []
            for j in range(n_planes):
                t = side[f"{op.prefix}:{name}:{j}"]
                v = t[slot]
                if op.how == "left":
                    # Unmatched probe rows stay valid with null values.
                    # Inner joins skip the select: not-found rows become
                    # invalid below, so their gathered garbage is masked
                    # everywhere downstream.
                    v = jnp.where(found, v, jnp.zeros((), v.dtype))
                planes.append(v)
            cols[name] = tuple(planes)
        if op.how == "inner":
            valid = valid & found
        return cols, valid

    def apply(cols, valid):
        cols = dict(cols)
        side = cols.pop("__side__", None)
        for kind, payload in steps:
            if kind == "map":
                # Broadcast so literal-only expressions yield full planes.
                new_cols = {}
                for name, b in payload:
                    v = b.fn(cols)
                    planes = v if isinstance(v, tuple) else (v,)
                    new_cols[name] = tuple(
                        jnp.broadcast_to(p, valid.shape) for p in planes
                    )
                cols = new_cols
            elif kind == "lookup":
                cols, valid = apply_lookup(payload, cols, valid, side)
            else:
                valid = valid & jnp.broadcast_to(payload.fn(cols), valid.shape)
        return cols, valid

    return apply, relation, dicts


def _split_chain(ops):
    """[pre(map/filter)...] [agg]? [post(map/filter)...] [limit at end]?

    A LimitOp may only terminate a fragment — the engine splits chains at
    interior limits so the cap applies at its plan position (Carnot's
    LimitNode aborts upstream sources the same way,
    ``src/carnot/exec/limit_node.h``).
    """
    pre, agg, post, limit = [], None, [], None
    for i, op in enumerate(ops):
        if isinstance(op, LimitOp):
            if i != len(ops) - 1:
                raise BindError(
                    "LimitOp must terminate a fragment (engine splits chains)"
                )
            limit = op.n
        elif isinstance(op, AggOp):
            if agg is not None:
                raise BindError("multiple aggregates in one fragment")
            agg = op
        elif agg is None:
            pre.append(op)
        else:
            post.append(op)
    return pre, agg, post, limit


def _expr_stats(e, stats):
    """(min, max, stride) bounds of an integer expression, or None.

    Interval + stride arithmetic over the affine expressions the planner
    emits for time windowing: ``bin(t, d)`` yields multiples of ``d``,
    and +/-/*-by-literal keep the lattice. The invariant maintained is
    "every value ≡ min (mod stride)", which is exactly what the dense
    packing needs: code = (v - min) // stride is exact. Constants carry
    stride 0 (gcd identity)."""
    import math

    if isinstance(e, ColumnRef):
        s = stats.get(e.name)
        if s is None:
            return None
        return (int(s[0]), int(s[1]), int(s[2]) if len(s) > 2 else 1)
    if isinstance(e, Literal):
        v = e.value
        if isinstance(v, bool) or not isinstance(v, int):
            return None
        return (v, v, 0)
    if not isinstance(e, FuncCall):
        return None
    args = [_expr_stats(a, stats) for a in e.args]
    if any(a is None for a in args):
        return None
    if e.name == "bin" and len(args) == 2 and args[1][0] == args[1][1]:
        d = args[1][0]
        lo, hi, _s = args[0]
        if d <= 0 or lo < 0:
            # jnp's floor-mod and this arithmetic agree for non-negative
            # values; negative time bases don't occur, so just decline.
            return None
        return (lo - lo % d, hi - hi % d, d)
    if e.name in ("add", "subtract") and len(args) == 2:
        (la, ha, sa), (lb, hb, sb) = args
        st = math.gcd(sa, sb)
        if e.name == "add":
            return (la + lb, ha + hb, st)
        return (la - hb, ha - lb, st)
    if e.name == "multiply" and len(args) == 2:
        (la, ha, sa), (lb, hb, sb) = args
        const = None
        var = None
        if lb == hb:
            const, var = lb, (la, ha, sa)
        elif la == ha:
            const, var = la, (lb, hb, sb)
        if const is None or const <= 0:
            return None
        lo, hi, st = var
        return (lo * const, hi * const, st * const)
    return None


def _propagate_stats(ops, stats):
    """Carry input-column (min, max[, stride]) bounds through leading
    Map/Filter ops. Pass-through ColumnRefs keep their source bounds;
    affine integer expressions (time binning) get derived strided
    bounds via ``_expr_stats``; filters narrow, so bounds stay valid."""
    if not stats:
        return stats
    for op in ops:
        if isinstance(op, MapOp):
            nxt = {}
            for name, e in op.exprs:
                s = _expr_stats(e, stats)
                if s is not None and s[2] != 0:
                    nxt[name] = s
            stats = nxt
    return stats


def compile_fragment(ops, input_relation, input_dicts, registry: Registry,
                     allow_dense: bool = True, col_stats=None) -> CompiledFragment:
    pre, agg, post, limit = _split_chain(ops)
    apply_pre, rel1, dicts1 = _bind_pre_stage(pre, input_relation, dict(input_dicts), registry)

    if agg is None:
        if post:
            raise AssertionError("post ops without agg should be in pre")
        out_meta = [
            ColumnMeta(name=n, dtype=t, dict=dicts1.get(n)) for n, t in rel1.items()
        ]

        @jax.jit
        def update(cols, valid):
            return apply_pre(cols, _range_valid(cols, valid))

        return CompiledFragment(
            relation=rel1, out_meta=out_meta, is_agg=False, update=update,
            limit=limit, apply_rows=apply_pre,
        )

    return _compile_agg(
        agg, post, limit, apply_pre, rel1, dicts1, registry,
        allow_dense=allow_dense, col_stats=_propagate_stats(pre, col_stats),
        pre_ops=pre,
    )


def unpack_dense_slots(iota, doms, col_types, xp, offsets=None, strides=None):
    """Dense slot indices -> per-group-col key planes.

    The single source of the unpack arithmetic, shared by the traced
    finalize (xp=jnp) and the bridge-payload expansion (xp=np) so the
    packing order / NULL encoding can never diverge between them.
    ``offsets`` shifts stats-derived integer codes back to their values;
    ``strides`` scales step-indexed codes (binned time keys) back.
    """
    import numpy as np

    planes = []
    pack = 1
    for d in doms:
        pack *= d
    offsets = offsets or (0,) * len(doms)
    strides = strides or (1,) * len(doms)
    for dt, dom, off, st in zip(col_types, doms, offsets, strides):
        pack //= dom
        code = (iota // pack) % dom
        if dt == DataType.BOOLEAN:
            planes.append(code.astype(np.bool_))
        elif dt in _INT_KEY_TYPES:
            planes.append((code * st + off).astype(np.int64))
        else:  # STRING: last sub-slot decodes back to NULL_ID (-1)
            planes.append(
                xp.where(code == dom - 1, -1, code).astype(np.int32)
            )
    return planes


# Stats bounds round outward to this grain so ordinary appends (which
# nudge a column's min/max) neither change the compiled domain nor churn
# the fragment cache; only growth past the grain recompiles.
_STATS_Q = 4096


def _round_stat_bounds(lo: int, hi: int, stride: int = 1) -> tuple:
    """Round bounds outward to the _STATS_Q grain IN STRIDE STEPS, so the
    rounded lo keeps the values' residue class (the dense packing divides
    by the stride exactly)."""
    if stride <= 1:
        return (lo - lo % _STATS_Q, hi - hi % _STATS_Q + _STATS_Q - 1, 1)
    lo_r = lo - ((lo // stride) % _STATS_Q) * stride
    hi_r = hi + (_STATS_Q - 1 - (hi // stride) % _STATS_Q) * stride
    return (lo_r, hi_r, stride)


def _static_key_domains(rel1, dicts1, group_cols, col_stats=None):
    """Per-column (domain size, value offset, value stride) triples, or
    None when any column's domain is not known at compile time.

    Dictionary-encoded STRING columns have exactly ``len(dict) + 1``
    possible device codes (ids 0..len-1 plus NULL_ID), BOOLEANs two.
    Integer/time keys are dense when the table store's append-time
    min/max stats (``Table.col_stats``) bound them: the domain is
    [min, max] and the offset shifts values to zero-based codes. Rows
    outside a stats-derived domain (appends racing the query) flag
    overflow, and the engine's rebucket retry recompiles against fresh
    stats. Float keys have no dense form -> None.
    """
    doms = []
    for c in group_cols:
        dt = rel1.col_type(c)
        if dt == DataType.STRING and dicts1.get(c) is not None:
            doms.append((len(dicts1[c]) + 1, 0, 1))  # last slot = NULL_ID
        elif dt == DataType.BOOLEAN:
            doms.append((2, 0, 1))
        elif (
            dt in (DataType.INT64, DataType.TIME64NS)
            and col_stats
            and c in col_stats
        ):
            lo, hi, stride = _round_stat_bounds(*col_stats[c])
            if hi - lo + 1 <= 0:
                return None
            doms.append(((hi - lo) // stride + 1, lo, stride))
        else:
            return None
    return doms


def _pure_select_map(pre):
    """out col -> source table col when the pre-stage is only pure
    column-select/rename Maps (the shape column pruning emits); None when
    any real computation or filtering happens before the aggregate."""
    mapping = None  # None = identity so far
    for op in pre:
        if not isinstance(op, MapOp) or not all(
            isinstance(e, ColumnRef) for _n, e in op.exprs
        ):
            return None
        new = {}
        for n2, e in op.exprs:
            src = e.name if mapping is None else mapping.get(e.name)
            if src is None:
                return None
            new[n2] = src
        mapping = new
    return mapping if mapping is not None else {}


def _compile_agg(agg: AggOp, post, limit, apply_pre, rel1, dicts1, registry,
                 allow_dense=True, col_stats=None, pre_ops=()):
    g = agg.max_groups
    for c in agg.group_cols:
        if not rel1.has_column(c):
            raise BindError(f"group column {c!r} not in {rel1}")

    # Static dense key domain: when every group column's device code has a
    # statically-known small domain, the PACKED CODE is the group id —
    # no per-window sort or hash, and state merges are slot-aligned
    # (regroup-free), the shape XLA/TPU executes best. Carnot has no
    # analog (its RowTuple hash map is domain-oblivious,
    # ``src/carnot/exec/agg_node.h:66``); this is the TPU-first design.
    # Integer keys qualify through the table store's append-time min/max
    # stats (a bincount-class scatter replaces hash probing); they get a
    # larger domain budget because a single int column can't suffer the
    # multi-key packing blowup the base limit protects against.
    dense_domains = None
    dense_offsets = None
    dense_strides = None
    if allow_dense and agg.group_cols:
        doms = _static_key_domains(
            rel1, dicts1, list(agg.group_cols), col_stats
        )
        if doms is not None:
            total = 1
            for d, _off, _st in doms:
                total *= d
            has_int = any(off or rel1.col_type(c) in _INT_KEY_TYPES
                          for (_d, off, _st), c in zip(doms, agg.group_cols))
            # The larger int budget is justified only for a SINGLE int
            # key (no multi-key packing blowup); mixed/multi-key domains
            # stay under the base limit.
            limit_slots = (
                get_flag("int_dense_domain_limit")
                if has_int and len(agg.group_cols) == 1
                else get_flag("dense_domain_limit")
            )
            if total <= limit_slots:
                dense_domains = tuple(d for d, _off, _st in doms)
                dense_offsets = tuple(off for _d, off, _st in doms)
                dense_strides = tuple(st for _d, _off, st in doms)
                g = total

    # Bind aggregate input expressions and resolve UDAs.
    aggs_bound = []  # (AggExpr, UDADef, [BoundExpr], [cast pairs])
    for ae in agg.aggs:
        arg_bound = [bind_expr(a, rel1, dicts1, registry) for a in ae.args]
        uda: UDADef = registry.get_uda(ae.uda_name, [b.dtype for b in arg_bound])
        casts = list(zip([b.dtype for b in arg_bound], uda.arg_types))
        aggs_bound.append((ae, uda, arg_bound, casts))

    group_cols = list(agg.group_cols)
    key_plane_index = []  # (col, plane_i) per key plane
    for c in group_cols:
        for i in range(len(device_dtypes(rel1.col_type(c)))):
            key_plane_index.append((c, i))

    def init_state():
        if dense_domains is not None:
            keys = ()  # implicit: slot index IS the packed key
        else:
            keys = tuple(
                jnp.full(
                    g,
                    pad_values(rel1.col_type(c))[i],
                    dtype=device_dtypes(rel1.col_type(c))[i],
                )
                for c, i in key_plane_index
            )
        carries = {ae.out_name: uda.init(g) for ae, uda, _, _ in aggs_bound}
        return {
            "keys": keys,
            "valid": jnp.zeros(g, dtype=jnp.bool_),
            "carries": carries,
            "overflow": jnp.zeros((), dtype=jnp.bool_),
        }

    def dense_slot_ids(cols, valid):
        """Packed key code per row + out-of-domain flag.

        slot = sum(code_i * stride_i); NULL_ID (-1) string codes land in
        each column's last sub-slot and masked rows in the trash slot g.
        Stats-derived integer codes are offset to zero base; a row whose
        value escaped the compile-time [min, max] (an append racing the
        query) goes to the trash slot and raises ``oob`` so the engine's
        rebucket retry recompiles against fresh stats.
        """
        slot = None
        oob = None
        for (c, _i), dom, off, st in zip(
            key_plane_index, dense_domains, dense_offsets, dense_strides
        ):
            p = cols[c][0]
            if rel1.col_type(c) in _INT_KEY_TYPES:
                raw = p - off
                if st > 1:
                    # Strided domain (binned time keys): the slot is the
                    # step index; off-grid values (appends racing the
                    # stats) are out-of-domain, not silently misbinned.
                    out = (raw < 0) | (raw >= dom * st) | (raw % st != 0)
                    raw = raw // st
                else:
                    out = (raw < 0) | (raw >= dom)
                oob = out if oob is None else (oob | out)
                code = jnp.clip(raw, 0, dom - 1).astype(jnp.int32)
            else:
                code = jnp.clip(
                    jnp.where(p < 0, dom - 1, p).astype(jnp.int32), 0, dom - 1
                )
            slot = code if slot is None else slot * jnp.int32(dom) + code
        if oob is None:
            oob_any = jnp.zeros((), dtype=jnp.bool_)
            keep = valid
        else:
            oob = oob & valid
            oob_any = jnp.any(oob)
            keep = valid & ~oob
        # ONE select to the trash slot (several chained wheres over [n]
        # i64 planes cost real memory bandwidth at window scale).
        return jnp.where(keep, slot, g).astype(jnp.int32), oob_any

    def dense_key_planes():
        """Reconstruct the [g] key planes from the slot index (traced)."""
        return unpack_dense_slots(
            jnp.arange(g, dtype=jnp.int64),
            dense_domains,
            [rel1.col_type(c) for c, _i in key_plane_index],
            jnp,
            offsets=dense_offsets,
            strides=dense_strides,
        )

    # NOTE: merge_states materializes neutral carries by calling uda.init(g)
    # DURING tracing (never precompute them eagerly here): a concrete jax
    # Array captured as a jit-closure constant permanently degrades every
    # subsequent dispatch on the axon TPU tunnel to ~65ms/call.

    # Per-window group ids for NON-dense keys: backend-matched by
    # default — XLA's TPU sort is fast while its CPU sort is ~90x slower
    # than scatter, so 'auto' sorts on TPU and hashes on CPU. The small
    # [2G] regroup merges below always sort.
    impl = get_flag("groupby_impl")
    if impl == "auto":
        impl = "sort" if jax.default_backend() == "tpu" else "hash"
    window_group_ids = (
        dense_group_ids_hash if impl == "hash" else dense_group_ids
    )

    # Pallas dense fold (TPU): count/sum/mean/max over FLOAT64 planes
    # route through the hand-scheduled MXU kernel — one-hot contractions
    # with VMEM-resident [G] accumulators replace per-UDA HBM scatters
    # (ops/pallas_groupby.py). 'auto' engages on the TPU backend;
    # 'interpret' runs the kernel in interpreter mode on any backend
    # (the equivalence tests); 'off' disables.
    _pallas_mode = get_flag("pallas_dense_fold")
    pallas_fold = (
        dense_domains is not None
        and _pallas_mode in ("auto", "interpret")
        and (_pallas_mode == "interpret" or jax.default_backend() == "tpu")
        and g <= 2048  # [chunk, G] one-hot must fit VMEM
        and all(
            ae.uda_name == "count"
            or (
                ae.uda_name in ("sum", "mean", "max", "min")
                and len(arg_bound) == 1
                and casts[0][1] == DataType.FLOAT64
            )
            for ae, _uda, arg_bound, casts in aggs_bound
        )
    )

    def _pallas_window_carries(gids, cols, valid):
        """Per-agg carries via dense_group_fold; returns (carries, valid_w)."""
        from ..ops.pallas_groupby import dense_group_fold

        interpret = _pallas_mode == "interpret"
        g_pad = -(-g // 128) * 128
        n = valid.shape[0]
        chunk = min(2048, n, max(128, (1 << 20) // g_pad))
        while n % chunk:
            chunk //= 2
        # Trash rows must match NO kernel column, incl. the pad range.
        gids_p = jnp.where(gids >= g, jnp.int32(g_pad), gids)
        # One kernel pass per distinct ARG EXPRESSION (sum+mean+max over
        # the same column share a single sweep — the kernel returns all
        # three statistics anyway).
        folds: dict = {}

        need_min = any(ae.uda_name == "min" for ae, _u, _b, _c in aggs_bound)

        def fold_for(a):
            cnt, s, mx, mn = dense_group_fold(
                gids_p, a, g_pad, chunk=chunk, interpret=interpret,
                want_min=need_min,
            )
            return cnt[:g], s[:g], mx[:g], mn[:g] if mn is not None else None

        carries_w = {}
        cnt_shared = None
        for ae, uda, arg_bound, casts in aggs_bound:
            if ae.uda_name == "count":
                continue
            fkey = (_struct_key(ae.args), casts[0])
            if fkey not in folds:
                a = apply_cast(arg_bound[0].fn(cols), *casts[0])
                folds[fkey] = fold_for(jnp.broadcast_to(a, valid.shape))
            cnt, s, mx, mn = folds[fkey]
            cnt_shared = cnt
            init_leaf = uda.init(g)
            if ae.uda_name == "sum":
                carries_w[ae.out_name] = s.astype(init_leaf.dtype)
            elif ae.uda_name == "mean":
                carries_w[ae.out_name] = (
                    s.astype(init_leaf[0].dtype),
                    cnt.astype(init_leaf[1].dtype),
                )
            else:  # max/min: empty slots keep the UDA's neutral fill
                ext = mx if ae.uda_name == "max" else mn
                carries_w[ae.out_name] = jnp.where(
                    cnt > 0, ext.astype(init_leaf.dtype), init_leaf
                )
        if cnt_shared is None:
            # count-only aggregation: one kernel pass over a zero column.
            cnt_shared = fold_for(jnp.zeros(n, dtype=jnp.float32))[0]
        for ae, uda, _b, _c in aggs_bound:
            if ae.uda_name == "count":
                carries_w[ae.out_name] = cnt_shared.astype(
                    uda.init(g).dtype
                )
        return carries_w, cnt_shared > 0

    def window_state(cols, valid):
        """Fold one window of rows into a fresh [G]-slot group state.

        ``valid`` is a bool[n] mask or a (lo, hi) row-range scalar pair
        (the device-resident-window form)."""
        valid = _range_valid(cols, valid)
        cols, valid = apply_pre(cols, valid)
        if dense_domains is not None:
            gids, oob = dense_slot_ids(cols, valid)
            keys_w = ()
            valid_w = None  # filled below (count carries give it free)
            # Dense slots cannot overflow by count; stats-derived integer
            # domains overflow only when a row's key escapes the
            # compile-time bounds (oob flags it for the rebucket retry).
            n_w = jnp.where(oob, g + 1, 0).astype(jnp.int32)
            if pallas_fold:
                carries_w, valid_w = _pallas_window_carries(gids, cols, valid)
                return {
                    "keys": (),
                    "valid": valid_w,
                    "carries": carries_w,
                    "overflow": n_w > g,
                }
        else:
            key_planes = [cols[c][i] for c, i in key_plane_index]
            gids, keys_w, valid_w, n_w = window_group_ids(key_planes, valid, g)

        carries_w = {}
        for ae, uda, arg_bound, casts in aggs_bound:
            args = [
                apply_cast(b.fn(cols), have, want)
                for b, (have, want) in zip(arg_bound, casts)
            ]
            args = [jnp.broadcast_to(a, valid.shape) for a in args]
            carries_w[ae.out_name] = uda.update(uda.init(g), gids, valid, *args)
        if valid_w is None:
            # Dense mode: a count aggregate's fresh carry already says
            # which slots saw rows — reuse it instead of paying a third
            # scatter pass over the window.
            cnt_name = next(
                (ae.out_name for ae, uda, _b, _c in aggs_bound
                 if ae.uda_name == "count"),
                None,
            )
            if cnt_name is not None:
                valid_w = carries_w[cnt_name] > 0
            else:
                valid_w = (
                    jnp.zeros(g + 1, dtype=jnp.bool_).at[gids].set(True)[:g]
                )
        return {
            "keys": tuple(keys_w),
            "valid": valid_w,
            "carries": carries_w,
            "overflow": n_w > g,
        }

    def merge_states(sa, sb):
        """Associative merge of two group states (slot orders may differ).

        This single function is both the window accumulator and the
        distributed finalize: per-device partial states gathered over the
        mesh merge through it, replacing Carnot's UDA Serialize -> GRPC ->
        finalize-agg pipeline (``planner/distributed/splitter/partial_op_mgr``).
        Dense-domain states merge slot-for-slot — no regroup sort at all.
        """
        if dense_domains is not None:
            carries = {
                ae.out_name: uda.merge(
                    sa["carries"][ae.out_name], sb["carries"][ae.out_name]
                )
                for ae, uda, _, _ in aggs_bound
            }
            return {
                "keys": (),
                "valid": sa["valid"] | sb["valid"],
                "carries": carries,
                "overflow": sa["overflow"] | sb["overflow"],
            }
        ids_a, ids_b, m_keys, m_valid, n_tot = regroup_pair(
            sa["keys"], sa["valid"], sb["keys"], sb["valid"], g
        )
        carries = {}
        for ae, uda, _, _ in aggs_bound:
            neutral = uda.init(g)
            ca = scatter_carry(
                sa["carries"][ae.out_name], ids_a, sa["valid"], g, neutral
            )
            cb = scatter_carry(
                sb["carries"][ae.out_name], ids_b, sb["valid"], g, neutral
            )
            carries[ae.out_name] = uda.merge(ca, cb)
        overflow = sa["overflow"] | sb["overflow"] | (n_tot > g)
        return {
            "keys": tuple(m_keys),
            "valid": m_valid,
            "carries": carries,
            "overflow": overflow,
        }

    @jax.jit
    def update(state, cols, valid):
        return merge_states(state, window_state(cols, valid))

    @jax.jit
    def update_all(state, cols_list, los, his):
        """Fold MANY equal-capacity windows in ONE program: stack the
        per-window planes on device and lax.scan the window fold. One
        dispatch (one tunnel round trip) replaces W of them; XLA overlaps
        the scan iterations' memory traffic.

        ``cols_list`` is a tuple of per-window cols dicts; ``los``/``his``
        are i32[W] row-range bounds (the mask builds in-program).
        Query-constant side inputs (``__side__``, the fused-lookup-join
        build tables) are identical across windows and must NOT be
        stacked W times — they lift out and rejoin inside the scan body.
        """
        side = None
        stripped = []
        for c in cols_list:
            c = dict(c)
            s = c.pop("__side__", None)
            side = side if side is not None else s
            stripped.append(c)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *stripped
        )

        def body(st, xs):
            c, lo, hi = xs
            if side is not None:
                c = {**c, "__side__": side}
            return merge_states(st, window_state(c, (lo, hi))), None

        out, _ = jax.lax.scan(body, state, (stacked, los, his))
        return out

    # Output relation: group cols then agg outputs (struct sketches keep a
    # [G, k] plane; they are host-materialized and opaque to post ops).
    out_items = [(c, rel1.col_type(c)) for c in group_cols]
    out_meta = [
        ColumnMeta(name=c, dtype=rel1.col_type(c), dict=dicts1.get(c))
        for c in group_cols
    ]
    struct_cols = set()
    for ae, uda, arg_bound, _ in aggs_bound:
        out_items.append((ae.out_name, uda.return_type))
        if uda.struct_fields:
            struct_cols.add(ae.out_name)
            out_meta.append(
                ColumnMeta(
                    name=ae.out_name, dtype=uda.return_type,
                    struct_fields=uda.struct_fields,
                )
            )
        else:
            d = arg_bound[0].dict if (
                uda.return_type == DataType.STRING and arg_bound
            ) else None
            out_meta.append(ColumnMeta(name=ae.out_name, dtype=uda.return_type, dict=d))
    out_rel = Relation(out_items)

    # Bind post-agg ops against the non-struct view of the output.
    post_rel = Relation([(n, t) for n, t in out_items if n not in struct_cols])
    post_dicts = {m.name: m.dict for m in out_meta if m.dict is not None}
    apply_post, post_rel_out, post_dicts_out = _bind_pre_stage(
        post, post_rel, post_dicts, registry
    )
    # Struct planes never flow through device post-ops (the planner fuses
    # pluck(quantiles(...)) into _quantile_* UDAs instead). Post filters
    # keep all columns, so struct columns survive them; a post MapOp is a
    # full projection and cannot reference struct columns (binding against
    # post_rel, which excludes them, raises).
    post_has_map = any(isinstance(op, MapOp) for op in post)
    if post:
        final_meta = [
            ColumnMeta(n, post_rel_out.col_type(n), dict=post_dicts_out.get(n))
            for n in post_rel_out.column_names
        ]
        if not post_has_map:
            final_meta += [m for m in out_meta if m.struct_fields is not None]
        out_rel = post_rel_out
    else:
        final_meta = out_meta

    @jax.jit
    def finalize(state):
        cols = {}
        if dense_domains is not None:
            for c, plane in zip(group_cols, dense_key_planes()):
                cols[c] = (plane,)
        else:
            for c, _ in zip(group_cols, range(len(group_cols))):
                planes = tuple(
                    kp
                    for kp, (kc, _i) in zip(state["keys"], key_plane_index)
                    if kc == c
                )
                cols[c] = planes
        for ae, uda, _, _ in aggs_bound:
            out = uda.finalize(state["carries"][ae.out_name])
            cols[ae.out_name] = (out,)
        valid = state["valid"]
        device_cols = {n: p for n, p in cols.items() if n not in struct_cols}
        device_cols, valid = apply_post(device_cols, valid)
        for s in struct_cols:
            device_cols[s] = cols[s]
        return device_cols, valid, state["overflow"]

    string_carry_sources = []
    for ae, uda, arg_bound, _ in aggs_bound:
        if (
            uda.return_type == DataType.STRING
            and not uda.struct_fields
            and any(b.dtype == DataType.STRING for b in arg_bound)
        ):
            string_carry_sources.append(
                (ae.out_name, tuple(_expr_columns(ae.args)))
            )

    # Native-fold seam: dense-domain fragments whose aggregates all have
    # associative scalar carries hand the scatter passes to the CPU
    # multi-core kernel; XLA keeps the elementwise pre-stage + slot-id
    # packing (engine._fold_agg_state_native).
    native_fold = None
    if dense_domains is not None and all(
        (
            ae.uda_name in ("count", "sum", "mean", "min", "max")
            or ae.uda_name == "quantiles"
            or ae.uda_name.startswith("_quantile_")
        )
        and len(arg_bound) == 1
        for ae, _uda, arg_bound, _casts in aggs_bound
    ):
        def fold_inputs(cols, valid):
            valid = _range_valid(cols, valid)
            cols2, valid2 = apply_pre(cols, valid)
            gids, oob = dense_slot_ids(cols2, valid2)
            args = []
            for ae, _uda, arg_bound, casts in aggs_bound:
                if ae.uda_name == "count":
                    args.append(None)  # count reads no value column
                    continue
                b, (have, want) = arg_bound[0], casts[0]
                a = apply_cast(b.fn(cols2), have, want)
                args.append(jnp.broadcast_to(a, valid2.shape))
            return gids, tuple(args), oob

        # Raw mode: when the pre-stage is a pure column select and every
        # key/arg is a direct table column, the kernel reads the STAGED
        # PLANES themselves — zero device work in the fold path.
        raw = None
        sel = _pure_select_map(pre_ops)
        if sel is not None:
            def _src(c):
                return c if not sel else sel.get(c)

            key_specs, key_srcs = [], []
            for (c, pi), dom, off, st in zip(
                key_plane_index, dense_domains, dense_offsets, dense_strides
            ):
                dt = rel1.col_type(c)
                src = _src(c)
                if src is None or pi != 0 or len(device_dtypes(dt)) != 1:
                    key_srcs = None
                    break
                if dt == DataType.STRING:
                    kind = 0
                elif dt == DataType.BOOLEAN:
                    kind = 1
                else:
                    kind = 2
                key_specs.append((kind, dom, off, st))
                key_srcs.append(src)
            arg_srcs = []
            if key_srcs is not None:
                for ae, _uda, _b, _c in aggs_bound:
                    if ae.uda_name == "count":
                        arg_srcs.append(None)
                        continue
                    e = ae.args[0] if ae.args else None
                    src = _src(e.name) if isinstance(e, ColumnRef) else None
                    if src is None or len(device_dtypes(rel1.col_type(e.name))) != 1:
                        arg_srcs = None
                        break
                    arg_srcs.append(src)
            if key_srcs is not None and arg_srcs is not None:
                raw = {
                    "key_cols": tuple(key_srcs),
                    "key_specs": tuple(key_specs),
                    "arg_cols": tuple(arg_srcs),
                }

        native_fold = {
            "inputs_jit": jax.jit(fold_inputs),
            "plan": tuple(
                (ae.out_name, ae.uda_name, uda.init)
                for ae, uda, _b, _c in aggs_bound
            ),
            "raw": raw,
        }

    return CompiledFragment(
        relation=out_rel,
        out_meta=final_meta,
        is_agg=True,
        update=update,
        update_all=update_all,
        finalize=finalize,
        init_state=init_state,
        limit=limit,
        window_state=window_state,
        merge_states=merge_states,
        native_fold=native_fold,
        apply_rows=apply_pre,
        key_plane_index=tuple(key_plane_index),
        group_relation=rel1,
        string_carry_sources=tuple(string_carry_sources),
        dense_domains=dense_domains or (),
        dense_offsets=dense_offsets or (),
        dense_strides=dense_strides or (),
    )


def _expr_columns(exprs):
    """Column names referenced anywhere in a tuple of Expr trees."""
    from .plan import ColumnRef, FuncCall

    out: list[str] = []

    def walk(e):
        if isinstance(e, ColumnRef):
            if e.name not in out:
                out.append(e.name)
        elif isinstance(e, FuncCall):
            for a in e.args:
                walk(a)

    for e in exprs:
        walk(e)
    return out
