"""Query engine: plan DAG -> streamed, jit-compiled execution.

Reference parity: the Carnot facade (``src/carnot/carnot.h:39-95``
Carnot::ExecutePlan) + ExecutionGraph (``exec/exec_graph.cc:295``). The
TPU execution model:

- Each maximal linear chain of Map/Filter/Agg/Limit over one input
  compiles to a single fragment program (see fragment.py).
- Tables stream through in fixed-capacity windows (static shapes -> one
  compile, reused every window; the Table::Cursor batch loop analog).
- DAG joints (Join/Union) materialize their small (post-agg) inputs and
  continue; joins run host-side on dense ids (N:1, right-unique) or
  fuse into the probe fragment (see joins.py).
- Aggregation group state survives across windows via the regroup
  machinery, so a billion-row table aggregates in O(windows) device
  dispatches with O(G) memory.

Module layout (split r5): stream.py (stream/result primitives),
joins.py (join routing + union + fused lookup build), bridge.py
(agent-mode payloads + merge). This module keeps the Engine facade and
the window-staging/fold execution core, and re-exports the split names
for compatibility.
"""

from __future__ import annotations

import itertools
import threading
import time

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.relation import Relation
from ..udf.registry import Registry, default_registry
from .bridge import (  # noqa: F401  (re-exported)
    AggStatePayload,
    RowsPayload,
    _PendingAggBridge,
    _compact_payload,
    _expand_dense_payload,
    bind_bridge,
    bridge_payload,
    merge_agg_bridge,
)
from . import threadmap
from .fragment import compile_fragment_cached as compile_fragment
from .pipeline import WindowPipeline
from .trace import Tracer, plan_script
from .joins import (  # noqa: F401  (re-exported)
    _join_dispatch,
    _union_host,
    try_fused_join,
)
# NOTE: DEVICE_JOIN_MIN_ROWS deliberately NOT re-exported — patching a
# re-exported copy would be a silent no-op; joins.py is the patch point.
from .plan import (
    AggOp,
    TableSinkOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from .stream import (  # noqa: F401  (re-exported)
    QueryCancelled,
    QueryError,
    _apply_limit,
    _block_if,
    _chain_out_relation,
    _col,
    _concat_host,
    _double_agg_groups,
    _empty_host_batch,
    _Stream,
    _stream_col_stats,
    _timed,
    _to_host_batch,
    _window_shapes,
)


class DeviceResult:
    """Device-resident aggregate query output.

    Holds the finalized [G] column planes + validity on device. The axon
    TPU tunnel journals device work lazily until a process's first
    device-to-host readback; that flush executes everything recorded and
    switches later dispatches to a synchronous mode (~65ms round trip
    each) in which compiling NEW programs can stall. Callers therefore
    compile/warm with ``materialize=False`` and control when the single
    readback — ``to_host()``, which also resolves group-overflow
    rebucketing — happens. ``block_until_ready()`` fences without
    reading back (it does NOT flush the journal).

    Reference contrast: Carnot's MemorySink always lands rows host-side
    (``src/carnot/exec/memory_sink_node.cc``); on TPU the result's natural
    home is HBM until a client asks for bytes.
    """

    def __init__(self, engine, stream, frag, cols, valid, overflow,
                 stats=None, qstats=None):
        self._engine = engine
        self._stream = stream
        self._frag = frag
        self._cols = cols
        self._valid = valid
        self._overflow = overflow
        self._stats = stats
        self._qstats = qstats  # the CREATING query's stats (analyze mode)
        self._host: HostBatch | None = None

    @property
    def relation(self):
        return self._frag.relation

    def block_until_ready(self) -> "DeviceResult":
        import jax

        jax.block_until_ready((self._cols, self._valid, self._overflow))
        return self

    def to_host(self) -> HostBatch:
        if self._host is not None:
            return self._host
        eng, stream, frag = self._engine, self._stream, self._frag
        cols, valid, overflow = self._cols, self._valid, self._overflow
        stats = self._stats
        while bool(overflow):
            # NOTE: the rebucket re-folds the source table AS IT IS NOW —
            # rows appended between execute and to_host are included,
            # unlike the no-overflow snapshot. Callers needing snapshot
            # semantics materialize before further ingest (the service
            # shell serializes queries against appends anyway).
            # Rebucket: double max_groups and re-run the stream (the same
            # recovery the device join uses on output overflow; Carnot's
            # hash map grows instead, ``agg_node.cc``).
            stream = _double_agg_groups(stream)
            frag = compile_fragment(
                stream.chain, stream.relation, stream.dicts, eng.registry,
                col_stats=_stream_col_stats(stream),
            )
            if self._qstats is not None:
                # Fresh per-attempt stats: rows/windows stay per-attempt
                # and the attempt is marked (analyze fidelity).
                stats = self._qstats.new_fragment(stream.chain)
                stats.ops = stats.ops + ("rebucket",)
            state = eng._fold_agg_state(stream, frag, stats)
            with _timed(stats, "finalize"):
                cols, valid, overflow = frag.finalize(state)
                _block_if(stats, (cols, valid, overflow))
        with _timed(stats, "materialize"):
            out = _to_host_batch(frag.out_meta, cols, np.asarray(valid))
        if stats is not None:
            stats.rows_out = out.length
        self._host = _apply_limit(out, frag.limit)
        self._cols = self._valid = self._overflow = None  # release HBM
        return self._host

    def to_pydict(self, **kw):
        return self.to_host().to_pydict(**kw)


class _QueryScratch:
    """Per-query execution state, one instance per in-flight
    ``execute_plan`` (thread-local on the engine). This is what used to
    live as engine attributes under ``_exec_guard``'s one-query-at-a-
    time serialization — moving it here is what lets independent
    queries overlap on one engine (certified by pxlock: the lock-order/
    request-from-handler rules repo-green + lockdep-clean concurrency
    suites; see docs/ANALYSIS.md "pxlock")."""

    __slots__ = (
        "cancel", "stats", "pipeline", "join_decision",
        "resource_report", "table_sinks",
    )

    def __init__(self, cancel=None, stats=None):
        self.cancel = cancel  # per-query cancel event (execute_plan arg)
        self.stats = stats  # the trace's stats spine (QueryStats)
        self.pipeline: dict | None = None
        self.join_decision = None
        self.resource_report = None
        self.table_sinks: dict = {}


class Engine:
    """Owns tables + registry; executes plans. (EngineState analog,
    ``src/carnot/engine_state.h``.)"""

    def __init__(self, registry: Registry | None = None,
                 window_rows: int | None = None,
                 pipeline_depth: int | None = None):
        from ..config import get_flag
        from ..table_store import TableStore

        self.registry = registry or default_registry()
        self.table_store = TableStore()
        self.window_rows = window_rows or get_flag("window_rows")
        # Window-executor prefetch depth (pipeline.py): staging of window
        # N+1 overlaps compute of window N; 1 = serial.
        self.pipeline_depth = int(pipeline_depth or get_flag("pipeline_depth"))
        # Per-query execution scratch (thread-local: each concurrent
        # execute_plan runs on its own caller thread). The ``last_*``
        # attributes below are engine-level LAST-FINISHED-QUERY
        # snapshots for bench/tests/observability — under concurrency
        # they are last-writer-wins by design; anything correctness-
        # bearing reads the scratch, never these.
        self._tls = threading.local()
        # Guards the last-* snapshots, pipeline totals and the
        # in-flight counters (tiny critical sections, no blocking calls
        # inside — lock-order leaf).
        self._state_lock = threading.Lock()
        self._inflight = 0
        self.max_inflight = 0  # high-water concurrent queries (tests/obs)
        # Pipeline accounting: per-query snapshot + engine-lifetime totals
        # (exported by services.observability.engine_collector).
        self._last_pipeline: dict | None = None
        self.pipeline_totals = {
            "windows": 0, "stage_secs": 0.0, "stall_secs": 0.0,
        }
        self.last_stats = None
        # Always-on query-lifecycle tracing (exec/trace.py): every
        # execute_plan gets a trace (spans + stats spine, ring-buffered,
        # /debug/queryz). Cheap: timestamps only, no device sync.
        self.tracer = Tracer()
        # Engine-STATE mutation guard. Queries no longer serialize on it
        # (per-query state lives on ``_QueryScratch``); it remains for
        # subclasses that mutate engine-scoped execution state around
        # super().execute_plan() (DistributedEngine's replan swaps the
        # mesh) and as the "engine not stuck" probe the fault tests
        # acquire. Reentrant so such a subclass can nest.
        self._exec_guard = threading.RLock()
        self._last_table_sinks: dict = {}  # {table: rows} from TableSinkOps
        # Routing outcome of the most recent materialized JoinOp
        # (joins.JoinDecision): strategy, build-side swap, capacity,
        # overflow retries, zone-skipped windows. Bench and tests read
        # it; None until a query joins.
        self._last_join_decision = None
        self._last_resource_report = None
        # OTel egress collection (export_otel): init here, not lazily —
        # a hasattr-then-assign under concurrent queries could lose an
        # export.
        self.otel_exports: list = []
        # Learned join-output capacities, keyed by (mode, plan hash,
        # node): a repeated query starts at the rung its last run
        # settled on. Engine-scoped — plan hashes don't capture table
        # identity, so a shared cache would cross-seed engines running
        # the same script over different data.
        self._join_capacity_cache: dict = {}
        # Self-telemetry (services/telemetry.py TelemetryCollector):
        # when attached, finished traces fold into __queries__/__spans__
        # tables and observed per-script cardinalities feed back into
        # _compile_table_stats. None = off (the default for bare
        # engines; agents/deploy roles wire it).
        self.telemetry = None
        # Device-tier observability (exec/programs.py): the shared
        # device-memory monitor brackets every execute_plan so the
        # query's high-water device bytes land in
        # QueryResourceUsage.device_peak_bytes (memory_stats() is None
        # on CPU — the bracket then costs two no-op samples).
        from .programs import default_device_monitor

        self.device_memory = default_device_monitor()
        self.device_memory.start()  # no-op unless device_memory_poll_s
        # Local result cache (exec/result_cache.py; result_cache_mb
        # flag, 0 = off): broker-less deployments cache merged results
        # at execute_query exactly like the broker's execute path.
        from .result_cache import ResultCache

        self.result_cache = ResultCache()
        # Incremental materialized views (exec/views.py): lazily
        # constructed on first use — ViewRegistry imports streaming,
        # which imports this module.
        self._views = None

    # -- per-query scratch plumbing ------------------------------------------
    # The underscore accessors keep the long-standing call sites in
    # joins.py / bridge.py (`getattr(engine, "_query_stats", None)`,
    # `engine.last_join_decision = ...`) working unchanged while the
    # state behind them became per-query.
    @property
    def _scratch(self) -> "_QueryScratch | None":
        return getattr(self._tls, "scratch", None)

    @property
    def _query_stats(self):
        s = self._scratch
        return s.stats if s is not None else None

    @property
    def _cancel(self):
        s = self._scratch
        return s.cancel if s is not None else None

    @property
    def last_join_decision(self):
        s = self._scratch
        if s is not None and s.join_decision is not None:
            return s.join_decision
        return self._last_join_decision

    @last_join_decision.setter
    def last_join_decision(self, jd) -> None:
        s = self._scratch
        if s is not None:
            s.join_decision = jd
        self._last_join_decision = jd

    @property
    def last_resource_report(self):
        s = self._scratch
        if s is not None:
            return s.resource_report
        return self._last_resource_report

    @property
    def last_pipeline(self) -> dict | None:
        s = self._scratch
        if s is not None and s.pipeline is not None:
            return s.pipeline
        return self._last_pipeline

    @property
    def last_table_sinks(self) -> dict:
        s = self._scratch
        if s is not None:
            return s.table_sinks
        return self._last_table_sinks

    @property
    def tables(self) -> dict:
        """{name: default-tablet (or first) Table} view over the store."""
        out = {}
        for n in self.table_store.table_names():
            t = self.table_store.get_table(n)
            if t is None:
                tablets = self.table_store.tablets(n)
                t = tablets[0] if tablets else None
            out[n] = t
        return out

    # -- table management ----------------------------------------------------
    def create_table(self, name: str, relation: Relation | None = None,
                     max_bytes: int = -1):
        t = self.table_store.add_table(name, relation, max_bytes=max_bytes)
        # Tables created through an engine stage device windows at the
        # engine's streaming size from the first append on.
        t.device_window_rows = self.window_rows
        return t

    def append_data(self, name: str, data, time_cols=("time_",)):
        """Push path (Stirling's RegisterDataPushCallback analog)."""
        # Atomic get-or-create at THIS engine's streaming window size so
        # first appends stage device windows correctly (and concurrent
        # first appends never replace each other's table).
        self.table_store.ensure_table(
            name, device_window_rows=self.window_rows
        )
        return self.table_store.append_data(name, data, time_cols=time_cols)

    # -- execution -----------------------------------------------------------
    def execute_query(self, query: str, now_ns: int = 0,
                      max_output_rows: int = 10_000,
                      analyze: bool = False,
                      materialize: bool = True) -> dict:
        """Compile a PxL script and execute it (Carnot::ExecuteQuery parity,
        ``src/carnot/carnot.cc:122-134``). Returns {output name: HostBatch}.
        ``analyze`` records per-fragment stats on ``self.last_stats``.
        ``materialize=False`` leaves aggregate outputs device-resident
        (returns DeviceResult — call ``.to_host()`` for bytes)."""
        from ..planner import CompilerState, compile_pxl
        from . import result_cache as rc

        # The query's lifecycle trace starts HERE so the parse/compile/
        # plan phase gets its own span; execute_plan ends the trace.
        trace = self.tracer.begin_query(script=query, analyze=analyze)
        # Local result cache / materialized views (the broker-less
        # repeat fast path): only for fully-materialized, non-analyze
        # runs — an analyze run's point is the execution stats, and a
        # DeviceResult must not be shared between callers.
        servable = materialize and not analyze and "pxtrace" not in query
        cache_status = ""
        if servable and self.result_cache.enabled():
            status, entry, lag_ms = self.result_cache.lookup(
                query, now_ns, max_output_rows, self._table_watermark_ns
            )
            if status == rc.HIT:
                trace.cache = rc.HIT
                trace.usage.freshness_lag_ms = lag_ms
                self.tracer.end_query(trace, status="ok")
                return dict(entry.result)
            cache_status = status
        if servable:
            view_res = self._try_view_answer(
                query, now_ns, max_output_rows, trace
            )
            if view_res is not None:
                trace.cache = rc.VIEW
                self.tracer.end_query(trace, status="ok")
                return view_res
        trace.cache = cache_status
        try:
            with trace.span("compile"):
                state = CompilerState(
                    schemas={n: t.relation for n, t in self.tables.items()},
                    registry=self.registry,
                    now_ns=now_ns,
                    max_output_rows=max_output_rows,
                    table_stats=self._compile_table_stats(),
                )
                compiled = compile_pxl(query, state)
        except BaseException as e:
            self.tracer.end_query(
                trace, status="error", error=f"{type(e).__name__}: {e}"
            )
            raise
        # Watermark snapshot BEFORE execution (conservative: ingest
        # landing mid-scan makes the stored watermark older than
        # reality, so the next lookup re-validates rather than
        # over-trusting), and the cache disposition resolved before the
        # trace ends so __queries__ rows carry it.
        store_wms: dict | None = None
        if servable and self.result_cache.enabled():
            tables, _ = rc.scan_info(compiled.plan)
            wms = {t: self._table_watermark_ns(t) for t in tables}
            if tables and all(w is not None for w in wms.values()):
                store_wms = wms
            else:
                trace.cache = rc.BYPASS
        try:
            result = self.execute_plan(
                compiled.plan, analyze=analyze, materialize=materialize,
                trace=trace,
            )
        except BaseException as e:
            # Safety net for execute_plan overrides that can raise before
            # reaching the base implementation (e.g. DistributedEngine's
            # replan): end_query is idempotent, so the normal path —
            # where execute_plan already ended the trace — is a no-op.
            self.tracer.end_query(
                trace,
                status=(
                    "cancelled" if isinstance(e, QueryCancelled) else "error"
                ),
                error=f"{type(e).__name__}: {e}",
            )
            raise
        if store_wms is not None and isinstance(result, dict):
            self.result_cache.store(
                query, state.now_ns, max_output_rows, compiled.plan,
                result, store_wms.get,
            )
        return result

    def _table_watermark_ns(self, table: str):
        """Current max event-time watermark across ``table``'s tablets
        (None = unknown table / no time index) — the local engine's
        half of the result cache's validity predicate."""
        from ..table_store import table as _table_mod

        tablets = self.table_store.tablets(table)
        if not tablets:
            return None
        return _table_mod.max_watermark_ns(tablets)

    @property
    def views(self):
        """Lazily built ViewRegistry (exec/views.py) — deferred because
        views ride StreamingQuery, whose module imports this one."""
        if self._views is None:
            from .views import ViewRegistry

            self._views = ViewRegistry(self)
        return self._views

    def _try_view_answer(self, query: str, now_ns: int,
                         max_output_rows: int, trace):
        """Materialized-view fast path: count the run, auto/manifest-
        register when warranted, and answer finalize-over-state when a
        registered view covers this query. None = execute normally.
        Never raises — a view failure falls back to full execution."""
        from .views import view_candidates_enabled

        if not view_candidates_enabled(query):
            return None
        try:
            return self.views.serve(
                query, now_ns=now_ns, max_output_rows=max_output_rows,
                trace=trace,
            )
        except Exception:
            import logging

            logging.getLogger("pixie_tpu.views").warning(
                "materialized-view answer failed; executing normally",
                exc_info=True,
            )
            return None

    def _compile_table_stats(self) -> dict:
        """Ingest-sketch stats snapshot for the optimizer
        (``CompilerState.table_stats``): per-table row counts + per-key-
        column HLL NDV estimates. A few microseconds per column — the
        sketches were maintained at append time."""
        out: dict = {}
        # Snapshots: the agent's heartbeat thread builds this while a
        # query/ingest thread appends tables and sketched columns —
        # iterating the live dicts intermittently dies with "dictionary
        # changed size during iteration" (observed as a heartbeat-
        # thread flake that silently killed the heartbeat loop).
        for n, t in list(self.tables.items()):
            sk = getattr(t, "sketches", None)
            if not sk:
                continue
            cols = list(sk.cols.items())
            out[n] = {
                "rows": sk.rows,
                "ndv": {
                    c: s.ndv for c, s in cols if s.rows
                },
                # Global zone maps per sketched column — pxbound's join
                # overlap term (analysis/bounds.py) reads them.
                "zones": {
                    c: (s.lo, s.hi)
                    for c, s in cols
                    if s.rows and s.lo is not None
                },
            }
            # Storage-tier seeding (docs/STORAGE.md): pxbound reads the
            # OBSERVED per-tier bytes/row off the freshness envelope to
            # seed staged-bytes and cold-decode-bytes bounds — resident
            # widths the schema walk cannot see (compression, dict
            # codes vs raw strings).
            if getattr(t, "_tier", None) is not None:
                f = t.freshness()
                hr, cr = int(f["hot_rows"]), int(f["cold_rows"])
                out[n]["tier"] = {
                    "hot_rows": hr,
                    "cold_rows": cr,
                    "hot_row_bytes": f["hot_bytes"] / hr if hr else None,
                    "cold_row_bytes": f["cold_bytes"] / cr if cr else None,
                    "raw_row_bytes": (
                        (f["hot_bytes"] + f["cold_raw_bytes"]) / (hr + cr)
                        if hr + cr else None
                    ),
                }
        # Telemetry feedback (arXiv:2102.02440): OBSERVED per-script
        # output cardinalities from past runs, keyed by script hash
        # under a dunder key no table name can collide with. compile_pxl
        # resolves the entry for the script being compiled so optimizer
        # rules (push_agg_through_join sizing) can floor their capacity
        # estimates at reality instead of trusting a drifted sketch.
        if self.telemetry is not None:
            obs = self.telemetry.observed()
            if obs:
                out["__observed__"] = obs
        return out

    def set_metadata_state(self, state) -> None:
        """Attach k8s metadata; rebinds the metadata UDFs to a snapshot of
        ``state`` (reference: per-query AgentMetadataState), preserving all
        other registrations on this engine's registry."""
        from ..metadata.funcs import METADATA_FUNC_NAMES, register_metadata_funcs

        self.metadata_state = state
        reg = self.registry.clone("engine", exclude=METADATA_FUNC_NAMES)
        register_metadata_funcs(reg, state)
        self.registry = reg

    def execute_plan(
        self, plan: Plan, bridge_inputs: dict | None = None,
        analyze: bool = False, materialize: bool = True,
        cancel=None, trace=None,
    ) -> dict:
        """Execute a plan. Whole plans return {sink name: HostBatch}.

        Split-fragment plans (from the distributed splitter, agent mode):
        a plan ending in BridgeSinkOps additionally returns
        {("bridge", id): payload}; a merge plan starting from
        BridgeSourceOps reads ``bridge_inputs`` = {bridge id: [payloads]}.

        ``analyze`` records per-fragment, per-stage execution stats
        (exec_node.h:40 ExecNodeStats analog) on ``self.last_stats``.

        Concurrent queries overlap on one Engine: every per-query
        execution state (cancel handle, stats spine, pipeline snapshot,
        join decision, resource report, table sinks) lives on a
        thread-local :class:`_QueryScratch`, so the Agent's bus
        dispatcher threads (execute/merge/bridge work) and broker-side
        worker threads run independent queries side by side. Shared
        engine state is individually thread-safe: TableStore/Tracer/
        ProgramRegistry/DeviceMemoryMonitor carry their own locks, the
        learned join-capacity cache locks in joins.py, and the
        ``last_*`` snapshots are last-finished-query observability
        (``_state_lock``). Subclasses that mutate engine-SCOPED
        execution state around super() (DistributedEngine's mesh
        replan) still serialize on ``_exec_guard``.

        ``trace`` is the query's in-progress QueryTrace when the caller
        (execute_query) already began one; otherwise a fresh trace is
        started here. Either way this call ends it — after execution,
        so the trace sinks (slow-query log, OTLP push to a possibly-
        slow collector) never run inside the scratch scope.
        """
        if trace is None:
            trace = self.tracer.begin_query(
                script=plan_script(plan), analyze=analyze
            )
        status, error = "ok", ""
        try:
            return self._execute_plan_scoped(
                plan, bridge_inputs, analyze, materialize, cancel, trace
            )
        except QueryCancelled as e:
            status, error = "cancelled", str(e)
            raise
        except BaseException as e:
            status, error = "error", f"{type(e).__name__}: {e}"
            raise
        finally:
            self.tracer.end_query(trace, status=status, error=error)

    def _execute_plan_scoped(
        self, plan, bridge_inputs, analyze, materialize, cancel, trace
    ) -> dict:
        # The trace's stats spine IS the per-fragment stats object —
        # analyze just runs it with sync=True (see analyze.py).
        scratch = _QueryScratch(cancel=cancel, stats=trace.stats)
        # pxbound's plan-time resource envelope (analysis/bounds.py),
        # attached by compile_pxl: join-buffer pre-sizing reads it, and
        # the soundness gate compares it against the trace's observed
        # QueryResourceUsage.
        scratch.resource_report = getattr(plan, "resource_report", None)
        # Predicted-vs-observed calibration (__queries__ feedback loop):
        # stamp the plan's predicted cost on the trace so the telemetry
        # fold records it NEXT TO the observed usage — px/bound_accuracy
        # computes the per-script calibration ratio from the pair. The
        # broker path stamps its merged (logical + wire) cost instead.
        if trace.predicted is None and scratch.resource_report is not None:
            from ..analysis.bounds import merged_cost

            trace.predicted = merged_cost(scratch.resource_report, None)
        mem_token = (
            self.device_memory.query_begin()
            if self.device_memory is not None else None
        )
        prev = self._scratch  # defensive: a nested call restores it
        self._tls.scratch = scratch
        with self._state_lock:
            self._inflight += 1
            if self._inflight > self.max_inflight:
                self.max_inflight = self._inflight
        # Profiler attribution: CPU samples taken on this thread while
        # the plan runs carry the query's qid/tenant/script hash
        # (exec/threadmap.py; phase refined by pipeline/program hooks).
        tm_token = threadmap.bind(trace=trace, phase="host")
        try:
            return self._execute_plan_inner(plan, bridge_inputs, materialize)
        finally:
            threadmap.unbind(tm_token)
            self._tls.scratch = prev
            if analyze:
                self.last_stats = trace.stats
            trace.pipeline = (
                dict(scratch.pipeline) if scratch.pipeline else None
            )
            jd = scratch.join_decision
            if jd is not None:
                trace.usage.retries += int(getattr(jd, "retries", 0))
                trace.usage.skipped_windows += int(
                    getattr(jd, "skipped_windows", 0)
                )
            if mem_token is not None:
                trace.usage.device_peak_bytes = (
                    self.device_memory.query_end(mem_token)
                )
            # Publish the last-finished-query snapshots (observability/
            # bench/test seams; last-writer-wins under concurrency).
            with self._state_lock:
                self._inflight -= 1
                self._last_pipeline = scratch.pipeline
                self._last_table_sinks = scratch.table_sinks
                # jd may be None: a finished non-join query clears the
                # snapshot (callers must not re-account a previous
                # query's decision).
                self._last_join_decision = jd
                self._last_resource_report = scratch.resource_report

    @staticmethod
    def _plan_fingerprint(plan: Plan) -> int:
        """Structural plan hash (cached on the plan object): keys the
        joins' learned-capacity cache so a repeated script starts at the
        output-capacity rung its last run settled on."""
        fp = getattr(plan, "_fingerprint", None)
        if fp is None:
            fp = hash(tuple(
                (nid, type(n.op).__name__, repr(n.op), tuple(n.inputs))
                for nid, n in sorted(plan.nodes.items())
            ))
            plan._fingerprint = fp
        return fp

    def _execute_plan_inner(
        self, plan: Plan, bridge_inputs: dict | None = None,
        materialize: bool = True,
    ) -> dict:
        results: dict[int, object] = {}
        outputs: dict = {}
        consumers: dict[int, int] = {}
        for n in plan.nodes.values():
            for i in n.inputs:
                consumers[i] = consumers.get(i, 0) + 1

        def mat_input(nid):
            """Materialize a node's result once; cache for fan-out."""
            r = results[nid]
            if not isinstance(r, HostBatch):
                r = self._materialize(r)
                results[nid] = r
            return r

        for nid in plan.topo_order():
            node = plan.nodes[nid]
            op = node.op
            if isinstance(op, MemorySourceOp):
                tablets = self.table_store.tablets(op.table)
                if not tablets:
                    raise QueryError(f"no table named {op.table!r}")
                # Tablets share relation + string dictionaries (enforced by
                # TableStore); a query scans all of them.
                self._note_scan_freshness(op, tablets)
                base = next((t for t in tablets if len(t.relation)), tablets[0])
                chain = []
                if op.columns is not None:
                    chain.append(
                        MapOp(exprs=tuple((c, _col(c)) for c in op.columns))
                    )
                results[nid] = _Stream(
                    base.relation, dict(base.dicts), chain, tablets, op
                )
            elif isinstance(op, UDTFSourceOp):
                results[nid] = self._run_udtf(op)
            elif isinstance(op, EmptySourceOp):
                results[nid] = _empty_host_batch(
                    Relation(list(op.relation_items))
                )
            elif isinstance(op, (MapOp, FilterOp, AggOp, LimitOp)):
                upstream = results[node.inputs[0]]
                if isinstance(upstream, _PendingAggBridge):
                    # The finalize half of a split aggregate: merge the
                    # shipped partial states and finalize — the agent-mode
                    # form of the bridge collective.
                    if not (isinstance(op, AggOp) and op.mode == "finalize"):
                        raise QueryError(
                            "agg bridge must feed its finalize AggOp"
                        )
                    results[nid] = merge_agg_bridge(self, upstream)
                    continue
                st = self._as_stream(upstream)
                if st.chain and isinstance(st.chain[-1], LimitOp):
                    # A limit terminates its fragment: apply the cap at its
                    # plan position, then keep chaining on the result.
                    st = self._as_stream(self._materialize(st))
                if isinstance(op, AggOp) and any(
                    isinstance(o, AggOp) for o in st.chain
                ):
                    # Two blocking aggs never share a fragment: the first
                    # materializes (its output is small), the second re-
                    # aggregates it (the splitter's cut-at-blocking-op rule,
                    # planner/distributed/splitter/splitter.h:75).
                    st = self._as_stream(self._materialize(st))
                results[nid] = st.extend(op)
            elif isinstance(op, JoinOp):
                fused = try_fused_join(self, nid, node, results, consumers)
                if fused is not None:
                    from .joins import JoinDecision

                    self.last_join_decision = JoinDecision(
                        strategy="fused",
                        reason="dense-domain N:1 in-fragment lookup",
                    )
                    results[nid] = fused
                else:
                    from .joins import stream_join_stats

                    # Ingest-sketch stats must be read BEFORE
                    # materialization (the table provenance dies with
                    # the stream); they steer build-side choice,
                    # capacity estimation and zone skipping.
                    lstats = stream_join_stats(
                        results[node.inputs[0]], op.left_on
                    )
                    rstats = stream_join_stats(
                        results[node.inputs[1]], op.right_on
                    )
                    left = mat_input(node.inputs[0])
                    right = mat_input(node.inputs[1])
                    # Join-buffer pre-sizing (pxbound): the plan-time
                    # capacity estimate covers inputs run-time sketches
                    # cannot see (post-aggregate build sides) — used as
                    # the fallback rung before the historical default.
                    report = self.last_resource_report
                    planned = (
                        report.join_capacity.get(nid)
                        if report is not None else None
                    )
                    results[nid] = _join_dispatch(
                        left, right, op, self,
                        left_stats=lstats, right_stats=rstats,
                        cap_key=(self._plan_fingerprint(plan), nid),
                        planned_capacity=planned,
                    )
            elif isinstance(op, UnionOp):
                mats = [mat_input(i) for i in node.inputs]
                results[nid] = _union_host(mats)
            elif isinstance(op, ResultSinkOp):
                src_id = node.inputs[0]
                r = results[src_id]
                if (
                    not materialize
                    and isinstance(r, _Stream)
                    and consumers.get(src_id, 0) <= 1
                ):
                    # Device-resident result: the readback (and any
                    # overflow rebucket) happens in DeviceResult.to_host.
                    outputs[op.name] = self._run_fragment(r)
                else:
                    outputs[op.name] = mat_input(src_id)
            elif isinstance(op, TableSinkOp):
                hb = mat_input(node.inputs[0])
                self.append_data(op.table, hb)
                # Not a client output (clients iterate result tables);
                # recorded on the engine for callers/tests.
                self.last_table_sinks[op.table] = hb.length
            elif isinstance(op, OTelExportSinkOp):
                from .otel import batch_to_otlp

                payload = batch_to_otlp(mat_input(node.inputs[0]), op.spec)
                self.export_otel(payload, op.spec.endpoint)
            elif isinstance(op, BridgeSinkOp):
                from .bridge import payload_nbytes

                payload = bridge_payload(self, results[node.inputs[0]])
                outputs[("bridge", op.bridge_id)] = payload
                # Wire accounting (QueryResourceUsage): bridge egress is
                # what this fragment ships to the merge tier.
                qstats = self._query_stats
                if qstats is not None and getattr(qstats, "trace", None):
                    qstats.trace.add_wire_bytes(payload_nbytes(payload))
            elif isinstance(op, BridgeSourceOp):
                if not bridge_inputs or op.bridge_id not in bridge_inputs:
                    raise QueryError(f"no input for bridge {op.bridge_id}")
                results[nid] = bind_bridge(bridge_inputs[op.bridge_id])
            else:
                raise QueryError(f"unsupported operator {op}")
            # Fan-out of a stream: materialize once, share the batch —
            # EXCEPT pure table scans (empty/column-select chains over
            # table sources): their windows are device-cache-resident,
            # so each consumer re-scanning them is free, while a
            # materialize would round-trip the whole table through host
            # memory. (Consumers then fold against the table as it is
            # when THEY run — the same snapshot caveat DeviceResult
            # documents for rebuckets.)
            if consumers.get(nid, 0) > 1 and isinstance(results[nid], _Stream):
                st = results[nid]
                from .fragment import _pure_select_map

                pure_scan = (
                    isinstance(st.source, list)
                    and not st.side
                    and _pure_select_map(st.chain) is not None
                )
                if not pure_scan:
                    results[nid] = self._materialize(st)
        return outputs

    def _note_scan_freshness(self, op, tablets) -> None:
        """Stamp result staleness for one table scan onto the query's
        trace: the scan's stop-time (or now, for unbounded scans) minus
        the max event-time watermark across the table's tablets. Host
        attribute reads only — no backend lock, no device work."""
        qstats = self._query_stats
        trace = getattr(qstats, "trace", None) if qstats is not None else None
        if trace is None:
            return
        from ..table_store import table as _table_mod

        wm = _table_mod.max_watermark_ns(tablets)
        if wm is None:
            return  # no time index / nothing appended: no signal
        ref = op.stop_time if op.stop_time is not None else time.time_ns()
        trace.note_freshness_lag(op.table, (int(ref) - wm) / 1e6)

    def export_otel(self, payload: dict, endpoint) -> None:
        """OTel egress. Default: collect in-memory (``otel_exports``,
        initialized in ``__init__``; list.append is atomic under
        concurrent queries); deployments override/replace with an OTLP
        pusher (the reference ships over OTLP gRPC — grpc is gated in
        this environment)."""
        self.otel_exports.append({"endpoint": endpoint, "payload": payload})

    def _run_udtf(self, op: UDTFSourceOp) -> HostBatch:
        """Execute a UDTF source (``udtf_source_node.h`` analog): call its
        fn with this engine as context and shape the rows to the declared
        relation."""
        udtf = self.registry.get_udtf(op.name)
        args = dict(op.args)
        for entry in udtf.init_args:  # declared defaults (3-tuples)
            if len(entry) == 3 and entry[0] not in args:
                args[entry[0]] = entry[2]
        data = udtf.fn(self, **args)
        rel = Relation(list(udtf.relation))
        hb = HostBatch.from_pydict(data, relation=rel, time_cols=())
        return hb

    # -- window fold core -----------------------------------------------------
    def _fold_agg_state(self, stream: "_Stream", frag, stats=None):
        """Stream the source through the fragment's window fold, returning
        the accumulated (unfinalized) group state.

        Equal-capacity device-resident window runs fold through
        ``update_all`` — ONE scan program per chunk of windows instead of
        one dispatch (one tunnel round trip) per window."""
        from ..config import get_flag

        import jax

        init_state, agg_step, _ = self._compile_steps(frag)
        if (
            self.cpu_parallel_fold
            and jax.default_backend() == "cpu"
            and frag.native_fold is not None
            and get_flag("cpu_fold_threads") != 1
        ):
            # CPU backend: XLA executes scatters single-threaded, capping
            # bincount-class aggregations at one core. Route the scatter
            # passes through the native multi-core kernel instead (XLA
            # still runs the elementwise pre-stage + slot packing).
            state = self._fold_agg_state_native(stream, frag, stats)
            if state is not None:
                return state
        state = init_state()
        # Scan-folding exists to amortize the TPU tunnel's ~70ms/dispatch
        # round trip; on the CPU backend dispatches are cheap and the
        # jnp.stack of window planes is a pure memory-bandwidth loss.
        # (DistributedEngine turns it off: update_all is a single-logical-
        # device jit and would bypass the shard_map distributed steps.)
        chunk_w = (
            get_flag("fold_scan_windows")
            if frag.update_all and self.scan_fold
            and jax.default_backend() == "tpu"
            else 0
        )
        pend_cols, pend_lo, pend_hi = [], [], []

        def flush_pending(state):
            if not pend_cols:
                return state
            if len(pend_cols) == 1:
                state = agg_step(state, pend_cols[0], (pend_lo[0], pend_hi[0]))
            else:
                state = frag.update_all(
                    state, tuple(pend_cols),
                    # Host int lists, not device buffers — no sync.
                    np.asarray(pend_lo, dtype=np.int32),  # pxlint: disable=host-sync-hot-path
                    np.asarray(pend_hi, dtype=np.int32),  # pxlint: disable=host-sync-hot-path
                )
            pend_cols.clear()
            pend_lo.clear()
            pend_hi.clear()
            return state

        pipe = self._window_pipeline(stream, stats)
        try:
            for cols, valid in pipe:
                batchable = (
                    chunk_w > 1
                    and isinstance(valid, tuple)
                    and (
                        not pend_cols
                        or _window_shapes(cols) == _window_shapes(pend_cols[0])
                    )
                )
                with _timed(stats, "compute"):
                    if batchable:
                        pend_cols.append(cols)
                        pend_lo.append(valid[0])
                        pend_hi.append(valid[1])
                        if len(pend_cols) >= chunk_w:
                            state = flush_pending(state)
                    else:
                        state = flush_pending(state)
                        state = agg_step(state, cols, valid)
                    _block_if(stats, state)
                if stats is not None:
                    stats.windows += 1
        finally:
            pipe.close()
            self._note_pipeline(pipe)
        with _timed(stats, "compute"):
            state = flush_pending(state)
            _block_if(stats, state)
        return state

    def _fold_agg_state_native(self, stream: "_Stream", frag, stats=None):
        """Fold via the native multi-core segmented-fold kernel.

        Per window, XLA produces (slot ids, per-agg value columns) —
        elementwise work it handles well — and ``native/seg_fold.cc``
        does the scatter passes with one table per core. Output tables
        accumulate across windows IN PLACE (the carries are associative),
        so there is no per-window state or merge at all. Returns None to
        fall back when the kernel is unavailable or a dtype is exotic.
        """
        import jax

        import jax.numpy as jnp

        from ..native import seg_fold_call

        plan = frag.native_fold["plan"]
        inputs_jit = frag.native_fold["inputs_jit"]
        g = len(np.asarray(frag.init_state()["valid"]))
        # One output table per flattened carry leaf, (g+1) rows (slot g
        # is the masked-row trash), pre-filled with the UDA's neutral
        # (init carries are uniform fills by construction).
        _OP = {"count": 0, "sum": 1, "min": 2, "max": 3}
        specs = []  # (op, dtype, arg_index | None) per leaf
        outs = []
        treedefs = []  # (out_name, treedef, n_leaves) for scalar aggs
        digests = []  # (out_name, init, arg_index, w, mw) for sketches
        hist_shift = None
        for j, (out_name, uda_name, init) in enumerate(plan):
            if uda_name == "quantiles" or uda_name.startswith("_quantile_"):
                # Sketch aggs: the kernel accumulates the GLOBAL dual
                # histogram across every window; ONE compress at the end
                # replaces the XLA path's per-window compress+merge
                # (histogram addition is exact — strictly less work,
                # no added error).
                from ..ops.tdigest import _hist_bins

                b = _hist_bins(g)
                if g * b > (1 << 22):  # host-table budget: XLA instead
                    return None
                hist_shift = 32 - b.bit_length() + 1
                digests.append((
                    out_name, init, j,
                    np.zeros(g * b, dtype=np.float32),
                    np.zeros(g * b, dtype=np.float32),
                ))
                continue
            leaves, treedef = jax.tree_util.tree_flatten(init(1))
            treedefs.append((out_name, treedef, len(leaves)))
            for li, leaf in enumerate(leaves):
                leaf = np.asarray(leaf)
                if uda_name == "mean":
                    # (sum, count) carry: leaf 0 sums the arg, leaf 1
                    # counts rows.
                    op, arg_i = (1, j) if li == 0 else (0, None)
                elif uda_name == "count":
                    op, arg_i = 0, None
                else:
                    op, arg_i = _OP[uda_name], j
                specs.append((op, leaf.dtype, arg_i))
                outs.append(np.full(g + 1, leaf.reshape(-1)[0], dtype=leaf.dtype))
        if not any(op == 0 for op, _dt, _a in specs):
            # Validity needs a row count; add a hidden one.
            specs.append((0, np.dtype(np.int64), None))
            outs.append(np.zeros(g + 1, dtype=np.int64))

        from ..native import np_view, seg_fold_raw_call, tdigest_hist_call

        raw = frag.native_fold.get("raw")
        if digests:
            # Sketch bins derive from the value planes the jit form
            # produces; the raw fast path handles scalar ops only.
            raw = None
        oob_any = False
        oob_acc = None  # ONE device scalar, read back ONCE post-loop
        xla_fallback = False  # aborted mid-stream: XLA re-runs the fold
        pipe = self._window_pipeline(stream, stats)
        try:
            for cols, valid in pipe:
                with _timed(stats, "compute"):
                    if raw is not None and isinstance(valid, tuple):
                        # Zero-device-work path: the kernel reads the
                        # staged planes directly (keys packed in-kernel;
                        # np_view shares the buffers, no copies).
                        planes = [
                            np_view(cols[c][0]) for c in raw["key_cols"]
                        ]
                        vals = [
                            None if a is None
                            else np_view(cols[raw["arg_cols"][a]][0])
                            for _op, _dt, a in specs
                        ]
                        oob_n = seg_fold_raw_call(
                            planes, raw["key_specs"], int(valid[0]),
                            int(valid[1]), g, specs, vals, outs,
                        )
                        if oob_n is not None:
                            oob_any = oob_any or oob_n > 0
                            if stats is not None:
                                stats.windows += 1
                            continue
                        # Unsupported dtype combo: fall through to the
                        # jit form for this (and subsequent) windows.
                    # NOTE: keep gids_dev/args referenced while the kernel
                    # reads their zero-copy views (np_view aliases
                    # buffers).
                    gids_dev, args, oob = inputs_jit(cols, valid)
                    gids = np_view(gids_dev)
                    vals = [
                        None if a is None else np_view(args[a])
                        for _op, _dt, a in specs
                    ]
                    if specs and not seg_fold_call(gids, g, specs, vals, outs):
                        xla_fallback = True
                        return None  # exotic dtype combo: XLA fallback
                    for _name, _init, j, w, mw in digests:
                        v = np_view(args[j])
                        if str(v.dtype) != "float32":
                            xla_fallback = True
                            return None
                        if not tdigest_hist_call(gids, v, g, hist_shift, w, mw):
                            xla_fallback = True
                            return None
                    # Deferred: a bool() here would force a device sync
                    # EVERY window, serializing the prefetch pipeline —
                    # accumulate on device (one scalar, O(1) memory)
                    # and read back once after the loop.
                    oob_acc = (
                        oob if oob_acc is None
                        else jnp.logical_or(oob_acc, oob)
                    )
                if stats is not None:
                    stats.windows += 1
        finally:
            pipe.close()
            if not xla_fallback:
                # A fallback's windows re-run through the XLA fold's own
                # pipeline — noting the aborted one would double-count.
                self._note_pipeline(pipe)
        if oob_acc is not None:
            # The one readback for the whole fold (materialization
            # boundary). # pxlint: disable=host-sync-hot-path
            oob_any = oob_any or bool(np.asarray(oob_acc))
        carries = {}
        k = 0
        for out_name, treedef, n_leaves in treedefs:
            leaves = [jnp.asarray(outs[k + i][:g]) for i in range(n_leaves)]
            carries[out_name] = jax.tree_util.tree_unflatten(treedef, leaves)
            k += n_leaves
        for out_name, init, _j, w, mw in digests:
            # ONE compression of the global histogram into the [G, K]
            # digest carry (batch_to_digest's ordered compress).
            from ..ops.tdigest import _compress

            kk = int(np.asarray(init(1)[0]).shape[1])
            b = len(w) // g
            w2 = w.reshape(g, b)
            means = np.where(w2 > 0, mw.reshape(g, b) / np.maximum(w2, 1e-30),
                             0.0).astype(np.float32)
            carries[out_name] = _compress(
                jnp.asarray(means), jnp.asarray(w2), kk, ordered=True
            )
        count_out = next(
            o for (op, _dt, _a), o in zip(specs, outs) if op == 0
        )
        return {
            "keys": (),
            "valid": jnp.asarray(count_out[:g] > 0),
            "carries": carries,
            "overflow": jnp.asarray(oob_any),
        }

    # -- internals -----------------------------------------------------------
    def _as_stream(self, res) -> _Stream:
        if isinstance(res, _Stream):
            return res
        hb: HostBatch = res
        return _Stream(hb.relation, dict(hb.dicts), [], hb)

    def _windows(self, stream: _Stream, stats=None):
        """Slice source batches into <= window_rows chunks."""
        if isinstance(stream.source, HostBatch):
            batches = [stream.source]
        else:
            from .zoneskip import chain_pruner

            sop = stream.source_op
            tables = (
                stream.source if isinstance(stream.source, list) else [stream.source]
            )
            batches = itertools.chain.from_iterable(
                t.scan(
                    sop.start_time if sop else None,
                    sop.stop_time if sop else None,
                    prune=chain_pruner(
                        t, stream.chain, getattr(t, "dicts", stream.dicts),
                        stats=stats,
                    ),
                )
                for t in tables
            )
        for b in batches:
            for off in range(0, max(b.length, 1), self.window_rows):
                if b.length == 0:
                    yield b
                    break
                idx = slice(off, min(off + self.window_rows, b.length))
                if idx.start == 0 and idx.stop == b.length:
                    yield b
                else:
                    yield HostBatch(
                        relation=b.relation,
                        cols={
                            n: tuple(p[idx] for p in ps) for n, ps in b.cols.items()
                        },
                        length=idx.stop - idx.start,
                        dicts=b.dicts,
                    )

    # -- execution seams (overridden by DistributedEngine) -------------------
    # Whether this engine may consume device-resident table windows (HBM
    # cold store). DistributedEngine stages row-sharded instead.
    device_residency = True
    # Whether N:1 joins may fuse into probe fragments as device lookups
    # (joins.try_fused_join); DistributedEngine gates this on mesh
    # side-table replication.
    fused_lookup_join = True
    # CPU-backend thread-parallel window folding; DistributedEngine turns
    # it off (its fold steps run inside shard_map over the mesh).
    cpu_parallel_fold = True
    # TPU scan-fold window batching (update_all); DistributedEngine turns
    # it off for the same reason — update_all is not a distributed step.
    scan_fold = True

    def _window_capacity(self, length: int) -> int:
        return max(bucket_capacity(self.window_rows), bucket_capacity(length))

    def _stage(self, hb: HostBatch, capacity: int):
        """Pad a host window to capacity and place it on device."""
        db = hb.to_device(capacity)
        return db.cols, db.valid

    def _check_cancel(self) -> None:
        c = getattr(self, "_cancel", None)
        if c is not None and c.is_set():
            raise QueryCancelled("query cancelled")

    def _staged_windows(self, stream: "_Stream", stats=None):
        """Yield (cols, valid) device-staged windows for a stream.

        Table sources use the device-resident window cache (zero
        host->device transfer once staged — SURVEY.md §7 stage 1 "HBM as
        cold"); host batches and distributed engines stage per window.
        Streams with side inputs (fused lookup-join build tables) carry
        them in every window's cols under ``__side__`` — device_put once
        per query, then reused as runtime args (never closure constants).
        """
        if stream.side:
            yield from self._staged_windows_with_side(stream, stats)
            return
        yield from self._staged_windows_inner(stream, stats)

    def _window_pipeline(self, stream: "_Stream", stats=None) -> WindowPipeline:
        """Pipelined view of ``_staged_windows``: staging for window N+1
        runs on a prefetch thread while the caller computes window N
        (``pipeline_depth`` windows in flight; 1 = serial, no thread).
        Callers MUST wrap iteration in try/finally close() — that is the
        no-leaked-threads / no-use-after-cancel contract."""
        return WindowPipeline(
            self._staged_windows(stream, stats), self.pipeline_depth,
            cancel=getattr(self, "_cancel", None), stats=stats,
        )

    def _note_pipeline(self, pipe: WindowPipeline) -> None:
        """Fold a finished pipeline's counters into the per-query snapshot
        (``scratch.pipeline``, which the query's trace snapshots at end;
        falls back to the engine-level snapshot for callers outside an
        execute_plan scope — the streaming cursor, DeviceResult
        rebuckets) and the engine-lifetime totals (state-locked: the
        totals are read-modify-write shared across concurrent queries).
        """
        c = pipe.counters()
        s = self._scratch
        with self._state_lock:
            if s is not None:
                lp = s.pipeline
                if lp is None:
                    lp = s.pipeline = {
                        "depth": c["depth"], "windows": 0,
                        "stage_secs": 0.0, "stall_secs": 0.0,
                    }
            else:
                lp = self._last_pipeline
                if lp is None:
                    lp = self._last_pipeline = {
                        "depth": c["depth"], "windows": 0,
                        "stage_secs": 0.0, "stall_secs": 0.0,
                    }
            lp["depth"] = c["depth"]
            tot = self.pipeline_totals
            for d in (lp, tot):
                d["windows"] += c["windows"]
                d["stage_secs"] += c["stage_secs"]
                d["stall_secs"] += c["stall_secs"]

    def _put_side(self, v):
        """Stage one fused-join side table (DistributedEngine replicates
        over its mesh instead)."""
        import jax

        return jax.device_put(v)

    def _staged_windows_with_side(self, stream: "_Stream", stats=None):
        side = {k: self._put_side(v) for k, v in stream.side.items()}
        for cols, valid in self._staged_windows_inner(stream, stats):
            yield {**cols, "__side__": side}, valid

    def _staged_windows_inner(self, stream: "_Stream", stats=None):
        from ..config import get_flag
        from ..table_store.coldstore import take_decode_meter
        from .zoneskip import chain_pruner

        use_cache = (
            self.device_residency
            and get_flag("device_residency")
            and not isinstance(stream.source, HostBatch)
        )
        if use_cache:
            sop = stream.source_op
            start = sop.start_time if sop else None
            stop = sop.stop_time if sop else None
            tables = (
                stream.source
                if isinstance(stream.source, list)
                else [stream.source]
            )
            for t in tables:
                if getattr(t, "_backend", None) is None:
                    continue
                pruner = chain_pruner(
                    t, stream.chain, getattr(t, "dicts", stream.dicts),
                    stats=stats,
                )
                for win, lo, hi in t.device_scan(
                    start, stop, window_rows=self.window_rows, prune=pruner
                ):
                    self._check_cancel()
                    # Cold-tier decode ran inside device_scan's staging
                    # (on THIS thread — the pipeline producer when
                    # prefetching): charge it to the query via the
                    # locked fragment stats, the only query-scoped
                    # object reachable from the producer thread.
                    dsec, dbytes = take_decode_meter()
                    if stats is not None:
                        if dsec or dbytes:
                            stats.add("decode", dsec, nbytes=dbytes)
                        stats.rows_in += hi - lo
                    # (lo, hi) scalar pair, not a mask: the fragment
                    # builds the iota mask INSIDE its program — a
                    # separate mask dispatch costs a tunnel round trip
                    # per window. np scalars stay dynamic (no retrace
                    # per offset).
                    yield win.cols, (
                        np.int32(lo - win.row0), np.int32(hi - win.row0)
                    )
            return
        for hb in self._windows(stream, stats=stats):
            self._check_cancel()
            dsec, dbytes = take_decode_meter()
            if stats is not None and (dsec or dbytes):
                stats.add("decode", dsec, nbytes=dbytes)
            with _timed(stats, "stage", rows=hb.length, nbytes=hb.nbytes):
                cols, valid = self._stage(hb, self._window_capacity(hb.length))
                _block_if(stats, cols)
            if stats is not None:
                stats.rows_in += hb.length
            yield cols, valid

    def _compile_steps(self, frag):
        """(init_state_fn, agg_step, rows_step) for a compiled fragment."""
        if frag.is_agg:
            return frag.init_state, frag.update, None
        return None, None, frag.update

    def _materialize(self, res) -> HostBatch:
        if isinstance(res, HostBatch):
            return res
        if isinstance(res, DeviceResult):
            return res.to_host()
        dr = self._run_fragment(res)
        if isinstance(dr, DeviceResult):
            return dr.to_host()
        return dr

    def _run_fragment(self, stream: "_Stream", frag=None):
        """Run a stream's fragment; agg chains return a DeviceResult
        (device-resident, no host readback — the first device-to-host
        transfer permanently switches the axon tunnel into a slow
        synchronous dispatch mode, so callers defer it as long as
        possible), non-agg chains a HostBatch. Callers that captured
        domain metadata from a probe compile pass that fragment in so
        the run cannot recompile against racing stats."""
        if frag is None:
            frag = compile_fragment(
                stream.chain, stream.relation, stream.dicts, self.registry,
                col_stats=_stream_col_stats(stream),
            )
        qstats = getattr(self, "_query_stats", None)
        stats = qstats.new_fragment(stream.chain) if qstats is not None else None

        if frag.is_agg:
            state = self._fold_agg_state(stream, frag, stats)
            with _timed(stats, "finalize"):
                cols, valid, overflow = frag.finalize(state)
                _block_if(stats, (cols, valid, overflow))
            return DeviceResult(
                self, stream, frag, cols, valid, overflow, stats,
                qstats=getattr(self, "_query_stats", None),
            )

        # Non-agg: stream windows, stop early once a limit is satisfied.
        _, _, rows_step = self._compile_steps(frag)
        pieces, total = [], 0
        pipe = self._window_pipeline(stream, stats)
        try:
            for cols, valid in pipe:
                with _timed(stats, "compute"):
                    out_cols, out_valid = rows_step(cols, valid)
                    _block_if(stats, (out_cols, out_valid))
                if stats is not None:
                    stats.windows += 1
                with _timed(stats, "materialize"):
                    piece = _to_host_batch(
                        frag.out_meta, out_cols, np.asarray(out_valid)
                    )
                pieces.append(piece)
                total += piece.length
                if frag.limit is not None and total >= frag.limit:
                    break
        finally:
            pipe.close()
            self._note_pipeline(pipe)
        out = _concat_host(pieces, frag.relation)
        if stats is not None:
            stats.rows_out = out.length
        return _apply_limit(out, frag.limit)
