"""Query engine: plan DAG -> streamed, jit-compiled execution.

Reference parity: the Carnot facade (``src/carnot/carnot.h:39-95``
Carnot::ExecutePlan) + ExecutionGraph (``exec/exec_graph.cc:295``). The
TPU execution model:

- Each maximal linear chain of Map/Filter/Agg/Limit over one input
  compiles to a single fragment program (see fragment.py).
- Tables stream through in fixed-capacity windows (static shapes -> one
  compile, reused every window; the Table::Cursor batch loop analog).
- DAG joints (Join/Union) materialize their small (post-agg) inputs and
  continue; joins run host-side on dense ids (N:1, right-unique).
- Aggregation group state survives across windows via the regroup
  machinery, so a billion-row table aggregates in O(windows) device
  dispatches with O(G) memory.
"""

from __future__ import annotations

import functools
import itertools
import json
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.dtypes import DataType, host_dtypes
from ..types.relation import Relation
from ..types.strings import NULL_ID, StringDictionary
from ..udf.registry import Registry, default_registry
from .fragment import ColumnMeta, compile_fragment
from .plan import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)


@dataclass
class AggStatePayload:
    """Partial-agg state shipped across a bridge (agent mode).

    The UDA ``Serialize``/``DeSerialize`` analog (``udf.h:99-100``): the
    serialized form IS the carry pytree plus enough metadata for the
    merge tier to recompile the identical fragment and realign string
    dictionary ids. String-valued *carries* (e.g. ``any`` over a string
    column) are not realigned — only group keys are; such UDAs need a
    shared dictionary to cross agents.
    """

    chain: tuple  # fragment ops [pre..., AggOp]
    input_relation: object  # Relation at fragment input
    input_dicts: dict  # {col: StringDictionary} at fragment input
    state: dict  # group-state pytree (numpy leaves)


@dataclass
class RowsPayload:
    """Materialized rows shipped across a bridge (plain GRPCSink analog)."""

    batch: HostBatch


@dataclass
class _PendingAggBridge:
    """Agg-bridge payloads awaiting their finalize AggOp."""

    payloads: list  # list[AggStatePayload]


class QueryError(Exception):
    pass


@dataclass
class InMemoryTable:
    """Minimal table: shared per-column dictionaries + row batches.

    Stand-in for the full hot/cold Table (stage 6); the engine only needs
    ``scan()`` -> HostBatch windows with shared dictionaries.
    """

    name: str
    relation: Relation
    dicts: dict = field(default_factory=dict)
    batches: list = field(default_factory=list)

    def append(self, data, time_cols=("time_",)) -> HostBatch:
        hb = (
            data
            if isinstance(data, HostBatch)
            else HostBatch.from_pydict(
                data,
                relation=self.relation if len(self.relation) else None,
                time_cols=time_cols,
                dicts=self.dicts,
            )
        )
        if not len(self.relation):
            self.relation = hb.relation
        for col, d in hb.dicts.items():
            self.dicts.setdefault(col, d)
        self.batches.append(hb)
        return hb

    @property
    def num_rows(self) -> int:
        return sum(b.length for b in self.batches)

    def scan(self, start_time=None, stop_time=None):
        """Yield batches, time-bounded on the ``time_`` column."""
        for b in self.batches:
            if (start_time is None and stop_time is None) or not b.relation.has_column(
                "time_"
            ):
                yield b
                continue
            t = b.cols["time_"][0]
            keep = np.ones(b.length, dtype=bool)
            if start_time is not None:
                keep &= t >= start_time
            if stop_time is not None:
                keep &= t < stop_time
            if keep.all():
                yield b
            elif keep.any():
                idx = np.nonzero(keep)[0]
                yield HostBatch(
                    relation=b.relation,
                    cols={n: tuple(p[idx] for p in ps) for n, ps in b.cols.items()},
                    length=len(idx),
                    dicts=b.dicts,
                )


@dataclass
class _Stream:
    relation: Relation
    dicts: dict
    chain: list
    source: object  # InMemoryTable | HostBatch
    source_op: Optional[MemorySourceOp] = None

    def extend(self, op):
        return _Stream(self.relation, self.dicts, self.chain + [op], self.source, self.source_op)


class Engine:
    """Owns tables + registry; executes plans. (EngineState analog,
    ``src/carnot/engine_state.h``.)"""

    def __init__(self, registry: Registry | None = None, window_rows: int = 1 << 17):
        from ..table_store import TableStore

        self.registry = registry or default_registry()
        self.table_store = TableStore()
        self.window_rows = window_rows

    @property
    def tables(self) -> dict:
        """{name: default-tablet (or first) Table} view over the store."""
        out = {}
        for n in self.table_store.table_names():
            t = self.table_store.get_table(n)
            if t is None:
                tablets = self.table_store.tablets(n)
                t = tablets[0] if tablets else None
            out[n] = t
        return out

    # -- table management ----------------------------------------------------
    def create_table(self, name: str, relation: Relation | None = None,
                     max_bytes: int = -1):
        return self.table_store.add_table(name, relation, max_bytes=max_bytes)

    def append_data(self, name: str, data, time_cols=("time_",)):
        """Push path (Stirling's RegisterDataPushCallback analog)."""
        return self.table_store.append_data(name, data, time_cols=time_cols)

    # -- execution -----------------------------------------------------------
    def execute_query(self, query: str, now_ns: int = 0,
                      max_output_rows: int = 10_000) -> dict:
        """Compile a PxL script and execute it (Carnot::ExecuteQuery parity,
        ``src/carnot/carnot.cc:122-134``). Returns {output name: HostBatch}."""
        from ..planner import CompilerState, compile_pxl

        state = CompilerState(
            schemas={n: t.relation for n, t in self.tables.items()},
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=max_output_rows,
        )
        compiled = compile_pxl(query, state)
        return self.execute_plan(compiled.plan)

    def set_metadata_state(self, state) -> None:
        """Attach k8s metadata; rebinds the metadata UDFs to a snapshot of
        ``state`` (reference: per-query AgentMetadataState), preserving all
        other registrations on this engine's registry."""
        from ..metadata.funcs import METADATA_FUNC_NAMES, register_metadata_funcs

        self.metadata_state = state
        reg = self.registry.clone("engine", exclude=METADATA_FUNC_NAMES)
        register_metadata_funcs(reg, state)
        self.registry = reg

    def execute_plan(
        self, plan: Plan, bridge_inputs: dict | None = None
    ) -> dict:
        """Execute a plan. Whole plans return {sink name: HostBatch}.

        Split-fragment plans (from the distributed splitter, agent mode):
        a plan ending in BridgeSinkOps additionally returns
        {("bridge", id): payload}; a merge plan starting from
        BridgeSourceOps reads ``bridge_inputs`` = {bridge id: [payloads]}.
        """
        results: dict[int, object] = {}
        outputs: dict = {}
        consumers: dict[int, int] = {}
        for n in plan.nodes.values():
            for i in n.inputs:
                consumers[i] = consumers.get(i, 0) + 1

        def mat_input(nid):
            """Materialize a node's result once; cache for fan-out."""
            r = results[nid]
            if not isinstance(r, HostBatch):
                r = self._materialize(r)
                results[nid] = r
            return r

        for nid in plan.topo_order():
            node = plan.nodes[nid]
            op = node.op
            if isinstance(op, MemorySourceOp):
                tablets = self.table_store.tablets(op.table)
                if not tablets:
                    raise QueryError(f"no table named {op.table!r}")
                # Tablets share relation + string dictionaries (enforced by
                # TableStore); a query scans all of them.
                base = next((t for t in tablets if len(t.relation)), tablets[0])
                chain = []
                if op.columns is not None:
                    chain.append(
                        MapOp(exprs=tuple((c, _col(c)) for c in op.columns))
                    )
                results[nid] = _Stream(
                    base.relation, dict(base.dicts), chain, tablets, op
                )
            elif isinstance(op, UDTFSourceOp):
                results[nid] = self._run_udtf(op)
            elif isinstance(op, EmptySourceOp):
                results[nid] = _empty_host_batch(
                    Relation(list(op.relation_items))
                )
            elif isinstance(op, (MapOp, FilterOp, AggOp, LimitOp)):
                upstream = results[node.inputs[0]]
                if isinstance(upstream, _PendingAggBridge):
                    # The finalize half of a split aggregate: merge the
                    # shipped partial states and finalize — the agent-mode
                    # form of the bridge collective.
                    if not (isinstance(op, AggOp) and op.mode == "finalize"):
                        raise QueryError(
                            "agg bridge must feed its finalize AggOp"
                        )
                    results[nid] = self._merge_agg_bridge(upstream)
                    continue
                st = self._as_stream(upstream)
                if st.chain and isinstance(st.chain[-1], LimitOp):
                    # A limit terminates its fragment: apply the cap at its
                    # plan position, then keep chaining on the result.
                    st = self._as_stream(self._materialize(st))
                if isinstance(op, AggOp) and any(
                    isinstance(o, AggOp) for o in st.chain
                ):
                    # Two blocking aggs never share a fragment: the first
                    # materializes (its output is small), the second re-
                    # aggregates it (the splitter's cut-at-blocking-op rule,
                    # planner/distributed/splitter/splitter.h:75).
                    st = self._as_stream(self._materialize(st))
                results[nid] = st.extend(op)
            elif isinstance(op, JoinOp):
                left = mat_input(node.inputs[0])
                right = mat_input(node.inputs[1])
                results[nid] = _join_dispatch(left, right, op)
            elif isinstance(op, UnionOp):
                mats = [mat_input(i) for i in node.inputs]
                results[nid] = _union_host(mats)
            elif isinstance(op, ResultSinkOp):
                outputs[op.name] = mat_input(node.inputs[0])
            elif isinstance(op, OTelExportSinkOp):
                from .otel import batch_to_otlp

                payload = batch_to_otlp(mat_input(node.inputs[0]), op.spec)
                self.export_otel(payload, op.spec.endpoint)
            elif isinstance(op, BridgeSinkOp):
                outputs[("bridge", op.bridge_id)] = self._bridge_payload(
                    results[node.inputs[0]]
                )
            elif isinstance(op, BridgeSourceOp):
                if not bridge_inputs or op.bridge_id not in bridge_inputs:
                    raise QueryError(f"no input for bridge {op.bridge_id}")
                results[nid] = self._bind_bridge(bridge_inputs[op.bridge_id])
            else:
                raise QueryError(f"unsupported operator {op}")
            # Fan-out of a stream: materialize once, share the batch.
            if consumers.get(nid, 0) > 1 and isinstance(results[nid], _Stream):
                results[nid] = self._materialize(results[nid])
        return outputs

    def export_otel(self, payload: dict, endpoint) -> None:
        """OTel egress. Default: collect in-memory (``otel_exports``);
        deployments override/replace with an OTLP pusher (the reference
        ships over OTLP gRPC — grpc is gated in this environment)."""
        if not hasattr(self, "otel_exports"):
            self.otel_exports = []
        self.otel_exports.append({"endpoint": endpoint, "payload": payload})

    def _run_udtf(self, op: UDTFSourceOp) -> HostBatch:
        """Execute a UDTF source (``udtf_source_node.h`` analog): call its
        fn with this engine as context and shape the rows to the declared
        relation."""
        udtf = self.registry.get_udtf(op.name)
        args = dict(op.args)
        for entry in udtf.init_args:  # declared defaults (3-tuples)
            if len(entry) == 3 and entry[0] not in args:
                args[entry[0]] = entry[2]
        data = udtf.fn(self, **args)
        rel = Relation(list(udtf.relation))
        hb = HostBatch.from_pydict(data, relation=rel, time_cols=())
        return hb

    # -- bridge (agent-mode) machinery ----------------------------------------
    def _fold_agg_state(self, stream: "_Stream", frag):
        """Stream the source through the fragment's window fold, returning
        the accumulated (unfinalized) group state."""
        init_state, agg_step, _ = self._compile_steps(frag)
        state = init_state()
        for hb in self._windows(stream):
            cols, valid = self._stage(hb, self._window_capacity(hb.length))
            state = agg_step(state, cols, valid)
        return state

    def _bridge_payload(self, res):
        """Produce a BridgeSink payload: partial-agg state for agg chains,
        materialized rows otherwise (GRPCSinkNode's two modes)."""
        if isinstance(res, _Stream) and any(
            isinstance(o, AggOp) for o in res.chain
        ):
            import jax

            frag = compile_fragment(
                res.chain, res.relation, res.dicts, self.registry
            )
            state = self._fold_agg_state(res, frag)
            return AggStatePayload(
                chain=tuple(res.chain),
                input_relation=res.relation,
                input_dicts=dict(res.dicts),
                state=jax.tree_util.tree_map(np.asarray, state),
            )
        return RowsPayload(batch=self._materialize(res))

    def _bind_bridge(self, payloads):
        payloads = payloads if isinstance(payloads, list) else [payloads]
        if not payloads:
            raise QueryError("bridge received no payloads")
        if all(isinstance(p, RowsPayload) for p in payloads):
            return _union_host([p.batch for p in payloads])
        if all(isinstance(p, AggStatePayload) for p in payloads):
            return _PendingAggBridge(payloads)
        raise QueryError("mixed payload kinds on one bridge")

    def _merge_agg_bridge(self, pending: _PendingAggBridge) -> HostBatch:
        """Merge shipped partial-agg states and finalize.

        The agent-mode replacement for the on-mesh collective: states from
        k agents fold through the fragment's associative merge, after the
        group-key string ids of every agent are remapped into one
        canonical dictionary (the reference ships raw strings over GRPC,
        so alignment is implicit there; here ids must be reconciled).
        """
        import jax
        import jax.numpy as jnp

        from .fragment import _bind_pre_stage, _split_chain

        p0 = pending.payloads[0]
        frag = compile_fragment(
            list(p0.chain), p0.input_relation, dict(p0.input_dicts), self.registry
        )
        key_plane_index = frag.key_plane_index
        group_rel = frag.group_relation
        pre, _agg, _post, _limit = _split_chain(list(p0.chain))
        # Per-agent post-pre-stage dictionaries for the group columns.
        per_agent_dicts = []
        for p in pending.payloads:
            _, rel1_a, dicts1 = _bind_pre_stage(
                list(pre), p.input_relation, dict(p.input_dicts), self.registry
            )
            if tuple(rel1_a.items()) != tuple(group_rel.items()):
                raise QueryError(
                    f"bridge schema mismatch: {rel1_a} vs {group_rel}"
                )
            per_agent_dicts.append(dicts1)
        # Canonical dictionary + id remap per string group column.
        canonical: dict[str, StringDictionary] = {}
        states = []
        for p, dicts1 in zip(pending.payloads, per_agent_dicts):
            keys = list(p.state["keys"])
            for pi, (c, i) in enumerate(key_plane_index):
                if group_rel.col_type(c) != DataType.STRING or i != 0:
                    continue
                src = dicts1.get(c)
                if src is None:
                    continue
                dst = canonical.setdefault(c, StringDictionary())
                remap = np.fromiter(
                    (dst.get_or_add(s) for s in src.strings),
                    dtype=np.int32,
                    count=len(src),
                )
                ids = np.asarray(keys[pi])
                if len(remap) == 0:
                    # Empty dictionary (agent had no rows): every slot is
                    # already the null id — nothing to remap.
                    keys[pi] = np.full_like(ids, NULL_ID, dtype=np.int32)
                else:
                    keys[pi] = np.where(
                        ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID
                    ).astype(np.int32)
            states.append({**p.state, "keys": tuple(keys)})
        merge = jax.jit(frag.merge_states)
        acc = jax.tree_util.tree_map(jnp.asarray, states[0])
        for s in states[1:]:
            acc = merge(acc, jax.tree_util.tree_map(jnp.asarray, s))
        cols, valid, overflow = frag.finalize(acc)
        if bool(overflow):
            raise QueryError(
                "group-by overflow merging bridge states; raise max_groups"
            )
        meta = [
            (
                ColumnMeta(m.name, m.dtype, dict=canonical[m.name])
                if m.name in canonical
                else m
            )
            for m in frag.out_meta
        ]
        return _to_host_batch(meta, cols, np.asarray(valid))

    # -- internals -----------------------------------------------------------
    def _as_stream(self, res) -> _Stream:
        if isinstance(res, _Stream):
            return res
        hb: HostBatch = res
        return _Stream(hb.relation, dict(hb.dicts), [], hb)

    def _windows(self, stream: _Stream):
        """Slice source batches into <= window_rows chunks."""
        if isinstance(stream.source, HostBatch):
            batches = [stream.source]
        else:
            sop = stream.source_op
            tables = (
                stream.source if isinstance(stream.source, list) else [stream.source]
            )
            batches = itertools.chain.from_iterable(
                t.scan(
                    sop.start_time if sop else None, sop.stop_time if sop else None
                )
                for t in tables
            )
        for b in batches:
            for off in range(0, max(b.length, 1), self.window_rows):
                if b.length == 0:
                    yield b
                    break
                idx = slice(off, min(off + self.window_rows, b.length))
                if idx.start == 0 and idx.stop == b.length:
                    yield b
                else:
                    yield HostBatch(
                        relation=b.relation,
                        cols={
                            n: tuple(p[idx] for p in ps) for n, ps in b.cols.items()
                        },
                        length=idx.stop - idx.start,
                        dicts=b.dicts,
                    )

    # -- execution seams (overridden by DistributedEngine) -------------------
    def _window_capacity(self, length: int) -> int:
        return max(bucket_capacity(self.window_rows), bucket_capacity(length))

    def _stage(self, hb: HostBatch, capacity: int):
        """Pad a host window to capacity and place it on device."""
        db = hb.to_device(capacity)
        return db.cols, db.valid

    def _compile_steps(self, frag):
        """(init_state_fn, agg_step, rows_step) for a compiled fragment."""
        if frag.is_agg:
            return frag.init_state, frag.update, None
        return None, None, frag.update

    def _materialize(self, res) -> HostBatch:
        if isinstance(res, HostBatch):
            return res
        stream: _Stream = res
        frag = compile_fragment(
            stream.chain, stream.relation, stream.dicts, self.registry
        )

        if frag.is_agg:
            state = self._fold_agg_state(stream, frag)
            cols, valid, overflow = frag.finalize(state)
            if bool(overflow):
                raise QueryError(
                    "group-by overflow: more distinct groups than max_groups; "
                    "raise AggOp.max_groups"
                )
            out = _to_host_batch(frag.out_meta, cols, np.asarray(valid))
            return _apply_limit(out, frag.limit)

        # Non-agg: stream windows, stop early once a limit is satisfied.
        _, _, rows_step = self._compile_steps(frag)
        pieces, total = [], 0
        for hb in self._windows(stream):
            cols, valid = self._stage(hb, self._window_capacity(hb.length))
            out_cols, out_valid = rows_step(cols, valid)
            piece = _to_host_batch(frag.out_meta, out_cols, np.asarray(out_valid))
            pieces.append(piece)
            total += piece.length
            if frag.limit is not None and total >= frag.limit:
                break
        out = _concat_host(pieces, frag.relation)
        return _apply_limit(out, frag.limit)


def _col(name):
    from .plan import ColumnRef

    return ColumnRef(name)


def _to_host_batch(meta_list, cols, valid) -> HostBatch:
    idx = np.nonzero(valid)[0]
    out_cols: dict = {}
    dicts: dict = {}
    rel_items = []
    for m in meta_list:
        if m.struct_fields is not None:
            planes = np.asarray(cols[m.name][0])[idx]  # [rows, k] floats
            d = StringDictionary()
            ids = np.fromiter(
                (
                    d.get_or_add(
                        json.dumps(
                            {f: round(float(v), 6) for f, v in zip(m.struct_fields, row)}
                        )
                    )
                    for row in planes
                ),
                dtype=np.int32,
                count=len(planes),
            )
            out_cols[m.name] = (ids,)
            dicts[m.name] = d
            rel_items.append((m.name, DataType.STRING))
            continue
        hdts = host_dtypes(m.dtype)
        out_cols[m.name] = tuple(
            np.asarray(p)[idx].astype(h) for p, h in zip(cols[m.name], hdts)
        )
        if m.dict is not None:
            dicts[m.name] = m.dict
        rel_items.append((m.name, m.dtype))
    return HostBatch(
        relation=Relation(rel_items), cols=out_cols, length=len(idx), dicts=dicts
    )


def _empty_host_batch(relation, dicts=None) -> HostBatch:
    cols = {
        n: tuple(np.empty(0, dtype=h) for h in host_dtypes(t))
        for n, t in relation.items()
    }
    return HostBatch(relation=relation, cols=cols, length=0, dicts=dict(dicts or {}))


def _concat_host(pieces, relation) -> HostBatch:
    nonempty = [p for p in pieces if p.length > 0]
    if not nonempty:
        dicts = pieces[0].dicts if pieces else {}
        return _empty_host_batch(relation, dicts)
    pieces = nonempty
    first = pieces[0]
    if len(pieces) == 1:
        return first
    cols = {
        n: tuple(
            np.concatenate([p.cols[n][i] for p in pieces])
            for i in range(len(first.cols[n]))
        )
        for n in first.relation.column_names
    }
    return HostBatch(
        relation=first.relation,
        cols=cols,
        length=sum(p.length for p in pieces),
        dicts=first.dicts,
    )


def _apply_limit(hb: HostBatch, limit) -> HostBatch:
    if limit is None or hb.length <= limit:
        return hb
    return HostBatch(
        relation=hb.relation,
        cols={n: tuple(p[:limit] for p in ps) for n, ps in hb.cols.items()},
        length=limit,
        dicts=hb.dicts,
    )


def _key_tuples(hb: HostBatch, on, remaps):
    keys = []
    for c in on:
        ids = hb.cols[c][0]
        if c in remaps:
            # Null string ids (-1) must stay null, not wrap to the last entry.
            ids = np.where(
                ids >= 0, remaps[c][np.clip(ids, 0, None)], NULL_ID
            ).astype(ids.dtype)
        keys.append(ids)
    extra = [hb.cols[c][1] for c in on if len(hb.cols[c]) > 1]
    return list(zip(*(list(k) for k in (keys + extra)))) if keys else []


# Inputs smaller than this run the host dict join (when N:1 applies);
# larger inputs and right/outer/N:M joins go to the device kernel.
DEVICE_JOIN_MIN_ROWS = 1 << 15


def _join_dispatch(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """Route a join to the host N:1 path or the device N:M kernel.

    Reference: ``equijoin_node.cc`` always hash-joins; here small unique-
    key inner/left joins (the post-agg common case) stay on host, and
    everything else uses ``pixie_tpu.ops.join.device_join``.
    """
    if len(op.left_on) != len(op.right_on):
        raise QueryError("join key arity mismatch")
    small = left.length + right.length < DEVICE_JOIN_MIN_ROWS
    if op.how in ("inner", "left") and small:
        try:
            return _join_host(left, right, op)
        except _BuildNotUnique:
            pass  # N:M fan-out -> device kernel
    if left.length == 0 or right.length == 0:
        return _join_degenerate(left, right, op)
    return _join_device(left, right, op)


class _BuildNotUnique(Exception):
    pass


def _align_join_dicts(left, right, op):
    """String-dictionary id remaps so key ids compare across sides.

    Returns (l_remap, r_remap, key_dicts): key_dicts maps a left key
    column to the merged dictionary (union preserves left ids, so pair
    rows stay valid and coalesced build-side ids land past them).
    """
    l_remap: dict = {}
    r_remap: dict = {}
    key_dicts: dict = {}
    for lc, rc in zip(op.left_on, op.right_on):
        ld, rd = left.dicts.get(lc), right.dicts.get(rc)
        if ld is not None and rd is not None and ld is not rd:
            merged, rl, rr = ld.union(rd)
            l_remap[lc], r_remap[rc] = rl, rr
            key_dicts[lc] = merged
    return l_remap, r_remap, key_dicts


def _join_out_schema(left, right, op):
    """(out_rel, ordered (side, src_col) pairs) for join output columns."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    src = [("l", c) for c in left.relation.column_names] + [
        ("r", c) for c in right.relation.column_names if c not in op.right_on
    ]
    return out_rel, src


def _join_degenerate(left, right, op: JoinOp) -> HostBatch:
    """Joins where one side is empty (device kernel needs real rows)."""
    out_rel, src = _join_out_schema(left, right, op)
    if op.how == "inner" or (op.how == "left" and left.length == 0) or (
        op.how == "right" and right.length == 0
    ):
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    elif op.how in ("left", "outer") and right.length == 0:
        keep_l, keep_r = np.arange(left.length), np.full(left.length, -1)
    elif op.how in ("right", "outer") and left.length == 0:
        keep_l, keep_r = np.full(right.length, -1), np.arange(right.length)
    else:  # outer with one side non-empty handled above; both empty:
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    _, r_remap, key_dicts = _align_join_dicts(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        keep_l, keep_l >= 0, keep_r, keep_r >= 0,
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _assemble_join(left, right, op, out_rel, src, l_idx, l_take, r_idx, r_take,
                   r_remap=None, key_dicts=None):
    """Gather output columns from per-row indices + take masks.

    Join key columns coalesce (SQL USING semantics): a right/outer extra
    row — whose probe side is null — takes its key from the build side,
    remapped into the merged dictionary for strings.
    """
    r_remap = r_remap or {}
    key_dicts = key_dicts or {}
    key_map = dict(zip(op.left_on, op.right_on))
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for side, c in src:
        n = next(names)
        hb = left if side == "l" else right
        idx = l_idx if side == "l" else r_idx
        take = l_take if side == "l" else r_take
        rc = key_map.get(c) if side == "l" else None
        nullv = NULL_ID if hb.relation.col_type(c) == DataType.STRING else 0
        planes = []
        for pi, p in enumerate(hb.cols[c]):
            if len(p) == 0:
                taken = np.full(len(idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(idx, 0, len(p) - 1)]
            if not take.all():
                if rc is not None:
                    q = right.cols[rc][pi]
                    if pi == 0 and rc in r_remap:
                        q = np.where(
                            q >= 0, r_remap[rc][np.clip(q, 0, None)], NULL_ID
                        ).astype(q.dtype)
                    alt = (
                        np.full(len(r_idx), nullv, dtype=p.dtype)
                        if len(q) == 0
                        else q[np.clip(r_idx, 0, len(q) - 1)]
                    )
                    taken = np.where(
                        take, taken, np.where(r_take, alt, nullv)
                    ).astype(p.dtype)
                else:
                    taken = np.where(take, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in hb.dicts:
            out_dicts[n] = (
                key_dicts.get(c, hb.dicts[c]) if side == "l" else hb.dicts[c]
            )
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _join_key_planes(hb, cols, remaps):
    planes = []
    for c in cols:
        for i, p in enumerate(hb.cols[c]):
            if i == 0 and c in remaps:
                p = np.where(
                    p >= 0, remaps[c][np.clip(p, 0, None)], NULL_ID
                ).astype(p.dtype)
            planes.append(p)
    return planes


@functools.lru_cache(maxsize=64)
def _device_join_cache(n_build, n_probe, dtypes, capacity, how):
    """One jitted kernel per (bucketed shapes, key dtypes, capacity, how)."""
    import jax

    from ..ops.join import device_join

    return jax.jit(
        lambda bk, bv, pk, pv: device_join(bk, bv, pk, pv, capacity, how)
    )


def _join_device(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:M device join: pad to bucketed capacities, run the sort-based
    kernel, re-run doubled on overflow, gather columns host-side."""
    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    probe_planes = _join_key_planes(left, op.left_on, l_remap)
    build_planes = _join_key_planes(right, op.right_on, r_remap)
    for bp, pp in zip(build_planes, probe_planes):
        if bp.dtype != pp.dtype:
            raise QueryError(
                f"join key dtype mismatch: {bp.dtype} vs {pp.dtype}"
            )

    nb, np_ = bucket_capacity(right.length), bucket_capacity(left.length)

    def pad(p, cap):
        out = np.zeros(cap, dtype=p.dtype)
        out[: len(p)] = p
        return out

    bk = [pad(p, nb) for p in build_planes]
    pk = [pad(p, np_) for p in probe_planes]
    bv = np.zeros(nb, dtype=bool)
    bv[: right.length] = True
    pv = np.zeros(np_, dtype=bool)
    pv[: left.length] = True

    capacity = bucket_capacity(max(left.length + right.length, 1))
    while True:
        fn = _device_join_cache(
            nb, np_, tuple(str(p.dtype) for p in bk), capacity, op.how
        )
        p_idx, p_take, b_idx, b_take, out_valid, overflow = (
            np.asarray(a) for a in fn(bk, bv, pk, pv)
        )
        if not bool(overflow):
            break
        capacity *= 2

    sel = np.nonzero(out_valid)[0]
    out_rel, src = _join_out_schema(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        p_idx[sel], p_take[sel], b_idx[sel], b_take[sel],
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _join_host(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:1 equijoin on host (post-agg inputs are small).

    Reference: ``src/carnot/exec/equijoin_node.cc`` build+probe — here the
    build side must be unique on the key (raises _BuildNotUnique for the
    dispatcher to fall through to the device kernel).
    """
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)

    lk = _key_tuples(left, op.left_on, l_remap)
    rk = _key_tuples(right, op.right_on, r_remap)
    lookup: dict = {}
    for i, k in enumerate(rk):
        if k in lookup:
            raise _BuildNotUnique(op.right_on, k)
        lookup[k] = i

    match = np.fromiter((lookup.get(k, -1) for k in lk), dtype=np.int64, count=len(lk))
    if op.how == "inner":
        l_idx = np.nonzero(match >= 0)[0]
    elif op.how == "left":
        l_idx = np.arange(left.length)
    else:
        raise QueryError(f"unsupported join how={op.how!r}")
    r_idx = match[l_idx]

    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for c in left.relation.column_names:
        n = next(names)
        out_cols[n] = tuple(p[l_idx] for p in left.cols[c])
        if c in left.dicts:
            out_dicts[n] = left.dicts[c]
    for c in right.relation.column_names:
        if c in op.right_on:
            continue
        n = next(names)
        planes = []
        nullv = NULL_ID if right.relation.col_type(c) == DataType.STRING else 0
        for p in right.cols[c]:
            if len(p) == 0:  # empty build side: all-null fill
                taken = np.full(len(l_idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(r_idx, 0, None)]
                if op.how == "left":
                    taken = np.where(r_idx >= 0, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in right.dicts:
            out_dicts[n] = right.dicts[c]
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _union_host(mats) -> HostBatch:
    """Schema-aligned concatenation with dictionary re-encoding."""
    first = mats[0]
    for m in mats[1:]:
        if tuple(m.relation.column_names) != tuple(first.relation.column_names):
            raise QueryError("union inputs must share a schema")
    out_cols: dict = {}
    out_dicts: dict = {}
    for c, dt in first.relation.items():
        if dt == DataType.STRING:
            merged = StringDictionary()
            planes = []
            for m in mats:
                d = m.dicts.get(c, StringDictionary())
                # union preserves existing ids (append-only), so earlier
                # planes stay valid as merged grows.
                merged, _, remap = merged.union(d)
                ids = m.cols[c][0]
                planes.append(
                    np.where(ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID).astype(
                        np.int32
                    )
                )
            out_cols[c] = (np.concatenate(planes),)
            out_dicts[c] = merged
        else:
            out_cols[c] = tuple(
                np.concatenate([m.cols[c][i] for m in mats])
                for i in range(len(first.cols[c]))
            )
    return HostBatch(
        relation=first.relation,
        cols=out_cols,
        length=sum(m.length for m in mats),
        dicts=out_dicts,
    )
