"""Query engine: plan DAG -> streamed, jit-compiled execution.

Reference parity: the Carnot facade (``src/carnot/carnot.h:39-95``
Carnot::ExecutePlan) + ExecutionGraph (``exec/exec_graph.cc:295``). The
TPU execution model:

- Each maximal linear chain of Map/Filter/Agg/Limit over one input
  compiles to a single fragment program (see fragment.py).
- Tables stream through in fixed-capacity windows (static shapes -> one
  compile, reused every window; the Table::Cursor batch loop analog).
- DAG joints (Join/Union) materialize their small (post-agg) inputs and
  continue; joins run host-side on dense ids (N:1, right-unique).
- Aggregation group state survives across windows via the regroup
  machinery, so a billion-row table aggregates in O(windows) device
  dispatches with O(G) memory.
"""

from __future__ import annotations

import functools
import itertools
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.dtypes import DataType, host_dtypes
from ..types.relation import Relation
from ..types.strings import NULL_ID, StringDictionary
from ..udf.registry import Registry, default_registry
from .fragment import ColumnMeta, compile_fragment_cached as compile_fragment
from .plan import (
    AggOp,
    TableSinkOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    LookupJoinOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)


@dataclass
class AggStatePayload:
    """Partial-agg state shipped across a bridge (agent mode).

    The UDA ``Serialize``/``DeSerialize`` analog (``udf.h:99-100``): the
    serialized form IS the carry pytree plus enough metadata for the
    merge tier to recompile the identical fragment and realign string
    dictionary ids. String-valued *carries* (e.g. ``any`` over a string
    column) are not realigned — only group keys are; such UDAs need a
    shared dictionary to cross agents.
    """

    chain: tuple  # fragment ops [pre..., AggOp]
    input_relation: object  # Relation at fragment input
    input_dicts: dict  # {col: StringDictionary} at fragment input
    state: dict  # group-state pytree (numpy leaves)
    # Dense-domain states ship no key planes (slot index IS the packed
    # key); the producing fragment's domains let the merge side expand
    # them back to explicit keys (dictionaries may differ per agent).
    # ``dense_offsets`` shifts stats-derived integer codes back to values.
    dense_domains: tuple = ()
    dense_offsets: tuple = ()


@dataclass
class RowsPayload:
    """Materialized rows shipped across a bridge (plain GRPCSink analog)."""

    batch: HostBatch


@dataclass
class _PendingAggBridge:
    """Agg-bridge payloads awaiting their finalize AggOp."""

    payloads: list  # list[AggStatePayload]


def _expand_dense_payload(p, group_rel, key_plane_index):
    """Expand a dense-domain AggStatePayload to explicit key planes.

    Dense states carry no keys (slot index IS the packed key); the merge
    tier reconstructs them with the same unpack arithmetic the producing
    fragment's finalize uses, so the generic realign/merge path applies.
    """
    import dataclasses

    from .fragment import unpack_dense_slots

    doms = getattr(p, "dense_domains", ())
    if not doms:
        return p
    gd = len(p.state["valid"])
    keys = unpack_dense_slots(
        np.arange(gd, dtype=np.int64),
        doms,
        [group_rel.col_type(c) for c, _i in key_plane_index],
        np,
        offsets=getattr(p, "dense_offsets", ()),
    )
    return dataclasses.replace(
        p, state={**p.state, "keys": tuple(keys)}, dense_domains=(),
        dense_offsets=(),
    )


def _compact_payload(p):
    """Shrink an expanded dense-domain payload to its live slots.

    A dense state is domain-sized (up to ``dense_domain_limit`` slots)
    however few groups are live; merging every payload at that capacity
    is a large avoidable cost for small aggregates. Live slots compact to
    the front (padded to a power-of-two bucket with neutral invalid
    slots, so merge-fragment compiles stay shape-bucketed).
    """
    import dataclasses

    import jax

    valid = np.asarray(p.state["valid"])
    g = len(valid)
    live = int(valid.sum())
    cap = bucket_capacity(max(live, 1))
    if cap >= g:
        return p
    idx = np.nonzero(valid)[0]
    if len(idx) < cap:
        # Invalid slots hold uda-neutral carries by construction, so any
        # one of them is safe padding.
        fill = int(np.nonzero(~valid)[0][0])
        idx = np.concatenate(
            [idx, np.full(cap - len(idx), fill, dtype=np.int64)]
        )

    def take(leaf):
        a = np.asarray(leaf)
        return a[idx] if a.ndim and a.shape[0] == g else a

    return dataclasses.replace(p, state={
        "keys": tuple(take(k) for k in p.state["keys"]),
        "valid": valid[idx],
        "carries": jax.tree_util.tree_map(take, p.state["carries"]),
        "overflow": p.state["overflow"],
    })


class QueryError(Exception):
    pass


class QueryCancelled(QueryError):
    """Raised mid-stream when a query's cancel event fires (the
    ExecState::keep_running / exec_graph abort path,
    ``src/carnot/exec/exec_state.h``)."""


@dataclass
class _Stream:
    relation: Relation
    dicts: dict
    chain: list
    source: object  # list[Table] | Table | HostBatch
    source_op: Optional[MemorySourceOp] = None
    # Query-constant side-input arrays (numpy, keyed by reserved names)
    # passed to the fragment program alongside each window — the build
    # tables of fused lookup joins ride here, staged once per query.
    side: dict = field(default_factory=dict)

    def extend(self, op):
        return _Stream(
            self.relation, self.dicts, self.chain + [op], self.source,
            self.source_op, dict(self.side),
        )


def _chain_out_relation(stream: "_Stream", registry):
    """(relation, dicts) after a stream's pre-stage chain, or None if the
    chain does not bind (the caller falls back to the generic path)."""
    from .fragment import _bind_pre_stage

    try:
        _, rel, dicts = _bind_pre_stage(
            list(stream.chain), stream.relation, dict(stream.dicts), registry
        )
    except Exception:
        return None
    return rel, dicts


def _stream_col_stats(stream: "_Stream"):
    """Merged per-column (min, max) bounds across a stream's source
    tablets (None when the source is not table-backed or any tablet
    lacks stats for a column)."""
    src = stream.source
    if not isinstance(src, list) or not src:
        return None
    merged: dict | None = None
    for t in src:
        ts = getattr(t, "col_stats", None)
        if ts is None:
            return None
        if not ts:
            continue  # empty tablet (or no int columns): contributes no rows
        if merged is None:
            merged = dict(ts)
        else:
            merged = {
                c: (min(merged[c][0], ts[c][0]), max(merged[c][1], ts[c][1]))
                for c in merged.keys() & ts.keys()
            }
    return merged or None


class DeviceResult:
    """Device-resident aggregate query output.

    Holds the finalized [G] column planes + validity on device. The axon
    TPU tunnel journals device work lazily until a process's first
    device-to-host readback; that flush executes everything recorded and
    switches later dispatches to a synchronous mode (~65ms round trip
    each) in which compiling NEW programs can stall. Callers therefore
    compile/warm with ``materialize=False`` and control when the single
    readback — ``to_host()``, which also resolves group-overflow
    rebucketing — happens. ``block_until_ready()`` fences without
    reading back (it does NOT flush the journal).

    Reference contrast: Carnot's MemorySink always lands rows host-side
    (``src/carnot/exec/memory_sink_node.cc``); on TPU the result's natural
    home is HBM until a client asks for bytes.
    """

    def __init__(self, engine, stream, frag, cols, valid, overflow,
                 stats=None, qstats=None):
        self._engine = engine
        self._stream = stream
        self._frag = frag
        self._cols = cols
        self._valid = valid
        self._overflow = overflow
        self._stats = stats
        self._qstats = qstats  # the CREATING query's stats (analyze mode)
        self._host: Optional[HostBatch] = None

    @property
    def relation(self):
        return self._frag.relation

    def block_until_ready(self) -> "DeviceResult":
        import jax

        jax.block_until_ready((self._cols, self._valid, self._overflow))
        return self

    def to_host(self) -> HostBatch:
        if self._host is not None:
            return self._host
        eng, stream, frag = self._engine, self._stream, self._frag
        cols, valid, overflow = self._cols, self._valid, self._overflow
        stats = self._stats
        while bool(overflow):
            # NOTE: the rebucket re-folds the source table AS IT IS NOW —
            # rows appended between execute and to_host are included,
            # unlike the no-overflow snapshot. Callers needing snapshot
            # semantics materialize before further ingest (the service
            # shell serializes queries against appends anyway).
            # Rebucket: double max_groups and re-run the stream (the same
            # recovery the device join uses on output overflow; Carnot's
            # hash map grows instead, ``agg_node.cc``).
            stream = _double_agg_groups(stream)
            frag = compile_fragment(
                stream.chain, stream.relation, stream.dicts, eng.registry,
                col_stats=_stream_col_stats(stream),
            )
            if self._qstats is not None:
                # Fresh per-attempt stats: rows/windows stay per-attempt
                # and the attempt is marked (analyze fidelity).
                stats = self._qstats.new_fragment(stream.chain)
                stats.ops = stats.ops + ("rebucket",)
            state = eng._fold_agg_state(stream, frag, stats)
            with _timed(stats, "finalize"):
                cols, valid, overflow = frag.finalize(state)
                _block_if(stats, (cols, valid, overflow))
        with _timed(stats, "materialize"):
            out = _to_host_batch(frag.out_meta, cols, np.asarray(valid))
        if stats is not None:
            stats.rows_out = out.length
        self._host = _apply_limit(out, frag.limit)
        self._cols = self._valid = self._overflow = None  # release HBM
        return self._host

    def to_pydict(self, **kw):
        return self.to_host().to_pydict(**kw)


class Engine:
    """Owns tables + registry; executes plans. (EngineState analog,
    ``src/carnot/engine_state.h``.)"""

    def __init__(self, registry: Registry | None = None,
                 window_rows: int | None = None):
        from ..config import get_flag
        from ..table_store import TableStore

        self.registry = registry or default_registry()
        self.table_store = TableStore()
        self.window_rows = window_rows or get_flag("window_rows")
        self.last_stats = None
        self._query_stats = None
        self._cancel = None  # per-query cancel event (execute_plan arg)
        # One query at a time; reentrant so subclasses can hold it across
        # their own engine-state mutations around super().execute_plan().
        self._exec_guard = threading.RLock()
        self.last_table_sinks: dict = {}  # {table: rows} from TableSinkOps

    @property
    def tables(self) -> dict:
        """{name: default-tablet (or first) Table} view over the store."""
        out = {}
        for n in self.table_store.table_names():
            t = self.table_store.get_table(n)
            if t is None:
                tablets = self.table_store.tablets(n)
                t = tablets[0] if tablets else None
            out[n] = t
        return out

    # -- table management ----------------------------------------------------
    def create_table(self, name: str, relation: Relation | None = None,
                     max_bytes: int = -1):
        t = self.table_store.add_table(name, relation, max_bytes=max_bytes)
        # Tables created through an engine stage device windows at the
        # engine's streaming size from the first append on.
        t.device_window_rows = self.window_rows
        return t

    def append_data(self, name: str, data, time_cols=("time_",)):
        """Push path (Stirling's RegisterDataPushCallback analog)."""
        # Atomic get-or-create at THIS engine's streaming window size so
        # first appends stage device windows correctly (and concurrent
        # first appends never replace each other's table).
        self.table_store.ensure_table(
            name, device_window_rows=self.window_rows
        )
        return self.table_store.append_data(name, data, time_cols=time_cols)

    # -- execution -----------------------------------------------------------
    def execute_query(self, query: str, now_ns: int = 0,
                      max_output_rows: int = 10_000,
                      analyze: bool = False,
                      materialize: bool = True) -> dict:
        """Compile a PxL script and execute it (Carnot::ExecuteQuery parity,
        ``src/carnot/carnot.cc:122-134``). Returns {output name: HostBatch}.
        ``analyze`` records per-fragment stats on ``self.last_stats``.
        ``materialize=False`` leaves aggregate outputs device-resident
        (returns DeviceResult — call ``.to_host()`` for bytes)."""
        from ..planner import CompilerState, compile_pxl

        state = CompilerState(
            schemas={n: t.relation for n, t in self.tables.items()},
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=max_output_rows,
        )
        compiled = compile_pxl(query, state)
        return self.execute_plan(
            compiled.plan, analyze=analyze, materialize=materialize
        )

    def set_metadata_state(self, state) -> None:
        """Attach k8s metadata; rebinds the metadata UDFs to a snapshot of
        ``state`` (reference: per-query AgentMetadataState), preserving all
        other registrations on this engine's registry."""
        from ..metadata.funcs import METADATA_FUNC_NAMES, register_metadata_funcs

        self.metadata_state = state
        reg = self.registry.clone("engine", exclude=METADATA_FUNC_NAMES)
        register_metadata_funcs(reg, state)
        self.registry = reg

    def execute_plan(
        self, plan: Plan, bridge_inputs: dict | None = None,
        analyze: bool = False, materialize: bool = True,
        cancel=None,
    ) -> dict:
        """Execute a plan. Whole plans return {sink name: HostBatch}.

        Split-fragment plans (from the distributed splitter, agent mode):
        a plan ending in BridgeSinkOps additionally returns
        {("bridge", id): payload}; a merge plan starting from
        BridgeSourceOps reads ``bridge_inputs`` = {bridge id: [payloads]}.

        ``analyze`` records per-fragment, per-stage execution stats
        (exec_node.h:40 ExecNodeStats analog) on ``self.last_stats``.

        One query at a time per Engine: the cancel handle and stats are
        engine-scoped, so concurrent ``execute_plan`` calls (the Agent's
        bus dispatcher threads can overlap execute/merge/bridge work)
        serialize on an engine lock rather than corrupting each other's
        cancel handles.
        """
        with self._exec_guard:
            return self._execute_plan_guarded(
                plan, bridge_inputs, analyze, materialize, cancel
            )

    def _execute_plan_guarded(
        self, plan, bridge_inputs, analyze, materialize, cancel
    ) -> dict:
        self._cancel = cancel
        if analyze:
            from .analyze import QueryStats

            self._query_stats = QueryStats()
            t_start = time.perf_counter()
            try:
                out = self._execute_plan_inner(plan, bridge_inputs, materialize)
            finally:
                self._query_stats.total_seconds = time.perf_counter() - t_start
                self.last_stats = self._query_stats
                self._query_stats = None
                self._cancel = None
            return out
        try:
            return self._execute_plan_inner(plan, bridge_inputs, materialize)
        finally:
            self._cancel = None

    def _execute_plan_inner(
        self, plan: Plan, bridge_inputs: dict | None = None,
        materialize: bool = True,
    ) -> dict:
        self.last_table_sinks = {}
        results: dict[int, object] = {}
        outputs: dict = {}
        consumers: dict[int, int] = {}
        for n in plan.nodes.values():
            for i in n.inputs:
                consumers[i] = consumers.get(i, 0) + 1

        def mat_input(nid):
            """Materialize a node's result once; cache for fan-out."""
            r = results[nid]
            if not isinstance(r, HostBatch):
                r = self._materialize(r)
                results[nid] = r
            return r

        for nid in plan.topo_order():
            node = plan.nodes[nid]
            op = node.op
            if isinstance(op, MemorySourceOp):
                tablets = self.table_store.tablets(op.table)
                if not tablets:
                    raise QueryError(f"no table named {op.table!r}")
                # Tablets share relation + string dictionaries (enforced by
                # TableStore); a query scans all of them.
                base = next((t for t in tablets if len(t.relation)), tablets[0])
                chain = []
                if op.columns is not None:
                    chain.append(
                        MapOp(exprs=tuple((c, _col(c)) for c in op.columns))
                    )
                results[nid] = _Stream(
                    base.relation, dict(base.dicts), chain, tablets, op
                )
            elif isinstance(op, UDTFSourceOp):
                results[nid] = self._run_udtf(op)
            elif isinstance(op, EmptySourceOp):
                results[nid] = _empty_host_batch(
                    Relation(list(op.relation_items))
                )
            elif isinstance(op, (MapOp, FilterOp, AggOp, LimitOp)):
                upstream = results[node.inputs[0]]
                if isinstance(upstream, _PendingAggBridge):
                    # The finalize half of a split aggregate: merge the
                    # shipped partial states and finalize — the agent-mode
                    # form of the bridge collective.
                    if not (isinstance(op, AggOp) and op.mode == "finalize"):
                        raise QueryError(
                            "agg bridge must feed its finalize AggOp"
                        )
                    results[nid] = self._merge_agg_bridge(upstream)
                    continue
                st = self._as_stream(upstream)
                if st.chain and isinstance(st.chain[-1], LimitOp):
                    # A limit terminates its fragment: apply the cap at its
                    # plan position, then keep chaining on the result.
                    st = self._as_stream(self._materialize(st))
                if isinstance(op, AggOp) and any(
                    isinstance(o, AggOp) for o in st.chain
                ):
                    # Two blocking aggs never share a fragment: the first
                    # materializes (its output is small), the second re-
                    # aggregates it (the splitter's cut-at-blocking-op rule,
                    # planner/distributed/splitter/splitter.h:75).
                    st = self._as_stream(self._materialize(st))
                results[nid] = st.extend(op)
            elif isinstance(op, JoinOp):
                fused = self._try_fused_join(nid, node, results, consumers)
                if fused is not None:
                    results[nid] = fused
                else:
                    left = mat_input(node.inputs[0])
                    right = mat_input(node.inputs[1])
                    results[nid] = _join_dispatch(left, right, op)
            elif isinstance(op, UnionOp):
                mats = [mat_input(i) for i in node.inputs]
                results[nid] = _union_host(mats)
            elif isinstance(op, ResultSinkOp):
                src_id = node.inputs[0]
                r = results[src_id]
                if (
                    not materialize
                    and isinstance(r, _Stream)
                    and consumers.get(src_id, 0) <= 1
                ):
                    # Device-resident result: the readback (and any
                    # overflow rebucket) happens in DeviceResult.to_host.
                    outputs[op.name] = self._run_fragment(r)
                else:
                    outputs[op.name] = mat_input(src_id)
            elif isinstance(op, TableSinkOp):
                hb = mat_input(node.inputs[0])
                self.append_data(op.table, hb)
                # Not a client output (clients iterate result tables);
                # recorded on the engine for callers/tests.
                self.last_table_sinks[op.table] = hb.length
            elif isinstance(op, OTelExportSinkOp):
                from .otel import batch_to_otlp

                payload = batch_to_otlp(mat_input(node.inputs[0]), op.spec)
                self.export_otel(payload, op.spec.endpoint)
            elif isinstance(op, BridgeSinkOp):
                outputs[("bridge", op.bridge_id)] = self._bridge_payload(
                    results[node.inputs[0]]
                )
            elif isinstance(op, BridgeSourceOp):
                if not bridge_inputs or op.bridge_id not in bridge_inputs:
                    raise QueryError(f"no input for bridge {op.bridge_id}")
                results[nid] = self._bind_bridge(bridge_inputs[op.bridge_id])
            else:
                raise QueryError(f"unsupported operator {op}")
            # Fan-out of a stream: materialize once, share the batch.
            if consumers.get(nid, 0) > 1 and isinstance(results[nid], _Stream):
                results[nid] = self._materialize(results[nid])
        return outputs

    def export_otel(self, payload: dict, endpoint) -> None:
        """OTel egress. Default: collect in-memory (``otel_exports``);
        deployments override/replace with an OTLP pusher (the reference
        ships over OTLP gRPC — grpc is gated in this environment)."""
        if not hasattr(self, "otel_exports"):
            self.otel_exports = []
        self.otel_exports.append({"endpoint": endpoint, "payload": payload})

    def _run_udtf(self, op: UDTFSourceOp) -> HostBatch:
        """Execute a UDTF source (``udtf_source_node.h`` analog): call its
        fn with this engine as context and shape the rows to the declared
        relation."""
        udtf = self.registry.get_udtf(op.name)
        args = dict(op.args)
        for entry in udtf.init_args:  # declared defaults (3-tuples)
            if len(entry) == 3 and entry[0] not in args:
                args[entry[0]] = entry[2]
        data = udtf.fn(self, **args)
        rel = Relation(list(udtf.relation))
        hb = HostBatch.from_pydict(data, relation=rel, time_cols=())
        return hb

    # -- bridge (agent-mode) machinery ----------------------------------------
    def _fold_agg_state(self, stream: "_Stream", frag, stats=None):
        """Stream the source through the fragment's window fold, returning
        the accumulated (unfinalized) group state.

        Equal-capacity device-resident window runs fold through
        ``update_all`` — ONE scan program per chunk of windows instead of
        one dispatch (one tunnel round trip) per window."""
        from ..config import get_flag

        import jax

        init_state, agg_step, _ = self._compile_steps(frag)
        state = init_state()
        # Scan-folding exists to amortize the TPU tunnel's ~70ms/dispatch
        # round trip; on the CPU backend dispatches are cheap and the
        # jnp.stack of window planes is a pure memory-bandwidth loss.
        chunk_w = (
            get_flag("fold_scan_windows")
            if frag.update_all and jax.default_backend() == "tpu"
            else 0
        )
        pend_cols, pend_lo, pend_hi = [], [], []

        def flush_pending(state):
            if not pend_cols:
                return state
            if len(pend_cols) == 1:
                state = agg_step(state, pend_cols[0], (pend_lo[0], pend_hi[0]))
            else:
                state = frag.update_all(
                    state, tuple(pend_cols),
                    np.asarray(pend_lo, dtype=np.int32),
                    np.asarray(pend_hi, dtype=np.int32),
                )
            pend_cols.clear()
            pend_lo.clear()
            pend_hi.clear()
            return state

        for cols, valid in self._staged_windows(stream, stats):
            batchable = (
                chunk_w > 1
                and isinstance(valid, tuple)
                and (
                    not pend_cols
                    or _window_shapes(cols) == _window_shapes(pend_cols[0])
                )
            )
            with _timed(stats, "compute"):
                if batchable:
                    pend_cols.append(cols)
                    pend_lo.append(valid[0])
                    pend_hi.append(valid[1])
                    if len(pend_cols) >= chunk_w:
                        state = flush_pending(state)
                else:
                    state = flush_pending(state)
                    state = agg_step(state, cols, valid)
                _block_if(stats, state)
            if stats is not None:
                stats.windows += 1
        with _timed(stats, "compute"):
            state = flush_pending(state)
            _block_if(stats, state)
        return state

    def _bridge_payload(self, res):
        """Produce a BridgeSink payload: partial-agg state for agg chains,
        materialized rows otherwise (GRPCSinkNode's two modes)."""
        if isinstance(res, _Stream) and any(
            isinstance(o, AggOp) for o in res.chain
        ):
            import jax

            while True:
                frag = compile_fragment(
                    res.chain, res.relation, res.dicts, self.registry,
                    col_stats=_stream_col_stats(res),
                )
                state = self._fold_agg_state(res, frag)
                if not bool(np.asarray(state["overflow"])):
                    break
                res = _double_agg_groups(res)  # rebucket before shipping
            return AggStatePayload(
                chain=tuple(res.chain),
                input_relation=res.relation,
                input_dicts=dict(res.dicts),
                state=jax.tree_util.tree_map(np.asarray, state),
                dense_domains=frag.dense_domains,
                dense_offsets=frag.dense_offsets,
            )
        return RowsPayload(batch=self._materialize(res))

    def _bind_bridge(self, payloads):
        payloads = payloads if isinstance(payloads, list) else [payloads]
        if not payloads:
            raise QueryError("bridge received no payloads")
        if all(isinstance(p, RowsPayload) for p in payloads):
            return _union_host([p.batch for p in payloads])
        if all(isinstance(p, AggStatePayload) for p in payloads):
            return _PendingAggBridge(payloads)
        raise QueryError("mixed payload kinds on one bridge")

    def _merge_agg_bridge(self, pending: _PendingAggBridge) -> HostBatch:
        """Merge shipped partial-agg states and finalize.

        The agent-mode replacement for the on-mesh collective: states from
        k agents fold through the fragment's associative merge, after the
        group-key string ids of every agent are remapped into one
        canonical dictionary (the reference ships raw strings over GRPC,
        so alignment is implicit there; here ids must be reconciled).
        """
        import dataclasses

        import jax
        import jax.numpy as jnp

        from .fragment import _bind_pre_stage, _split_chain

        p0 = pending.payloads[0]
        # The merge fragment is compiled WITHOUT dense mode: agents encode
        # against their own dictionaries, so dense slot spaces are not
        # comparable across payloads — expand each dense state to explicit
        # key planes (then compact to live slots: a dense state is
        # domain-sized regardless of how few groups are live, and the
        # merge must not inherit that capacity) and realign through the
        # generic (sort-space) path. The group relation / key planes come
        # from binding the pre-stage directly — no compile needed before
        # the payload sizes are known.
        from ..types.dtypes import device_dtypes

        pre0, agg0, _post0, _limit0 = _split_chain(list(p0.chain))
        _, rel1, _ = _bind_pre_stage(
            pre0, p0.input_relation, dict(p0.input_dicts), self.registry
        )
        key_plane_index = tuple(
            (c, i)
            for c in agg0.group_cols
            for i in range(len(device_dtypes(rel1.col_type(c))))
        )
        group_rel = rel1
        pending = _PendingAggBridge(payloads=[
            _compact_payload(_expand_dense_payload(p, rel1, key_plane_index))
            for p in pending.payloads
        ])
        p0 = pending.payloads[0]
        # Merge at the largest payload capacity (smaller states pad with
        # neutral slots below); overflow rebucketing grows it if the
        # union of live groups spills.
        g = max(
            op.max_groups
            for p in pending.payloads
            for op in p.chain
            if isinstance(op, AggOp)
        )
        g = max([g] + [len(p.state["valid"]) for p in pending.payloads])
        chain = [
            dataclasses.replace(op, max_groups=g) if isinstance(op, AggOp) else op
            for op in p0.chain
        ]
        frag = compile_fragment(
            chain, p0.input_relation, dict(p0.input_dicts), self.registry,
            allow_dense=False,
        )
        if frag.string_carry_sources and len(pending.payloads) > 1:
            # String ids inside a CARRY (not a group key) cannot be
            # realigned after the fact; reject unless every agent encoded
            # from the very same dictionary objects (engine.py realigns
            # keys only — reference ships raw strings over GRPC instead).
            for out_name, src_cols in frag.string_carry_sources:
                for c in src_cols:
                    d0 = pending.payloads[0].input_dicts.get(c)
                    s0 = list(d0.strings) if d0 is not None else None
                    for p in pending.payloads[1:]:
                        d = p.input_dicts.get(c)
                        same = (
                            d is d0
                            or (d is not None and s0 is not None
                                and list(d.strings) == s0)
                        )
                        if not same:
                            raise QueryError(
                                f"aggregate {out_name!r} carries string ids "
                                f"of column {c!r} across agents whose "
                                "dictionaries disagree; results would be "
                                "garbage. Share one dictionary or aggregate "
                                "after merge."
                            )
        # Per-agent post-pre-stage dictionaries for the group columns.
        per_agent_dicts = []
        for p in pending.payloads:
            _, rel1_a, dicts1 = _bind_pre_stage(
                pre0, p.input_relation, dict(p.input_dicts), self.registry
            )
            if tuple(rel1_a.items()) != tuple(group_rel.items()):
                raise QueryError(
                    f"bridge schema mismatch: {rel1_a} vs {group_rel}"
                )
            per_agent_dicts.append(dicts1)
        # Canonical dictionary + id remap per string group column.
        canonical: dict[str, StringDictionary] = {}
        states = []
        for p, dicts1 in zip(pending.payloads, per_agent_dicts):
            keys = list(p.state["keys"])
            for pi, (c, i) in enumerate(key_plane_index):
                if group_rel.col_type(c) != DataType.STRING or i != 0:
                    continue
                src = dicts1.get(c)
                if src is None:
                    continue
                dst = canonical.setdefault(c, StringDictionary())
                remap = np.fromiter(
                    (dst.get_or_add(s) for s in src.strings),
                    dtype=np.int32,
                    count=len(src),
                )
                ids = np.asarray(keys[pi])
                if len(remap) == 0:
                    # Empty dictionary (agent had no rows): every slot is
                    # already the null id — nothing to remap.
                    keys[pi] = np.full_like(ids, NULL_ID, dtype=np.int32)
                else:
                    keys[pi] = np.where(
                        ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID
                    ).astype(np.int32)
            if bool(np.asarray(p.state["overflow"])):
                # Lost groups at the source cannot be recovered here; the
                # producing agent rebuckets before shipping (_bridge_payload).
                raise QueryError(
                    "bridge payload arrived with group overflow; producing "
                    "agent failed to rebucket"
                )
            states.append({**p.state, "keys": tuple(keys)})
        while True:
            # Pad smaller states into g neutral slots, fold-merge, and on
            # merged-distinct overflow double g and retry from the (still
            # intact) original states.
            init = frag.init_state()

            def pad(a, i):
                a = jnp.asarray(a)
                if a.ndim == 0 or a.shape[0] >= i.shape[0]:
                    return a
                return jnp.concatenate([a, i[a.shape[0]:]])

            merge = jax.jit(frag.merge_states)
            padded = [jax.tree_util.tree_map(pad, s, init) for s in states]
            acc = padded[0]
            for s in padded[1:]:
                acc = merge(acc, s)
            cols, valid, overflow = frag.finalize(acc)
            if not bool(overflow):
                break
            from ..config import get_flag

            if g * 2 > get_flag("max_groups_limit"):
                raise QueryError(
                    f"group-by overflow merging bridge states at "
                    f"max_groups={g}; rebucketing past the "
                    f"{get_flag('max_groups_limit')} cap refused "
                    "(PIXIE_TPU_MAX_GROUPS_LIMIT)"
                )
            g *= 2
            chain = [
                dataclasses.replace(op, max_groups=g)
                if isinstance(op, AggOp)
                else op
                for op in chain
            ]
            frag = compile_fragment(
                chain, p0.input_relation, dict(p0.input_dicts), self.registry,
                allow_dense=False,  # states carry explicit key planes
            )
        meta = [
            (
                ColumnMeta(m.name, m.dtype, dict=canonical[m.name])
                if m.name in canonical
                else m
            )
            for m in frag.out_meta
        ]
        return _to_host_batch(meta, cols, np.asarray(valid))

    # -- internals -----------------------------------------------------------
    def _as_stream(self, res) -> _Stream:
        if isinstance(res, _Stream):
            return res
        hb: HostBatch = res
        return _Stream(hb.relation, dict(hb.dicts), [], hb)

    def _windows(self, stream: _Stream):
        """Slice source batches into <= window_rows chunks."""
        if isinstance(stream.source, HostBatch):
            batches = [stream.source]
        else:
            sop = stream.source_op
            tables = (
                stream.source if isinstance(stream.source, list) else [stream.source]
            )
            batches = itertools.chain.from_iterable(
                t.scan(
                    sop.start_time if sop else None, sop.stop_time if sop else None
                )
                for t in tables
            )
        for b in batches:
            for off in range(0, max(b.length, 1), self.window_rows):
                if b.length == 0:
                    yield b
                    break
                idx = slice(off, min(off + self.window_rows, b.length))
                if idx.start == 0 and idx.stop == b.length:
                    yield b
                else:
                    yield HostBatch(
                        relation=b.relation,
                        cols={
                            n: tuple(p[idx] for p in ps) for n, ps in b.cols.items()
                        },
                        length=idx.stop - idx.start,
                        dicts=b.dicts,
                    )

    # -- execution seams (overridden by DistributedEngine) -------------------
    # Whether this engine may consume device-resident table windows (HBM
    # cold store). DistributedEngine stages row-sharded instead.
    device_residency = True

    def _window_capacity(self, length: int) -> int:
        return max(bucket_capacity(self.window_rows), bucket_capacity(length))

    def _stage(self, hb: HostBatch, capacity: int):
        """Pad a host window to capacity and place it on device."""
        db = hb.to_device(capacity)
        return db.cols, db.valid

    def _check_cancel(self) -> None:
        c = getattr(self, "_cancel", None)
        if c is not None and c.is_set():
            raise QueryCancelled("query cancelled")

    def _staged_windows(self, stream: "_Stream", stats=None):
        """Yield (cols, valid) device-staged windows for a stream.

        Table sources use the device-resident window cache (zero
        host->device transfer once staged — SURVEY.md §7 stage 1 "HBM as
        cold"); host batches and distributed engines stage per window.
        Streams with side inputs (fused lookup-join build tables) carry
        them in every window's cols under ``__side__`` — device_put once
        per query, then reused as runtime args (never closure constants).
        """
        if stream.side:
            yield from self._staged_windows_with_side(stream, stats)
            return
        yield from self._staged_windows_inner(stream, stats)

    def _staged_windows_with_side(self, stream: "_Stream", stats=None):
        import jax

        side = {k: jax.device_put(v) for k, v in stream.side.items()}
        for cols, valid in self._staged_windows_inner(stream, stats):
            yield {**cols, "__side__": side}, valid

    def _staged_windows_inner(self, stream: "_Stream", stats=None):
        from ..config import get_flag

        import jax

        use_cache = (
            self.device_residency
            and get_flag("device_residency")
            and not isinstance(stream.source, HostBatch)
        )
        if use_cache:
            sop = stream.source_op
            start = sop.start_time if sop else None
            stop = sop.stop_time if sop else None
            tables = (
                stream.source
                if isinstance(stream.source, list)
                else [stream.source]
            )
            for t in tables:
                if getattr(t, "_backend", None) is None:
                    continue
                for win, lo, hi in t.device_scan(
                    start, stop, window_rows=self.window_rows
                ):
                    self._check_cancel()
                    if stats is not None:
                        stats.rows_in += hi - lo
                    # (lo, hi) scalar pair, not a mask: the fragment
                    # builds the iota mask INSIDE its program — a
                    # separate mask dispatch costs a tunnel round trip
                    # per window. np scalars stay dynamic (no retrace
                    # per offset).
                    yield win.cols, (
                        np.int32(lo - win.row0), np.int32(hi - win.row0)
                    )
            return
        for hb in self._windows(stream):
            self._check_cancel()
            with _timed(stats, "stage", rows=hb.length):
                cols, valid = self._stage(hb, self._window_capacity(hb.length))
                _block_if(stats, cols)
            if stats is not None:
                stats.rows_in += hb.length
            yield cols, valid

    def _compile_steps(self, frag):
        """(init_state_fn, agg_step, rows_step) for a compiled fragment."""
        if frag.is_agg:
            return frag.init_state, frag.update, None
        return None, None, frag.update

    # -- fused lookup join ----------------------------------------------------
    # DistributedEngine turns this off: side tables would need replicated
    # shardings through the shard_map specs (future work with mesh
    # residency).
    fused_lookup_join = True

    def _try_fused_join(self, nid, node, results, consumers):
        """N:1 join as an in-fragment device lookup, or None to fall back.

        Reference contrast: ``equijoin_node.cc`` materializes output rows
        through a host hash map; here, when the build side resolves to a
        dense-domain table, the probe stream keeps flowing — each window
        gathers the build columns on device and the downstream
        Map/Filter/Agg fuse into the same XLA program (VERDICT r03 ask
        #2: output-row assembly never leaves the device).
        """
        from ..types.dtypes import device_dtypes

        op = node.op
        if not self.fused_lookup_join:
            return None
        if op.how not in ("inner", "left") or len(op.left_on) != 1:
            return None
        left_id, right_id = node.inputs
        left_res = results[left_id]
        if not isinstance(left_res, _Stream) or consumers.get(left_id, 0) > 1:
            return None
        if any(isinstance(o, (AggOp, LimitOp)) for o in left_res.chain):
            return None
        lc, rc = op.left_on[0], op.right_on[0]
        bound = _chain_out_relation(left_res, self.registry)
        if bound is None:
            return None
        left_rel, left_dicts = bound
        if not left_rel.has_column(lc):
            return None
        l_dt = left_rel.col_type(lc)
        if len(device_dtypes(l_dt)) != 1:
            return None

        right_res = results[right_id]
        if (
            isinstance(right_res, _Stream)
            and consumers.get(right_id, 0) <= 1
            and any(isinstance(o, AggOp) for o in right_res.chain)
        ):
            built = self._dense_agg_build(right_res, op, l_dt, left_dicts, lc, rc)
            if isinstance(built, tuple) and built[0] == "fallback":
                # The aggregate already executed; keep its rows for the
                # generic join path rather than re-folding the stream.
                results[right_id] = built[1]
                built = self._host_table_build(
                    built[1], op, l_dt, left_dicts, lc, rc
                )
        else:
            if not isinstance(right_res, HostBatch):
                return None
            built = self._host_table_build(right_res, op, l_dt, left_dicts, lc, rc)
        if built is None:
            return None
        lo, dom, found, value_tables, right_rel = built

        # Output naming: all left columns keep their names; right value
        # columns (minus the key) merge with the join suffix — the same
        # schema ``_join_out_schema`` produces for the host paths.
        try:
            out_rel = left_rel.merge(
                right_rel.select(
                    [c for c in right_rel.column_names if c not in op.right_on]
                ),
                suffix=op.suffix,
            )
        except Exception:
            return None
        value_srcs = [c for c in right_rel.column_names if c not in op.right_on]
        out_names = out_rel.column_names[len(left_rel.column_names):]

        out_cols = []
        side: dict = {}
        prefix = f"__lj{nid}"
        for src, out_name in zip(value_srcs, out_names):
            dt = right_rel.col_type(src)
            if dt == DataType.STRING:
                return None  # string values need mid-chain dict plumbing
            planes = value_tables[src]
            out_cols.append((out_name, dt, len(planes)))
            for j, p in enumerate(planes):
                side[f"{prefix}:{out_name}:{j}"] = p
        side[f"{prefix}:found"] = found

        lj = LookupJoinOp(
            key_col=lc, how=op.how, prefix=prefix, lo=int(lo), dom=int(dom),
            out_cols=tuple(out_cols),
        )
        st = left_res.extend(lj)
        st.side.update(side)
        return st

    def _dense_agg_build(self, right_stream, op, l_dt, left_dicts, lc, rc):
        """Build lookup tables straight from a dense aggregate's device
        state: the slot-aligned finalize output IS the table (slot =
        key - lo), so the build side never visits the host."""
        if any(isinstance(o, LimitOp) for o in right_stream.chain):
            return None
        frag_probe = compile_fragment(
            right_stream.chain, right_stream.relation, right_stream.dicts,
            self.registry, col_stats=_stream_col_stats(right_stream),
        )
        if (
            not frag_probe.is_agg
            or len(frag_probe.dense_domains) != 1
            or frag_probe.limit is not None
        ):
            return None
        # The dense slot space must be the probe key's own code space.
        agg_i = next(
            i for i, o in enumerate(right_stream.chain)
            if isinstance(o, AggOp)
        )
        agg = right_stream.chain[agg_i]
        if tuple(agg.group_cols) != (rc,):
            return None
        # Post-agg ops must leave the key column untouched — the slot
        # arithmetic pairs probe keys with SLOT indices, so a post map
        # that rewrites the key would silently mispair every row.
        for o in right_stream.chain[agg_i + 1:]:
            if isinstance(o, MapOp):
                key_expr = dict(o.exprs).get(rc)
                if key_expr != _col(rc):
                    return None
        out_rel = frag_probe.relation
        if rc not in out_rel.column_names:
            return None
        if out_rel.col_type(rc) != l_dt:
            return None
        if l_dt == DataType.STRING:
            meta = next(m for m in frag_probe.out_meta if m.name == rc)
            if left_dicts.get(lc) is not meta.dict:
                return None
        if any(m.struct_fields for m in frag_probe.out_meta):
            return None
        dr = self._run_fragment(right_stream)
        reject = bool(np.asarray(dr._overflow))  # stats raced an append
        value_tables = {
            n: tuple(dr._cols[n])
            for n in out_rel.column_names
            if n != rc and n in dr._cols
        }
        if set(value_tables) != {c for c in out_rel.column_names if c != rc}:
            reject = True
        if reject:
            # Don't discard the executed aggregate: hand the (rebucketed
            # if needed) rows back so the generic join path reuses them
            # instead of re-folding the whole right stream.
            return ("fallback", dr.to_host())
        return (
            frag_probe.dense_offsets[0], frag_probe.dense_domains[0],
            dr._valid, value_tables, out_rel,
        )

    def _host_table_build(self, right_hb, op, l_dt, left_dicts, lc, rc):
        """Build dense lookup tables from a materialized unique-key host
        batch (the post-agg N:1 case arriving as rows)."""
        from ..config import get_flag

        if not right_hb.relation.has_column(rc):
            return None
        if right_hb.relation.col_type(rc) != l_dt:
            return None
        if right_hb.length == 0:
            return None
        kb = np.asarray(right_hb.cols[rc][0])
        if l_dt == DataType.STRING:
            ld = left_dicts.get(lc)
            rd = right_hb.dicts.get(rc)
            if ld is None or rd is None:
                return None
            if rd is not ld:
                # Re-express build keys in the probe's id space without
                # growing it: unseen keys can never match a probe row.
                remap = np.fromiter(
                    (ld.lookup(s) for s in rd.strings),
                    dtype=np.int64, count=len(rd),
                )
                kb = np.where(kb >= 0, remap[np.clip(kb, 0, None)], -1)
            lo, dom = 0, len(ld) + 1
            in_dom = kb >= 0
        elif l_dt in (DataType.INT64, DataType.TIME64NS):
            lo, hi = int(kb.min()), int(kb.max())
            dom = hi - lo + 1
            if dom > get_flag("int_dense_domain_limit"):
                return None
            in_dom = np.ones(len(kb), dtype=bool)
        else:
            return None
        idx = np.where(in_dom, kb - lo, 0)
        found = np.zeros(dom, dtype=bool)
        # Uniqueness: a duplicate build key means N:M — not this path.
        found[idx[in_dom]] = True
        if int(found.sum()) != int(in_dom.sum()):
            return None
        from ..types.dtypes import device_dtypes

        value_tables = {}
        for c in right_hb.relation.column_names:
            if c == rc:
                continue
            ddts = device_dtypes(right_hb.relation.col_type(c))
            planes = []
            for p, ddt in zip(right_hb.cols[c], ddts):
                # Device dtype, not host: FLOAT64 host planes are f64 but
                # the device-plane invariant is f32 — an f64 side table
                # would re-admit f64 into fused device code.
                p = np.asarray(p)
                t = np.zeros(dom, dtype=ddt)
                if len(p):
                    t[idx[in_dom]] = p[in_dom]
                planes.append(t)
            value_tables[c] = tuple(planes)
        return lo, dom, found, value_tables, right_hb.relation

    def _materialize(self, res) -> HostBatch:
        if isinstance(res, HostBatch):
            return res
        if isinstance(res, DeviceResult):
            return res.to_host()
        dr = self._run_fragment(res)
        if isinstance(dr, DeviceResult):
            return dr.to_host()
        return dr

    def _run_fragment(self, stream: "_Stream"):
        """Run a stream's fragment; agg chains return a DeviceResult
        (device-resident, no host readback — the first device-to-host
        transfer permanently switches the axon tunnel into a slow
        synchronous dispatch mode, so callers defer it as long as
        possible), non-agg chains a HostBatch."""
        frag = compile_fragment(
            stream.chain, stream.relation, stream.dicts, self.registry,
            col_stats=_stream_col_stats(stream),
        )
        qstats = getattr(self, "_query_stats", None)
        stats = qstats.new_fragment(stream.chain) if qstats is not None else None

        if frag.is_agg:
            state = self._fold_agg_state(stream, frag, stats)
            with _timed(stats, "finalize"):
                cols, valid, overflow = frag.finalize(state)
                _block_if(stats, (cols, valid, overflow))
            return DeviceResult(
                self, stream, frag, cols, valid, overflow, stats,
                qstats=getattr(self, "_query_stats", None),
            )

        # Non-agg: stream windows, stop early once a limit is satisfied.
        _, _, rows_step = self._compile_steps(frag)
        pieces, total = [], 0
        for cols, valid in self._staged_windows(stream, stats):
            with _timed(stats, "compute"):
                out_cols, out_valid = rows_step(cols, valid)
                _block_if(stats, (out_cols, out_valid))
            if stats is not None:
                stats.windows += 1
            with _timed(stats, "materialize"):
                piece = _to_host_batch(
                    frag.out_meta, out_cols, np.asarray(out_valid)
                )
            pieces.append(piece)
            total += piece.length
            if frag.limit is not None and total >= frag.limit:
                break
        out = _concat_host(pieces, frag.relation)
        if stats is not None:
            stats.rows_out = out.length
        return _apply_limit(out, frag.limit)


def _window_shapes(cols) -> tuple:
    """Shape/dtype signature of a staged window (scan batching requires
    identical signatures so the stacked treedef stays one program).
    Side inputs are query-constant and never affect batchability."""
    return tuple(
        (c, tuple((p.shape, str(p.dtype)) for p in planes))
        for c, planes in sorted(cols.items())
        if c != "__side__"
    )


def _timed(stats, stage: str, rows: int = 0):
    """Stage timer context (no-op without stats) — keeps the analyze and
    plain execution paths one code path."""
    if stats is None:
        import contextlib

        return contextlib.nullcontext()
    return stats.timed(stage, rows)


def _block_if(stats, x) -> None:
    """block_until_ready under analyze only (attribution needs sync)."""
    if stats is not None:
        import jax

        jax.block_until_ready(x)


def _col(name):
    from .plan import ColumnRef

    return ColumnRef(name)


def _double_agg_groups(stream: "_Stream") -> "_Stream":
    """Return the stream with its AggOp's max_groups doubled (rebucket)."""
    import dataclasses

    from ..config import get_flag

    limit = get_flag("max_groups_limit")
    chain = []
    doubled = False
    for op in stream.chain:
        if isinstance(op, AggOp) and not doubled:
            g2 = op.max_groups * 2
            if g2 > limit:
                raise QueryError(
                    f"group-by overflow at max_groups={op.max_groups}; "
                    f"rebucketing past the {limit} cap refused "
                    "(PIXIE_TPU_MAX_GROUPS_LIMIT)"
                )
            chain.append(dataclasses.replace(op, max_groups=g2))
            doubled = True
        else:
            chain.append(op)
    if not doubled:
        raise AssertionError("no AggOp in overflowing chain")
    return _Stream(
        stream.relation, stream.dicts, chain, stream.source, stream.source_op
    )


def _to_host_batch(meta_list, cols, valid) -> HostBatch:
    idx = np.nonzero(valid)[0]
    out_cols: dict = {}
    dicts: dict = {}
    rel_items = []
    for m in meta_list:
        if m.struct_fields is not None:
            planes = np.asarray(cols[m.name][0])[idx]  # [rows, k] floats
            d = StringDictionary()
            ids = np.fromiter(
                (
                    d.get_or_add(
                        json.dumps(
                            {f: round(float(v), 6) for f, v in zip(m.struct_fields, row)}
                        )
                    )
                    for row in planes
                ),
                dtype=np.int32,
                count=len(planes),
            )
            out_cols[m.name] = (ids,)
            dicts[m.name] = d
            rel_items.append((m.name, DataType.STRING))
            continue
        hdts = host_dtypes(m.dtype)
        out_cols[m.name] = tuple(
            np.asarray(p)[idx].astype(h) for p, h in zip(cols[m.name], hdts)
        )
        if m.dict is not None:
            dicts[m.name] = m.dict
        rel_items.append((m.name, m.dtype))
    return HostBatch(
        relation=Relation(rel_items), cols=out_cols, length=len(idx), dicts=dicts
    )


def _empty_host_batch(relation, dicts=None) -> HostBatch:
    cols = {
        n: tuple(np.empty(0, dtype=h) for h in host_dtypes(t))
        for n, t in relation.items()
    }
    return HostBatch(relation=relation, cols=cols, length=0, dicts=dict(dicts or {}))


def _concat_host(pieces, relation) -> HostBatch:
    nonempty = [p for p in pieces if p.length > 0]
    if not nonempty:
        dicts = pieces[0].dicts if pieces else {}
        return _empty_host_batch(relation, dicts)
    pieces = nonempty
    first = pieces[0]
    if len(pieces) == 1:
        return first
    cols = {
        n: tuple(
            np.concatenate([p.cols[n][i] for p in pieces])
            for i in range(len(first.cols[n]))
        )
        for n in first.relation.column_names
    }
    return HostBatch(
        relation=first.relation,
        cols=cols,
        length=sum(p.length for p in pieces),
        dicts=first.dicts,
    )


def _apply_limit(hb: HostBatch, limit) -> HostBatch:
    if limit is None or hb.length <= limit:
        return hb
    return HostBatch(
        relation=hb.relation,
        cols={n: tuple(p[:limit] for p in ps) for n, ps in hb.cols.items()},
        length=limit,
        dicts=hb.dicts,
    )


def _key_tuples(hb: HostBatch, on, remaps):
    keys = []
    for c in on:
        ids = hb.cols[c][0]
        if c in remaps:
            # Null string ids (-1) must stay null, not wrap to the last entry.
            ids = np.where(
                ids >= 0, remaps[c][np.clip(ids, 0, None)], NULL_ID
            ).astype(ids.dtype)
        keys.append(ids)
    extra = [hb.cols[c][1] for c in on if len(hb.cols[c]) > 1]
    return list(zip(*(list(k) for k in (keys + extra)))) if keys else []


# Inputs smaller than this run the host dict join (when N:1 applies);
# larger inputs and right/outer/N:M joins go to the device kernel.
DEVICE_JOIN_MIN_ROWS = 1 << 15


def _join_dispatch(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """Route a join to the host N:1 path or the device N:M kernel.

    Reference: ``equijoin_node.cc`` always hash-joins; here small unique-
    key inner/left joins (the post-agg common case) stay on host, and
    everything else uses ``pixie_tpu.ops.join.device_join``.
    """
    if len(op.left_on) != len(op.right_on):
        raise QueryError("join key arity mismatch")
    small = left.length + right.length < DEVICE_JOIN_MIN_ROWS
    if op.how in ("inner", "left") and small:
        try:
            return _join_host(left, right, op)
        except _BuildNotUnique:
            pass  # N:M fan-out -> device kernel
    if left.length == 0 or right.length == 0:
        return _join_degenerate(left, right, op)
    import jax

    if op.how in ("inner", "left") and jax.default_backend() != "tpu":
        # XLA CPU sorts make the device kernel a regression there; the
        # vectorized numpy N:M join is the CPU-backend fast path.
        return _join_host_nm(left, right, op)
    return _join_device(left, right, op)


class _BuildNotUnique(Exception):
    pass


def _align_join_dicts(left, right, op):
    """String-dictionary id remaps so key ids compare across sides.

    Returns (l_remap, r_remap, key_dicts): key_dicts maps a left key
    column to the merged dictionary (union preserves left ids, so pair
    rows stay valid and coalesced build-side ids land past them).
    """
    l_remap: dict = {}
    r_remap: dict = {}
    key_dicts: dict = {}
    for lc, rc in zip(op.left_on, op.right_on):
        ld, rd = left.dicts.get(lc), right.dicts.get(rc)
        if ld is not None and rd is not None and ld is not rd:
            merged, rl, rr = ld.union(rd)
            l_remap[lc], r_remap[rc] = rl, rr
            key_dicts[lc] = merged
    return l_remap, r_remap, key_dicts


def _join_out_schema(left, right, op):
    """(out_rel, ordered (side, src_col) pairs) for join output columns."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    src = [("l", c) for c in left.relation.column_names] + [
        ("r", c) for c in right.relation.column_names if c not in op.right_on
    ]
    return out_rel, src


def _join_degenerate(left, right, op: JoinOp) -> HostBatch:
    """Joins where one side is empty (device kernel needs real rows)."""
    out_rel, src = _join_out_schema(left, right, op)
    if op.how == "inner" or (op.how == "left" and left.length == 0) or (
        op.how == "right" and right.length == 0
    ):
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    elif op.how in ("left", "outer") and right.length == 0:
        keep_l, keep_r = np.arange(left.length), np.full(left.length, -1)
    elif op.how in ("right", "outer") and left.length == 0:
        keep_l, keep_r = np.full(right.length, -1), np.arange(right.length)
    else:  # outer with one side non-empty handled above; both empty:
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    _, r_remap, key_dicts = _align_join_dicts(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        keep_l, keep_l >= 0, keep_r, keep_r >= 0,
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _assemble_join(left, right, op, out_rel, src, l_idx, l_take, r_idx, r_take,
                   r_remap=None, key_dicts=None):
    """Gather output columns from per-row indices + take masks.

    Join key columns coalesce (SQL USING semantics): a right/outer extra
    row — whose probe side is null — takes its key from the build side,
    remapped into the merged dictionary for strings.
    """
    r_remap = r_remap or {}
    key_dicts = key_dicts or {}
    key_map = dict(zip(op.left_on, op.right_on))
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for side, c in src:
        n = next(names)
        hb = left if side == "l" else right
        idx = l_idx if side == "l" else r_idx
        take = l_take if side == "l" else r_take
        rc = key_map.get(c) if side == "l" else None
        nullv = NULL_ID if hb.relation.col_type(c) == DataType.STRING else 0
        planes = []
        for pi, p in enumerate(hb.cols[c]):
            if len(p) == 0:
                taken = np.full(len(idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(idx, 0, len(p) - 1)]
            if not take.all():
                if rc is not None:
                    q = right.cols[rc][pi]
                    if pi == 0 and rc in r_remap:
                        q = np.where(
                            q >= 0, r_remap[rc][np.clip(q, 0, None)], NULL_ID
                        ).astype(q.dtype)
                    alt = (
                        np.full(len(r_idx), nullv, dtype=p.dtype)
                        if len(q) == 0
                        else q[np.clip(r_idx, 0, len(q) - 1)]
                    )
                    taken = np.where(
                        take, taken, np.where(r_take, alt, nullv)
                    ).astype(p.dtype)
                else:
                    taken = np.where(take, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in hb.dicts:
            out_dicts[n] = (
                key_dicts.get(c, hb.dicts[c]) if side == "l" else hb.dicts[c]
            )
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _join_key_planes(hb, cols, remaps):
    planes = []
    for c in cols:
        for i, p in enumerate(hb.cols[c]):
            if i == 0 and c in remaps:
                p = np.where(
                    p >= 0, remaps[c][np.clip(p, 0, None)], NULL_ID
                ).astype(p.dtype)
            planes.append(p)
    return planes


@functools.lru_cache(maxsize=64)
def _device_join_cache(n_build, n_probe, dtypes, capacity, how):
    """One jitted kernel per (bucketed shapes, key dtypes, capacity, how)."""
    import jax

    from ..ops.join import device_join

    return jax.jit(
        lambda bk, bv, pk, pv: device_join(bk, bv, pk, pv, capacity, how)
    )


def _join_device(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:M device join: pad to bucketed capacities, run the sort-based
    kernel, re-run doubled on overflow, gather columns host-side."""
    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    probe_planes = _join_key_planes(left, op.left_on, l_remap)
    build_planes = _join_key_planes(right, op.right_on, r_remap)
    for bp, pp in zip(build_planes, probe_planes):
        if bp.dtype != pp.dtype:
            raise QueryError(
                f"join key dtype mismatch: {bp.dtype} vs {pp.dtype}"
            )

    nb, np_ = bucket_capacity(right.length), bucket_capacity(left.length)

    def pad(p, cap):
        out = np.zeros(cap, dtype=p.dtype)
        out[: len(p)] = p
        return out

    bk = [pad(p, nb) for p in build_planes]
    pk = [pad(p, np_) for p in probe_planes]
    bv = np.zeros(nb, dtype=bool)
    bv[: right.length] = True
    pv = np.zeros(np_, dtype=bool)
    pv[: left.length] = True

    capacity = bucket_capacity(max(left.length + right.length, 1))
    while True:
        fn = _device_join_cache(
            nb, np_, tuple(str(p.dtype) for p in bk), capacity, op.how
        )
        p_idx, p_take, b_idx, b_take, out_valid, overflow = (
            np.asarray(a) for a in fn(bk, bv, pk, pv)
        )
        if not bool(overflow):
            break
        capacity *= 2

    sel = np.nonzero(out_valid)[0]
    out_rel, src = _join_out_schema(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        p_idx[sel], p_take[sel], b_idx[sel], b_take[sel],
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _join_host(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:1 equijoin on host (post-agg inputs are small).

    Reference: ``src/carnot/exec/equijoin_node.cc`` build+probe — here the
    build side must be unique on the key (raises _BuildNotUnique for the
    dispatcher to fall through to the device kernel).
    """
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)

    lk = _key_tuples(left, op.left_on, l_remap)
    rk = _key_tuples(right, op.right_on, r_remap)
    lookup: dict = {}
    for i, k in enumerate(rk):
        if k in lookup:
            raise _BuildNotUnique(op.right_on, k)
        lookup[k] = i

    match = np.fromiter((lookup.get(k, -1) for k in lk), dtype=np.int64, count=len(lk))
    if op.how == "inner":
        l_idx = np.nonzero(match >= 0)[0]
    elif op.how == "left":
        l_idx = np.arange(left.length)
    else:
        raise QueryError(f"unsupported join how={op.how!r}")
    r_idx = match[l_idx]
    return _assemble_join_host(left, right, op, l_idx, r_idx)


def _join_host_nm(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """Vectorized N:M inner/left equijoin on host (numpy sort+searchsorted)
    — the CPU-backend analog of the device kernel (XLA CPU sorts are too
    slow to route big joins through the device path there)."""
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)
    lk = _packed_key_ids(left, op.left_on, l_remap,
                         right, op.right_on, r_remap)
    lkeys, rkeys = lk
    order = np.argsort(rkeys, kind="stable")
    span = 0
    if len(rkeys) and len(lkeys):
        kmin = min(int(rkeys.min()), int(lkeys.min()))
        kmax = max(int(rkeys.max()), int(lkeys.max()))
        span = kmax - kmin + 1
    if 0 < span <= 4 * (len(lkeys) + len(rkeys)):
        # Dense key range: bincount + cumsum offsets replace the two
        # binary searches (random-access searchsorted over millions of
        # probes is the profile's hot spot).
        kcounts = np.bincount(rkeys - kmin, minlength=span)
        key_starts = np.zeros(span + 1, dtype=np.int64)
        np.cumsum(kcounts, out=key_starts[1:])
        lo = key_starts[lkeys - kmin]
        counts = kcounts[lkeys - kmin]
        hi = lo + counts
    else:
        srk = rkeys[order]
        lo = np.searchsorted(srk, lkeys, side="left")
        hi = np.searchsorted(srk, lkeys, side="right")
        counts = hi - lo
    if op.how == "left":
        counts = np.maximum(counts, 1)  # unmatched keep one null row
        unmatched = (hi - lo) == 0
    total = int(counts.sum())
    starts = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    l_idx = np.repeat(np.arange(left.length, dtype=np.int64), counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(starts[:-1], counts)
    if len(rkeys):
        r_idx = order[
            np.clip(np.repeat(lo, counts) + within, 0, len(rkeys) - 1)
        ]
    else:
        r_idx = np.full(total, -1, dtype=np.int64)
    if op.how == "left" and len(rkeys):
        r_idx = np.where(np.repeat(unmatched, counts), -1, r_idx)
    return _assemble_join_host(left, right, op, l_idx, r_idx)


def _packed_key_ids(left, left_on, l_remap, right, right_on, r_remap):
    """Dense i64 key ids comparable across both sides (np.unique over the
    stacked key planes of the concatenated inputs)."""
    def planes(b, cols, remap):
        out = []
        for c in cols:
            for i, p in enumerate(b.cols[c]):
                q = p
                if i == 0 and c in remap:
                    q = remap[c][np.clip(p, 0, None)]
                    q = np.where(p >= 0, q, NULL_ID)
                out.append(np.asarray(q))
        return out
    lp = planes(left, left_on, l_remap)
    rp = planes(right, right_on, r_remap)
    if len(lp) == 1:
        # Single-plane keys compare directly — no densification pass.
        return (lp[0].astype(np.int64, copy=False),
                rp[0].astype(np.int64, copy=False))
    stacked = np.stack(
        [np.concatenate([a.astype(np.int64, copy=False),
                         b.astype(np.int64, copy=False)])
         for a, b in zip(lp, rp)],
        axis=1,
    )
    _, inv = np.unique(stacked, axis=0, return_inverse=True)
    inv = inv.astype(np.int64).reshape(-1)
    return inv[: left.length], inv[left.length:]


def _assemble_join_host(left, right, op, l_idx, r_idx) -> HostBatch:
    """Row assembly for the host N:1 / N:M paths (r_idx=-1 -> null)."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for c in left.relation.column_names:
        n = next(names)
        out_cols[n] = tuple(p[l_idx] for p in left.cols[c])
        if c in left.dicts:
            out_dicts[n] = left.dicts[c]
    for c in right.relation.column_names:
        if c in op.right_on:
            continue
        n = next(names)
        planes = []
        nullv = NULL_ID if right.relation.col_type(c) == DataType.STRING else 0
        for p in right.cols[c]:
            if len(p) == 0:  # empty build side: all-null fill
                taken = np.full(len(l_idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(r_idx, 0, None)]
                if op.how == "left":
                    taken = np.where(r_idx >= 0, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in right.dicts:
            out_dicts[n] = right.dicts[c]
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _union_host(mats) -> HostBatch:
    """Schema-aligned union with dictionary re-encoding.

    When the schema carries a ``time_`` column the result is merged in
    time order — the reference UnionNode's k-way ordered merge of
    cross-PEM streams (``src/carnot/exec/union_node.cc``); a stable sort
    over the concatenation is equivalent given each input is itself
    time-ordered, and stays a single vectorized pass.
    """
    first = mats[0]
    for m in mats[1:]:
        if tuple(m.relation.column_names) != tuple(first.relation.column_names):
            raise QueryError("union inputs must share a schema")
    out_cols: dict = {}
    out_dicts: dict = {}
    for c, dt in first.relation.items():
        if dt == DataType.STRING:
            merged = StringDictionary()
            planes = []
            for m in mats:
                d = m.dicts.get(c, StringDictionary())
                # union preserves existing ids (append-only), so earlier
                # planes stay valid as merged grows.
                merged, _, remap = merged.union(d)
                ids = m.cols[c][0]
                planes.append(
                    np.where(ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID).astype(
                        np.int32
                    )
                )
            out_cols[c] = (np.concatenate(planes),)
            out_dicts[c] = merged
        else:
            out_cols[c] = tuple(
                np.concatenate([m.cols[c][i] for m in mats])
                for i in range(len(first.cols[c]))
            )
    if first.relation.has_column("time_"):
        order = np.argsort(out_cols["time_"][0], kind="stable")
        if not np.array_equal(order, np.arange(len(order))):
            out_cols = {
                c: tuple(p[order] for p in ps) for c, ps in out_cols.items()
            }
    return HostBatch(
        relation=first.relation,
        cols=out_cols,
        length=sum(m.length for m in mats),
        dicts=out_dicts,
    )
