"""Watermark-validated merged-result cache (the repeat-query fast path).

The dominant workload is the same ~75 bundled ``px/`` scripts
re-executed over moving time windows; every run today rescans O(data)
rows to recompute an answer that — between ingest watermark advances —
cannot have changed. PR 14's never-regressing per-table event-time
watermarks (``Table.watermark_ns``, cluster-merged by
``AgentTracker.table_stats()``) are exactly the validity predicate a
result cache needs, so this module caches a query's *merged result*
keyed on the script and validates it purely by watermark comparison —
never wall-clock TTL.

Key and validity
----------------

An entry is keyed on ``(sha256(script text), max_output_rows)`` —
deliberately NOT on ``now_ns``: a dashboard replaying the same script
over an advancing window must still hit. Instead each entry stores, at
execute time,

- the scanned-table set (from the compiled plan's MemorySourceOps) and
  each table's watermark,
- the resolved ``now_ns`` the time predicates were compiled against,
- whether the plan is time-dependent at all (any start/stop bound).

A lookup re-reads the CURRENT watermarks for the stored table set (no
compile needed — that is what makes a hit zero-cost) and classifies:

- ``miss``   — no entry, or a stored watermark EXCEEDS the current one
  (watermark regression: table expiry churn or an agent lost from the
  cluster view — the cached answer may cover rows that no longer
  exist, so the entry is dropped, and the re-execution degrades
  through the normal partial-results machinery exactly like a live
  query would);
- ``hit``    — every scanned table's watermark is unchanged, or
  advanced by at most the script's staleness budget, AND (for
  time-dependent plans) the requested ``now`` drifted from the stored
  one by at most that same budget. The served result re-stamps
  ``freshness_lag_ms`` against the CURRENT clock/watermarks: a hit is
  honest about its age.
- ``stale``  — entry exists but a watermark advanced (or ``now``
  drifted) beyond the budget: the caller re-executes and the fresh
  result replaces the entry.

The staleness budget comes from the script's manifest
(``staleness_budget_ms`` in ``manifest.yaml``) when the executed text
IS a bundled script, else the ``result_cache_staleness_ms`` flag.
Results that are partial, mutation-bearing (pxtrace), or scan a table
with no watermark are never stored (``bypass``).

Capacity is a byte-budgeted LRU ring (``result_cache_mb``; 0 disables
the cache entirely). Metrics: ``pixie_result_cache_{hits,misses,
stale}_total`` counters + the ``pixie_result_cache_bytes`` gauge
(inc/dec so broker- and engine-side instances sum). ``cachez()`` is
the ``/debug/cachez`` payload.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..config import get_flag

#: Statuses a query trace's ``cache`` field can carry.
HIT, MISS, STALE, BYPASS, VIEW = "hit", "miss", "stale", "bypass", "view"


def script_sha(script: str) -> str:
    return hashlib.sha256((script or "").encode()).hexdigest()


def scan_info(plan) -> tuple[tuple, bool]:
    """(scanned tables, time_dependent) from a compiled logical plan:
    the stored half of the validity predicate. ``time_dependent`` is
    True when any source carries a start/stop bound — only then can a
    repeat at a later ``now`` select different rows from UNCHANGED
    data (the window slid), so only then does the ``now``-drift check
    apply."""
    from .plan import MemorySourceOp

    tables: list = []
    time_dep = False
    for nid in plan.topo_order():
        op = plan.nodes[nid].op
        if isinstance(op, MemorySourceOp):
            if op.table not in tables:
                tables.append(op.table)
            if op.start_time is not None or op.stop_time is not None:
                time_dep = True
    return tuple(tables), time_dep


def result_nbytes(obj) -> int:
    """Recursive payload size estimate: HostBatch/ndarray ``.nbytes``
    where available, container sums otherwise. Feeds the LRU budget —
    an estimate, so it only needs to be proportional, not exact."""
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        try:
            return int(nb)
        except (TypeError, ValueError):
            pass
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, str):
        return len(obj.encode("utf-8", "ignore"))
    if isinstance(obj, dict):
        return sum(
            result_nbytes(k) + result_nbytes(v) for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple)):
        return sum(result_nbytes(v) for v in obj)
    return 64  # scalars / small objects


_BUDGET_CACHE: dict | None = None
_BUDGET_LOCK = threading.Lock()


def manifest_budgets() -> dict:
    """{sha256(pxl text): staleness_budget_ms} over the shipped script
    library — how a manifest's ``staleness_budget_ms`` reaches the
    cache when the executed text is a bundled script (the broker sees
    raw PxL, not script names). Loaded once per process."""
    global _BUDGET_CACHE
    with _BUDGET_LOCK:
        if _BUDGET_CACHE is None:
            budgets: dict = {}
            try:
                from ..scripts import load_all

                for sd in load_all():
                    ms = sd.manifest.get("staleness_budget_ms")
                    if ms is not None:
                        budgets[script_sha(sd.pxl)] = float(ms)
            except Exception:
                pass  # no script library (stripped deploys) — flag only
            _BUDGET_CACHE = budgets
        return _BUDGET_CACHE


@dataclass
class CacheEntry:
    key: tuple
    script_hash: str  # short hash (trace/script_hash parity, 12 hex)
    sha: str  # full key hash (manifest budget lookup)
    result: dict
    tables: tuple
    watermarks: dict  # table -> watermark_ns at store time
    stored_now_ns: int  # resolved compile-time now (time predicates)
    time_dependent: bool
    nbytes: int
    stored_unix_ns: int = field(default_factory=time.time_ns)
    hits: int = 0


class ResultCache:
    """Byte-budgeted LRU of merged query results, watermark-validated.

    Thread-safe; shared by the broker's execute path and (a separate
    instance) the local engine. All methods are cheap: a lookup is a
    dict probe + one watermark read per scanned table.
    """

    def __init__(self, registry=None):
        self._entries: "OrderedDict[tuple, CacheEntry]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self._registry = registry
        self._metrics: dict | None = None

    # -- config --------------------------------------------------------------
    @staticmethod
    def budget_bytes() -> int:
        return int(get_flag("result_cache_mb")) << 20

    def enabled(self) -> bool:
        return self.budget_bytes() > 0

    @staticmethod
    def staleness_budget_ms(sha: str) -> float:
        ms = manifest_budgets().get(sha)
        if ms is None:
            ms = float(get_flag("result_cache_staleness_ms"))
        return max(0.0, ms)

    # -- metrics -------------------------------------------------------------
    def _m(self) -> dict:
        if self._metrics is None:
            reg = self._registry
            if reg is None:
                from ..services.observability import default_registry

                reg = self._registry = default_registry
            self._metrics = {
                HIT: reg.counter(
                    "pixie_result_cache_hits_total",
                    "Queries served from the watermark-validated result "
                    "cache (zero compile/admission/dispatch cost)",
                ),
                MISS: reg.counter(
                    "pixie_result_cache_misses_total",
                    "Cacheable queries with no valid entry (absent or "
                    "watermark-regressed)",
                ),
                STALE: reg.counter(
                    "pixie_result_cache_stale_total",
                    "Cache entries found but past the script's "
                    "staleness budget (re-executed and replaced)",
                ),
                "bytes": reg.gauge(
                    "pixie_result_cache_bytes",
                    "Bytes held by result-cache entries (LRU budget "
                    "result_cache_mb; summed across broker + engine "
                    "instances)",
                ),
            }
        return self._metrics

    # -- core ----------------------------------------------------------------
    def lookup(self, script: str, now_ns: int, max_output_rows: int,
               wm_of) -> tuple[str, CacheEntry | None, float]:
        """Classify a repeat: ``(status, entry, freshness_lag_ms)``.

        ``wm_of(table) -> int | None`` reads the CURRENT watermark
        (cluster-merged at the broker, local max at an engine).
        ``entry`` is non-None only for ``hit``; ``freshness_lag_ms`` is
        the re-stamped staleness the served result should carry (worst
        scanned table, measured against the current clock).
        """
        sha = script_sha(script)
        key = (sha, int(max_output_rows))
        with self._lock:
            e = self._entries.get(key)
        if e is None:
            self._m()[MISS].inc()
            return MISS, None, 0.0
        budget_ms = self.staleness_budget_ms(sha)
        req_now = int(now_ns) or time.time_ns()
        stale = False
        lag_ms = 0.0
        for t in e.tables:
            cur = wm_of(t)
            stored = e.watermarks[t]
            if cur is None or cur < stored:
                # Watermark regression: expiry churn or an agent fell
                # out of the cluster view — rows the cached answer
                # covers may be gone. Drop the entry; the re-execution
                # degrades like any live query (partial results).
                self._drop(key)
                self._m()[MISS].inc()
                return MISS, None, 0.0
            if (cur - stored) / 1e6 > budget_ms:
                stale = True
            lag_ms = max(lag_ms, (req_now - stored) / 1e6)
        if e.time_dependent and (req_now - e.stored_now_ns) / 1e6 > budget_ms:
            stale = True
        if stale:
            self._m()[STALE].inc()
            return STALE, None, 0.0
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
            e.hits += 1
        self._m()[HIT].inc()
        return HIT, e, max(0.0, round(lag_ms, 3))

    def store(self, script: str, resolved_now_ns: int,
              max_output_rows: int, plan, result: dict, wm_of) -> str:
        """Insert a freshly computed result; returns the disposition
        for the trace (``miss`` = stored, ``bypass`` = not cacheable:
        no scanned table, or a scanned table with no watermark yet —
        without a watermark there is no validity predicate)."""
        tables, time_dep = scan_info(plan)
        if not tables:
            return BYPASS
        wms: dict = {}
        for t in tables:
            wm = wm_of(t)
            if wm is None:
                return BYPASS
            wms[t] = int(wm)
        nbytes = result_nbytes(result)
        budget = self.budget_bytes()
        if nbytes > budget:
            return MISS  # counted at lookup; too big to ever serve
        sha = script_sha(script)
        key = (sha, int(max_output_rows))
        e = CacheEntry(
            key=key, script_hash=sha[:12], sha=sha, result=result,
            tables=tables, watermarks=wms,
            stored_now_ns=int(resolved_now_ns) or time.time_ns(),
            time_dependent=time_dep, nbytes=nbytes,
        )
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = e
            self._bytes += nbytes
            while self._bytes > budget and len(self._entries) > 1:
                k, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                evicted.append(k)
            total = self._bytes
        self._m()["bytes"].set(total)
        return MISS

    def _drop(self, key: tuple) -> None:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._bytes -= e.nbytes
            total = self._bytes
        self._m()["bytes"].set(total)

    def clear(self) -> None:
        """Drop everything — the agent-churn hammer: a register or
        expiry changes which shards a merged result covers, and the
        cluster watermark alone cannot always see that (a restarted
        agent may re-report the same max). Cheap to be conservative:
        the next repeat re-executes and re-primes."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
        self._m()["bytes"].set(0)

    # -- introspection (/debug/cachez) ---------------------------------------
    def cachez(self) -> dict:
        with self._lock:
            entries = [
                {
                    "script_hash": e.script_hash,
                    "tables": list(e.tables),
                    "watermarks": dict(e.watermarks),
                    "time_dependent": e.time_dependent,
                    "nbytes": e.nbytes,
                    "hits": e.hits,
                    "stored_unix_ns": e.stored_unix_ns,
                    "max_output_rows": e.key[1],
                    "staleness_budget_ms": self.staleness_budget_ms(e.sha),
                }
                for e in self._entries.values()
            ]
            total = self._bytes
        return {
            "enabled": self.enabled(),
            "budget_bytes": self.budget_bytes(),
            "bytes": total,
            "entries": entries,
        }
