"""Per-query execution statistics (the ``analyze`` flag).

Reference parity: every Carnot ExecNode tracks ``ExecNodeStats``
(bytes/rows/batches, self vs child timers — ``src/carnot/exec/
exec_node.h:40-127``) and ``ExecutePlan`` ships per-operator
``queryresultspb.OperatorExecutionStats`` (``carnot.cc:389-423``). Here
the unit of execution is a compiled *fragment* (a whole Map/Filter/Agg
chain), so stats attach per fragment with a per-stage wall-time
breakdown of the TPU streaming pipeline:

- ``read``     host slab -> host window (cursor read)
- ``stage``    host -> device transfer + padding (zero when the window
               was already device-resident)
- ``compute``  device program (update/fold), measured to completion
- ``finalize`` agg finalize program
- ``materialize`` device -> host copy + host batch assembly
- ``stall``    consumer time blocked waiting on the window-prefetch
               pipeline (pipeline_depth > 1); high stall with low stage
               time means the device, not staging, is the bottleneck

Since the query-lifecycle tracing subsystem (``trace.py``) landed,
these stats are one detail level of the always-on trace spine: every
query gets a ``QueryStats`` (attached to its ``QueryTrace``) with
``sync=False`` — stage timers stamp host-side wall-clock boundaries and
overlap survives. Enabling ``analyze`` sets ``sync=True``, which forces
synchronization after each stage (``block_until_ready``) so stage times
attribute real device work — overlap is sacrificed for attribution; run
benchmarks with it off. With the pipelined window executor the ``stage``
timer runs on the prefetch thread while ``compute`` runs on the query
thread, so FragmentStats.add is lock-protected.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class StageStat:
    seconds: float = 0.0
    rows: int = 0
    count: int = 0
    # Bytes moved by this stage (today: host->device transfer bytes on
    # "stage" intervals; zero for device-cache-resident windows). Feeds
    # QueryResourceUsage.bytes_staged (trace.py).
    nbytes: int = 0


@dataclass
class FragmentStats:
    """Stats for one materialized fragment."""

    ops: tuple = ()  # operator type names in chain order
    windows: int = 0
    rows_in: int = 0
    rows_out: int = 0
    # True = analyze mode: _block_if syncs the device after each stage so
    # timings attribute device work. False = always-on tracing: stamp
    # wall-clock boundaries only, never force a sync.
    sync: bool = True
    stages: dict = field(default_factory=dict)  # {stage: StageStat}
    # Staging runs on the prefetch thread concurrently with compute on
    # the query thread (pipeline.py), so stage accumulation is locked.
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def add(self, stage: str, seconds: float, rows: int = 0,
            nbytes: int = 0) -> None:
        with self._lock:
            s = self.stages.setdefault(stage, StageStat())
            s.seconds += seconds
            s.rows += int(rows)
            s.count += 1
            s.nbytes += int(nbytes)

    def timed(self, stage: str, rows: int = 0, nbytes: int = 0):
        return _Timer(self, stage, rows, nbytes)

    def to_dict(self) -> dict:
        # Snapshot under the lock: /debug/queryz renders IN-FLIGHT
        # queries, so add() on the query/prefetch threads can be
        # inserting stage keys while a scrape iterates.
        with self._lock:
            stages = {
                k: (v.seconds, v.rows, v.count, v.nbytes)
                for k, v in self.stages.items()
            }
        return {
            "ops": list(self.ops),
            "windows": self.windows,
            "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "stages": {
                k: {"seconds": round(s, 6), "rows": r, "count": c,
                    "bytes": b}
                for k, (s, r, c, b) in stages.items()
            },
        }


class _Timer:
    def __init__(self, stats: FragmentStats, stage: str, rows: int,
                 nbytes: int = 0):
        self.stats, self.stage, self.rows = stats, stage, rows
        self.nbytes = nbytes

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.stats.add(
            self.stage, time.perf_counter() - self.t0, self.rows,
            self.nbytes,
        )


@dataclass
class QueryStats:
    """All fragment stats for one plan execution."""

    fragments: list = field(default_factory=list)  # list[FragmentStats]
    total_seconds: float = 0.0
    sync: bool = True  # propagated to fragments; see FragmentStats.sync

    def new_fragment(self, ops) -> FragmentStats:
        fs = FragmentStats(
            ops=tuple(type(o).__name__ for o in ops), sync=self.sync
        )
        self.fragments.append(fs)
        return fs

    def to_dict(self) -> dict:
        # Per-fragment to_dict snapshots under each fragment's lock;
        # totals come from those snapshots (never raw racing dicts).
        frags = [f.to_dict() for f in self.fragments]
        totals: dict = {}
        for fd in frags:
            for k, v in fd["stages"].items():
                totals[k] = totals.get(k, 0.0) + v["seconds"]
        return {
            "total_seconds": round(self.total_seconds, 6),
            "stage_totals": {k: round(v, 6) for k, v in sorted(totals.items())},
            "fragments": frags,
        }
