"""Join routing + union: host N:1 / vectorized N:M / device kernel /
fused in-fragment lookup joins.

Reference parity: ``src/carnot/exec/equijoin_node.cc`` (build+probe hash
join) and ``union_node.cc`` (k-way ordered merge). The TPU redesign
routes by shape and backend instead of always hash-joining:

- small unique-key inner/left joins run a host dict join,
- large N:M joins run the sort-based device kernel (TPU) or a
  vectorized numpy sort+searchsorted join (CPU backend, where XLA sorts
  are the wrong tool),
- N:1 joins against a dense-domain build side fuse INTO the probe
  stream's fragment as device gathers (``try_fused_join``) so output
  rows never materialize host-side.
"""

from __future__ import annotations

import functools

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.dtypes import DataType
from ..types.strings import NULL_ID, StringDictionary
from .fragment import compile_fragment_cached as compile_fragment
from .plan import AggOp, JoinOp, LimitOp, LookupJoinOp, MapOp
from .stream import (
    QueryError,
    _chain_out_relation,
    _col,
    _Stream,
    _stream_col_stats,
)


def _key_tuples(hb: HostBatch, on, remaps):
    keys = []
    for c in on:
        ids = hb.cols[c][0]
        if c in remaps:
            # Null string ids (-1) must stay null, not wrap to the last entry.
            ids = np.where(
                ids >= 0, remaps[c][np.clip(ids, 0, None)], NULL_ID
            ).astype(ids.dtype)
        keys.append(ids)
    extra = [hb.cols[c][1] for c in on if len(hb.cols[c]) > 1]
    return list(zip(*(list(k) for k in (keys + extra)))) if keys else []


# Inputs smaller than this run the host dict join (when N:1 applies);
# larger inputs and right/outer/N:M joins go to the device kernel.
DEVICE_JOIN_MIN_ROWS = 1 << 15


def _join_dispatch(left: HostBatch, right: HostBatch, op: JoinOp,
                   engine=None) -> HostBatch:
    """Route a join to the host N:1 path or the device N:M kernel.

    Reference: ``equijoin_node.cc`` always hash-joins; here small unique-
    key inner/left joins (the post-agg common case) stay on host, and
    everything else uses ``pixie_tpu.ops.join.device_join``. ``engine``
    (when the call comes from a query) carries the pipeline depth and
    the per-query cancel handle into the windowed device driver.
    """
    if len(op.left_on) != len(op.right_on):
        raise QueryError("join key arity mismatch")
    small = left.length + right.length < DEVICE_JOIN_MIN_ROWS
    if op.how in ("inner", "left") and small:
        try:
            return _join_host(left, right, op)
        except _BuildNotUnique:
            pass  # N:M fan-out -> device kernel
    if left.length == 0 or right.length == 0:
        return _join_degenerate(left, right, op)
    import jax

    if op.how in ("inner", "left") and jax.default_backend() != "tpu":
        # XLA CPU sorts make the device kernel a regression there; the
        # vectorized numpy N:M join is the CPU-backend fast path.
        return _join_host_nm(left, right, op)
    return _join_device(left, right, op, engine)


class _BuildNotUnique(Exception):
    pass


def _align_join_dicts(left, right, op):
    """String-dictionary id remaps so key ids compare across sides.

    Returns (l_remap, r_remap, key_dicts): key_dicts maps a left key
    column to the merged dictionary (union preserves left ids, so pair
    rows stay valid and coalesced build-side ids land past them).
    """
    l_remap: dict = {}
    r_remap: dict = {}
    key_dicts: dict = {}
    for lc, rc in zip(op.left_on, op.right_on):
        ld, rd = left.dicts.get(lc), right.dicts.get(rc)
        if ld is not None and rd is not None and ld is not rd:
            merged, rl, rr = ld.union(rd)
            l_remap[lc], r_remap[rc] = rl, rr
            key_dicts[lc] = merged
    return l_remap, r_remap, key_dicts


def _join_out_schema(left, right, op):
    """(out_rel, ordered (side, src_col) pairs) for join output columns."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    src = [("l", c) for c in left.relation.column_names] + [
        ("r", c) for c in right.relation.column_names if c not in op.right_on
    ]
    return out_rel, src


def _join_degenerate(left, right, op: JoinOp) -> HostBatch:
    """Joins where one side is empty (device kernel needs real rows)."""
    out_rel, src = _join_out_schema(left, right, op)
    if op.how == "inner" or (op.how == "left" and left.length == 0) or (
        op.how == "right" and right.length == 0
    ):
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    elif op.how in ("left", "outer") and right.length == 0:
        keep_l, keep_r = np.arange(left.length), np.full(left.length, -1)
    elif op.how in ("right", "outer") and left.length == 0:
        keep_l, keep_r = np.full(right.length, -1), np.arange(right.length)
    else:  # outer with one side non-empty handled above; both empty:
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    _, r_remap, key_dicts = _align_join_dicts(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        keep_l, keep_l >= 0, keep_r, keep_r >= 0,
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _assemble_join(left, right, op, out_rel, src, l_idx, l_take, r_idx, r_take,
                   r_remap=None, key_dicts=None):
    """Gather output columns from per-row indices + take masks.

    Join key columns coalesce (SQL USING semantics): a right/outer extra
    row — whose probe side is null — takes its key from the build side,
    remapped into the merged dictionary for strings.
    """
    r_remap = r_remap or {}
    key_dicts = key_dicts or {}
    key_map = dict(zip(op.left_on, op.right_on))
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for side, c in src:
        n = next(names)
        hb = left if side == "l" else right
        idx = l_idx if side == "l" else r_idx
        take = l_take if side == "l" else r_take
        rc = key_map.get(c) if side == "l" else None
        nullv = NULL_ID if hb.relation.col_type(c) == DataType.STRING else 0
        planes = []
        for pi, p in enumerate(hb.cols[c]):
            if len(p) == 0:
                taken = np.full(len(idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(idx, 0, len(p) - 1)]
            if not take.all():
                if rc is not None:
                    q = right.cols[rc][pi]
                    if pi == 0 and rc in r_remap:
                        q = np.where(
                            q >= 0, r_remap[rc][np.clip(q, 0, None)], NULL_ID
                        ).astype(q.dtype)
                    alt = (
                        np.full(len(r_idx), nullv, dtype=p.dtype)
                        if len(q) == 0
                        else q[np.clip(r_idx, 0, len(q) - 1)]
                    )
                    taken = np.where(
                        take, taken, np.where(r_take, alt, nullv)
                    ).astype(p.dtype)
                else:
                    taken = np.where(take, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in hb.dicts:
            out_dicts[n] = (
                key_dicts.get(c, hb.dicts[c]) if side == "l" else hb.dicts[c]
            )
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _join_key_planes(hb, cols, remaps):
    planes = []
    for c in cols:
        for i, p in enumerate(hb.cols[c]):
            if i == 0 and c in remaps:
                p = np.where(
                    p >= 0, remaps[c][np.clip(p, 0, None)], NULL_ID
                ).astype(p.dtype)
            planes.append(p)
    return planes


@functools.lru_cache(maxsize=64)
def _device_join_cache(n_build, n_probe, dtypes, capacity, how):
    """One jitted kernel per (bucketed shapes, key dtypes, capacity, how)."""
    import jax

    from ..ops.join import device_join

    return jax.jit(
        lambda bk, bv, pk, pv: device_join(bk, bv, pk, pv, capacity, how)
    )


@functools.lru_cache(maxsize=64)
def _probe_sorted_cache(n_build_cap, n_probe_cap, capacity, how):
    """One jitted presorted-probe kernel per (bucketed shapes, capacity,
    how); the sorted build side and its row count are runtime args, so
    every probe window of a query (and across queries of the same
    shapes) reuses one program."""
    import jax

    from ..ops.join import probe_sorted_join

    return jax.jit(
        lambda sbk, rb, pk, pv: probe_sorted_join(sbk, rb, pk, pv, capacity, how)
    )


def _join_device_windowed(left: HostBatch, right: HostBatch, op: JoinOp,
                          window_rows: int, engine=None) -> HostBatch:
    """Multi-window device join driver (inner/left N:M).

    The build side is packed to comparable int64 key ids, sorted, and
    staged on device ONCE per query (the fused-join ``__side__``
    discipline: a query-constant table rides as a reused runtime arg,
    never re-``device_put`` per window). Probe windows then stream
    through the window-prefetch pipeline, so staging window N+1 overlaps
    the join kernel on window N. Output rows are bit-identical to the
    single-shot kernel's: windows emit in probe order, and matches
    within a probe row follow build order on both paths.
    """
    import jax

    from ..config import get_flag
    from .pipeline import WindowPipeline
    from .stream import _block_if, _timed

    # Under analyze, the join gets its own stage breakdown (stage /
    # compute / stall) like every other window consumer.
    qstats = getattr(engine, "_query_stats", None) if engine is not None \
        else None
    stats = qstats.new_fragment([op]) if qstats is not None else None

    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    lkeys, rkeys = _packed_key_ids(left, op.left_on, l_remap,
                                   right, op.right_on, r_remap)
    order = np.argsort(rkeys, kind="stable")
    rb = len(order)
    nb = bucket_capacity(rb)
    sentinel = np.iinfo(np.int64).max  # sorts past every real key
    sbk = np.full(nb, sentinel, dtype=np.int64)
    sbk[:rb] = rkeys[order]
    sbk_dev = jax.device_put(sbk)  # staged once; reused by every window
    rb_s = np.int32(rb)

    wcap = bucket_capacity(min(window_rows, left.length))

    def staged_probe_windows():
        for off in range(0, left.length, window_rows):
            m = min(window_rows, left.length - off)
            with _timed(stats, "stage", rows=m):
                pk = np.full(wcap, sentinel, dtype=np.int64)
                pk[:m] = lkeys[off:off + m]
                pv = np.zeros(wcap, dtype=bool)
                pv[:m] = True
                pk_dev, pv_dev = jax.device_put(pk), jax.device_put(pv)
                _block_if(stats, (pk_dev, pv_dev))
            if stats is not None:
                stats.rows_in += m
            yield off, pk_dev, pv_dev

    parts = []  # (l_idx, l_take, r_idx, r_take) per window
    depth = (
        engine.pipeline_depth if engine is not None
        else get_flag("pipeline_depth")
    )
    pipe = WindowPipeline(
        staged_probe_windows(), depth,
        cancel=getattr(engine, "_cancel", None), stats=stats,
    )
    # Capacity persists across windows: once one window's fan-out forces
    # a doubling, later windows start there instead of re-overflowing.
    capacity = bucket_capacity(max(2 * window_rows, 1))
    try:
        for off, pk_dev, pv_dev in pipe:
            with _timed(stats, "compute"):
                while True:
                    fn = _probe_sorted_cache(nb, wcap, capacity, op.how)
                    p_idx, p_take, b_idx, b_take, out_valid, overflow = (
                        np.asarray(a)
                        for a in fn(sbk_dev, rb_s, pk_dev, pv_dev)
                    )
                    if not bool(overflow):
                        break
                    capacity *= 2
            if stats is not None:
                stats.windows += 1
            sel = np.nonzero(out_valid)[0]
            parts.append((
                p_idx[sel].astype(np.int64) + off,
                p_take[sel],
                order[np.clip(b_idx[sel], 0, max(rb - 1, 0))],
                b_take[sel],
            ))
    finally:
        pipe.close()
        if engine is not None:
            engine._note_pipeline(pipe)

    def cat(i, dtype):
        if not parts:
            return np.zeros(0, dtype=dtype)
        return np.concatenate([p[i] for p in parts]).astype(dtype, copy=False)

    out_rel, src = _join_out_schema(left, right, op)
    out = _assemble_join(
        left, right, op, out_rel, src,
        cat(0, np.int64), cat(1, bool), cat(2, np.int64), cat(3, bool),
        r_remap=r_remap, key_dicts=key_dicts,
    )
    if stats is not None:
        stats.rows_out = out.length
    return out


def _join_device(left: HostBatch, right: HostBatch, op: JoinOp,
                 engine=None) -> HostBatch:
    """N:M device join: pad to bucketed capacities, run the sort-based
    kernel, re-run doubled on overflow, gather columns host-side."""
    from ..config import get_flag

    probe_window = get_flag("join_probe_window_rows")
    if (
        op.how in ("inner", "left")
        and probe_window > 0
        and left.length > probe_window
        and right.length > 0
    ):
        # Same key-dtype guard as the single-shot path below — the
        # packed-id densify would otherwise paper over a mismatch via
        # numpy promotion (int64 vs float64 collides above 2^53).
        for lc, rc in zip(op.left_on, op.right_on):
            for lp_, rp_ in zip(left.cols[lc], right.cols[rc]):
                if lp_.dtype != rp_.dtype:
                    raise QueryError(
                        f"join key dtype mismatch: {rp_.dtype} vs {lp_.dtype}"
                    )
        # Windowable joins with a big probe side: sorted build staged
        # once, probe windows pipelined (one dispatch per window).
        return _join_device_windowed(left, right, op, probe_window, engine)
    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    probe_planes = _join_key_planes(left, op.left_on, l_remap)
    build_planes = _join_key_planes(right, op.right_on, r_remap)
    for bp, pp in zip(build_planes, probe_planes):
        if bp.dtype != pp.dtype:
            raise QueryError(
                f"join key dtype mismatch: {bp.dtype} vs {pp.dtype}"
            )

    nb, np_ = bucket_capacity(right.length), bucket_capacity(left.length)

    def pad(p, cap):
        out = np.zeros(cap, dtype=p.dtype)
        out[: len(p)] = p
        return out

    bk = [pad(p, nb) for p in build_planes]
    pk = [pad(p, np_) for p in probe_planes]
    bv = np.zeros(nb, dtype=bool)
    bv[: right.length] = True
    pv = np.zeros(np_, dtype=bool)
    pv[: left.length] = True

    capacity = bucket_capacity(max(left.length + right.length, 1))
    while True:
        fn = _device_join_cache(
            nb, np_, tuple(str(p.dtype) for p in bk), capacity, op.how
        )
        p_idx, p_take, b_idx, b_take, out_valid, overflow = (
            np.asarray(a) for a in fn(bk, bv, pk, pv)
        )
        if not bool(overflow):
            break
        capacity *= 2

    sel = np.nonzero(out_valid)[0]
    out_rel, src = _join_out_schema(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        p_idx[sel], p_take[sel], b_idx[sel], b_take[sel],
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _join_host(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:1 equijoin on host (post-agg inputs are small).

    Reference: ``src/carnot/exec/equijoin_node.cc`` build+probe — here the
    build side must be unique on the key (raises _BuildNotUnique for the
    dispatcher to fall through to the device kernel).
    """
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)

    lk = _key_tuples(left, op.left_on, l_remap)
    rk = _key_tuples(right, op.right_on, r_remap)
    lookup: dict = {}
    for i, k in enumerate(rk):
        if k in lookup:
            raise _BuildNotUnique(op.right_on, k)
        lookup[k] = i

    match = np.fromiter((lookup.get(k, -1) for k in lk), dtype=np.int64, count=len(lk))
    if op.how == "inner":
        l_idx = np.nonzero(match >= 0)[0]
    elif op.how == "left":
        l_idx = np.arange(left.length)
    else:
        raise QueryError(f"unsupported join how={op.how!r}")
    r_idx = match[l_idx]
    return _assemble_join_host(left, right, op, l_idx, r_idx)


def _join_host_nm(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:M inner/left equijoin on host — the CPU-backend analog of the
    device kernel (XLA CPU sorts are too slow to route big joins through
    the device path there). The native O(n) build+probe hash join
    (native/hash_join.cc) carries the bulk; the vectorized numpy
    sort/searchsorted form is the no-toolchain fallback."""
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)
    lk = _packed_key_ids(left, op.left_on, l_remap,
                         right, op.right_on, r_remap)
    lkeys, rkeys = lk

    from ..native import hash_join_call

    if len(rkeys) and len(lkeys):
        native = hash_join_call(rkeys, lkeys, left_outer=(op.how == "left"))
        if native is not None:
            l_idx, r_idx = native
            return _assemble_join_host(
                left, right, op,
                l_idx.astype(np.int64), r_idx.astype(np.int64),
            )
    order = np.argsort(rkeys, kind="stable")
    span = 0
    if len(rkeys) and len(lkeys):
        kmin = min(int(rkeys.min()), int(lkeys.min()))
        kmax = max(int(rkeys.max()), int(lkeys.max()))
        span = kmax - kmin + 1
    if 0 < span <= 4 * (len(lkeys) + len(rkeys)):
        # Dense key range: bincount + cumsum offsets replace the two
        # binary searches (random-access searchsorted over millions of
        # probes is the profile's hot spot).
        kcounts = np.bincount(rkeys - kmin, minlength=span)
        key_starts = np.zeros(span + 1, dtype=np.int64)
        np.cumsum(kcounts, out=key_starts[1:])
        lo = key_starts[lkeys - kmin]
        counts = kcounts[lkeys - kmin]
        hi = lo + counts
    else:
        srk = rkeys[order]
        lo = np.searchsorted(srk, lkeys, side="left")
        hi = np.searchsorted(srk, lkeys, side="right")
        counts = hi - lo
    if op.how == "left":
        counts = np.maximum(counts, 1)  # unmatched keep one null row
        unmatched = (hi - lo) == 0
    total = int(counts.sum())
    starts = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    l_idx = np.repeat(np.arange(left.length, dtype=np.int64), counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(starts[:-1], counts)
    if len(rkeys):
        r_idx = order[
            np.clip(np.repeat(lo, counts) + within, 0, len(rkeys) - 1)
        ]
    else:
        r_idx = np.full(total, -1, dtype=np.int64)
    if op.how == "left" and len(rkeys):
        r_idx = np.where(np.repeat(unmatched, counts), -1, r_idx)
    return _assemble_join_host(left, right, op, l_idx, r_idx)


def _packed_key_ids(left, left_on, l_remap, right, right_on, r_remap):
    """Dense i64 key ids comparable across both sides (np.unique over the
    stacked key planes of the concatenated inputs)."""
    def planes(b, cols, remap):
        out = []
        for c in cols:
            for i, p in enumerate(b.cols[c]):
                q = p
                if i == 0 and c in remap:
                    q = remap[c][np.clip(p, 0, None)]
                    q = np.where(p >= 0, q, NULL_ID)
                out.append(np.asarray(q))
        return out
    lp = planes(left, left_on, l_remap)
    rp = planes(right, right_on, r_remap)
    if (
        len(lp) == 1
        and np.issubdtype(lp[0].dtype, np.integer)
        and np.issubdtype(rp[0].dtype, np.integer)
    ):
        # Single-plane INTEGER keys compare directly — no densification
        # pass (the int64 cast is equality-preserving, wrapping uints
        # bijectively). Floats must densify: casting would truncate
        # 1.2 and 1.7 onto the same key.
        return (lp[0].astype(np.int64, copy=False),
                rp[0].astype(np.int64, copy=False))
    # Exact densify: per-plane np.unique codes (lossless for ANY dtype —
    # a blanket int64 cast would truncate float keys), then one unique
    # over the code tuples for multi-plane keys.
    codes = []
    for a, b in zip(lp, rp):
        _, inv = np.unique(np.concatenate([a, b]), return_inverse=True)
        codes.append(inv.astype(np.int64).reshape(-1))
    if len(codes) == 1:
        inv = codes[0]
    else:
        _, inv = np.unique(
            np.stack(codes, axis=1), axis=0, return_inverse=True
        )
        inv = inv.astype(np.int64).reshape(-1)
    return inv[: left.length], inv[left.length:]


def _assemble_join_host(left, right, op, l_idx, r_idx) -> HostBatch:
    """Row assembly for the host N:1 / N:M paths (r_idx=-1 -> null)."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for c in left.relation.column_names:
        n = next(names)
        out_cols[n] = tuple(p[l_idx] for p in left.cols[c])
        if c in left.dicts:
            out_dicts[n] = left.dicts[c]
    for c in right.relation.column_names:
        if c in op.right_on:
            continue
        n = next(names)
        planes = []
        nullv = NULL_ID if right.relation.col_type(c) == DataType.STRING else 0
        for p in right.cols[c]:
            if len(p) == 0:  # empty build side: all-null fill
                taken = np.full(len(l_idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(r_idx, 0, None)]
                if op.how == "left":
                    taken = np.where(r_idx >= 0, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in right.dicts:
            out_dicts[n] = right.dicts[c]
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _union_host(mats) -> HostBatch:
    """Schema-aligned union with dictionary re-encoding.

    When the schema carries a ``time_`` column the result is merged in
    time order — the reference UnionNode's k-way ordered merge of
    cross-PEM streams (``src/carnot/exec/union_node.cc``); a stable sort
    over the concatenation is equivalent given each input is itself
    time-ordered, and stays a single vectorized pass.
    """
    first = mats[0]
    for m in mats[1:]:
        if tuple(m.relation.column_names) != tuple(first.relation.column_names):
            raise QueryError("union inputs must share a schema")
    out_cols: dict = {}
    out_dicts: dict = {}
    for c, dt in first.relation.items():
        if dt == DataType.STRING:
            merged = StringDictionary()
            planes = []
            for m in mats:
                d = m.dicts.get(c, StringDictionary())
                # union preserves existing ids (append-only), so earlier
                # planes stay valid as merged grows.
                merged, _, remap = merged.union(d)
                ids = m.cols[c][0]
                planes.append(
                    np.where(ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID).astype(
                        np.int32
                    )
                )
            out_cols[c] = (np.concatenate(planes),)
            out_dicts[c] = merged
        else:
            out_cols[c] = tuple(
                np.concatenate([m.cols[c][i] for m in mats])
                for i in range(len(first.cols[c]))
            )
    if first.relation.has_column("time_"):
        order = np.argsort(out_cols["time_"][0], kind="stable")
        if not np.array_equal(order, np.arange(len(order))):
            out_cols = {
                c: tuple(p[order] for p in ps) for c, ps in out_cols.items()
            }
    return HostBatch(
        relation=first.relation,
        cols=out_cols,
        length=sum(m.length for m in mats),
        dicts=out_dicts,
    )


# -- fused lookup join --------------------------------------------------------
def try_fused_join(engine, nid, node, results, consumers):
    """N:1 join as an in-fragment device lookup, or None to fall back.

    Reference contrast: ``equijoin_node.cc`` materializes output rows
    through a host hash map; here, when the build side resolves to a
    dense-domain table, the probe stream keeps flowing — each window
    gathers the build columns on device and the downstream
    Map/Filter/Agg fuse into the same XLA program (VERDICT r03 ask
    #2: output-row assembly never leaves the device).
    """
    from ..types.dtypes import device_dtypes

    op = node.op
    if not engine.fused_lookup_join:
        return None
    if op.how not in ("inner", "left") or len(op.left_on) != 1:
        return None
    left_id, right_id = node.inputs
    left_res = results[left_id]
    if not isinstance(left_res, _Stream) or consumers.get(left_id, 0) > 1:
        return None
    if any(isinstance(o, (AggOp, LimitOp)) for o in left_res.chain):
        return None
    lc, rc = op.left_on[0], op.right_on[0]
    bound = _chain_out_relation(left_res, engine.registry)
    if bound is None:
        return None
    left_rel, left_dicts = bound
    if not left_rel.has_column(lc):
        return None
    l_dt = left_rel.col_type(lc)
    if len(device_dtypes(l_dt)) != 1:
        return None

    right_res = results[right_id]
    if (
        isinstance(right_res, _Stream)
        and consumers.get(right_id, 0) <= 1
        and any(isinstance(o, AggOp) for o in right_res.chain)
    ):
        built = _dense_agg_build(engine, right_res, op, l_dt, left_dicts, lc, rc)
        if isinstance(built, tuple) and built[0] == "fallback":
            # The aggregate already executed; keep its rows for the
            # generic join path rather than re-folding the stream.
            results[right_id] = built[1]
            built = _host_table_build(
                built[1], op, l_dt, left_dicts, lc, rc
            )
    else:
        if not isinstance(right_res, HostBatch):
            return None
        built = _host_table_build(right_res, op, l_dt, left_dicts, lc, rc)
    if built is None:
        return None
    lo, dom, found, value_tables, right_rel = built

    # Output naming: all left columns keep their names; right value
    # columns (minus the key) merge with the join suffix — the same
    # schema ``_join_out_schema`` produces for the host paths.
    try:
        out_rel = left_rel.merge(
            right_rel.select(
                [c for c in right_rel.column_names if c not in op.right_on]
            ),
            suffix=op.suffix,
        )
    except Exception:
        return None
    value_srcs = [c for c in right_rel.column_names if c not in op.right_on]
    out_names = out_rel.column_names[len(left_rel.column_names):]

    out_cols = []
    side: dict = {}
    prefix = f"__lj{nid}"
    for src, out_name in zip(value_srcs, out_names):
        dt = right_rel.col_type(src)
        if dt == DataType.STRING:
            return None  # string values need mid-chain dict plumbing
        planes = value_tables[src]
        out_cols.append((out_name, dt, len(planes)))
        for j, p in enumerate(planes):
            side[f"{prefix}:{out_name}:{j}"] = p
    side[f"{prefix}:found"] = found

    lj = LookupJoinOp(
        key_col=lc, how=op.how, prefix=prefix, lo=int(lo), dom=int(dom),
        out_cols=tuple(out_cols),
    )
    st = left_res.extend(lj)
    st.side.update(side)
    return st


def _dense_agg_build(engine, right_stream, op, l_dt, left_dicts, lc, rc):
    """Build lookup tables straight from a dense aggregate's device
    state: the slot-aligned finalize output IS the table (slot =
    key - lo), so the build side never visits the host."""
    if any(isinstance(o, LimitOp) for o in right_stream.chain):
        return None
    frag_probe = compile_fragment(
        right_stream.chain, right_stream.relation, right_stream.dicts,
        engine.registry, col_stats=_stream_col_stats(right_stream),
    )
    if (
        not frag_probe.is_agg
        or len(frag_probe.dense_domains) != 1
        or frag_probe.dense_strides not in ((), (1,))
        or frag_probe.limit is not None
    ):
        # (strided domains step-index their slots; the LookupJoinOp
        # gather arithmetic assumes stride 1.)
        return None
    # The dense slot space must be the probe key's own code space.
    agg_i = next(
        i for i, o in enumerate(right_stream.chain)
        if isinstance(o, AggOp)
    )
    agg = right_stream.chain[agg_i]
    if tuple(agg.group_cols) != (rc,):
        return None
    # Post-agg ops must leave the key column untouched — the slot
    # arithmetic pairs probe keys with SLOT indices, so a post map
    # that rewrites the key would silently mispair every row.
    for o in right_stream.chain[agg_i + 1:]:
        if isinstance(o, MapOp):
            key_expr = dict(o.exprs).get(rc)
            if key_expr != _col(rc):
                return None
    out_rel = frag_probe.relation
    if rc not in out_rel.column_names:
        return None
    if out_rel.col_type(rc) != l_dt:
        return None
    if l_dt == DataType.STRING:
        meta = next(m for m in frag_probe.out_meta if m.name == rc)
        if left_dicts.get(lc) is not meta.dict:
            return None
    if any(m.struct_fields for m in frag_probe.out_meta):
        return None
    # Execute the PROBE's fragment, not a recompile: an append racing
    # between two compiles (stats crossing the stats quantization
    # grain) would give the run a different dense domain/offset than
    # the lo/dom captured below, silently mispairing every lookup.
    # With the same fragment, a racing append past the captured
    # domain surfaces as dr._overflow and takes the reject path.
    dr = engine._run_fragment(right_stream, frag=frag_probe)
    reject = bool(np.asarray(dr._overflow))  # stats raced an append
    value_tables = {
        n: tuple(dr._cols[n])
        for n in out_rel.column_names
        if n != rc and n in dr._cols
    }
    if set(value_tables) != {c for c in out_rel.column_names if c != rc}:
        reject = True
    if reject:
        # Don't discard the executed aggregate: hand the (rebucketed
        # if needed) rows back so the generic join path reuses them
        # instead of re-folding the whole right stream.
        return ("fallback", dr.to_host())
    return (
        frag_probe.dense_offsets[0], frag_probe.dense_domains[0],
        dr._valid, value_tables, out_rel,
    )


def _host_table_build(right_hb, op, l_dt, left_dicts, lc, rc):
    """Build dense lookup tables from a materialized unique-key host
    batch (the post-agg N:1 case arriving as rows)."""
    from ..config import get_flag

    if not right_hb.relation.has_column(rc):
        return None
    if right_hb.relation.col_type(rc) != l_dt:
        return None
    if right_hb.length == 0:
        return None
    kb = np.asarray(right_hb.cols[rc][0])
    if l_dt == DataType.STRING:
        ld = left_dicts.get(lc)
        rd = right_hb.dicts.get(rc)
        if ld is None or rd is None:
            return None
        if rd is not ld:
            # Re-express build keys in the probe's id space without
            # growing it: unseen keys can never match a probe row.
            remap = np.fromiter(
                (ld.lookup(s) for s in rd.strings),
                dtype=np.int64, count=len(rd),
            )
            kb = np.where(kb >= 0, remap[np.clip(kb, 0, None)], -1)
        lo, dom = 0, len(ld) + 1
        in_dom = kb >= 0
    elif l_dt in (DataType.INT64, DataType.TIME64NS):
        lo, hi = int(kb.min()), int(kb.max())
        dom = hi - lo + 1
        if dom > get_flag("int_dense_domain_limit"):
            return None
        in_dom = np.ones(len(kb), dtype=bool)
    else:
        return None
    idx = np.where(in_dom, kb - lo, 0)
    found = np.zeros(dom, dtype=bool)
    # Uniqueness: a duplicate build key means N:M — not this path.
    found[idx[in_dom]] = True
    if int(found.sum()) != int(in_dom.sum()):
        return None
    from ..types.dtypes import device_dtypes

    value_tables = {}
    for c in right_hb.relation.column_names:
        if c == rc:
            continue
        ddts = device_dtypes(right_hb.relation.col_type(c))
        planes = []
        for p, ddt in zip(right_hb.cols[c], ddts):
            # Device dtype, not host: FLOAT64 host planes are f64 but
            # the device-plane invariant is f32 — an f64 side table
            # would re-admit f64 into fused device code.
            p = np.asarray(p)
            t = np.zeros(dom, dtype=ddt)
            if len(p):
                t[idx[in_dom]] = p[in_dom]
            planes.append(t)
        value_tables[c] = tuple(planes)
    return lo, dom, found, value_tables, right_hb.relation
